"""Sender-side object push manager.

TPU-native analog of the reference's PushManager
(src/ray/object_manager/push_manager.h:29): owner/holder-initiated chunked
pushes with per-destination concurrency caps and pipelined chunk RPCs, plus
receiver-side admission control (the receiver can refuse a push session when
saturated — reference: pull_manager.h:52 admission control — and the sender
backs off and retries).

The round-1 transfer path was pull-only (a node fetched chunks on demand);
pushes make broadcast possible: the holder streams an object out without the
receiver asking, and `rpc_broadcast_object` (raylet.py) fans out over a
binomial tree so a 1 GiB broadcast to N nodes costs the root O(log N) object
sends instead of N.

PR 10 rebuilt the hot path in two ways:

- **Raw frames**: when the receiver's `push_begin` reply advertises
  ``raw_ok``, chunks go out as raw frames (rpc.py RAW_CHUNK) — header +
  payload memoryview straight from the arena, no msgpack encode of the
  multi-MiB ``bytes`` and no ``bytes(...)`` copy. Receivers that don't
  advertise (mixed-version peers, ``transfer_raw_frames=False``) get the
  msgpack chunks they always did.

- **Cut-through relay**: `push_begin` carries the receiver's relay subtree,
  and `stream_from_session` forwards chunks downstream AS THEY ARRIVE
  (watermark-paced, starting after the first chunk) instead of after the
  local copy seals — broadcast latency drops from O(depth × size) to
  O(size + depth × chunk). The receiver's `push_commit` response folds in
  its subtree's outcome, so failures still propagate to the root.
"""

from __future__ import annotations

import asyncio
import logging
import time

from ray_tpu._private import flight_recorder
from ray_tpu._private.config import get_config
from ray_tpu._private.rpc import RAW_CHUNK, ConnectionLost
from ray_tpu._private.transfer_stats import TRANSFER

logger = logging.getLogger(__name__)


def subtree_node_ids(child: dict, subtree: list) -> list[str]:
    """Every node id a failed push to `child` takes down with it."""
    return [child["node_id"]] + [t["node_id"] for t in subtree or []]


class PushManager:
    def __init__(self, raylet):
        cfg = get_config()
        self.raylet = raylet
        self.chunk = cfg.object_transfer_chunk_bytes
        self.pipeline_depth = cfg.push_pipeline_depth
        self.max_per_dest = cfg.push_max_concurrent_per_dest
        self.admission_retries = cfg.push_admission_retries
        self.raw_enabled = cfg.transfer_raw_frames
        self._dest_sems: dict[str, asyncio.Semaphore] = {}
        self._active: dict[tuple, asyncio.Future] = {}

    def stats(self) -> dict:
        return {"active_pushes": len(self._active)}

    async def push(
        self,
        object_id: str,
        node_id: str,
        address,
        relay_targets: list | None = None,
        timeout: float | None = None,
    ) -> dict:
        """Push a sealed local object to one destination node; when
        ``relay_targets`` is given the destination cut-through-relays the
        object onward to that subtree. Returns ``{"ok": bool, "failed":
        [node_ids]}`` covering the destination AND its subtree.

        Plain (no-subtree) pushes of the same object to the same node
        deduplicate; relayed pushes never do — two broadcasts may hand the
        same child different subtrees and each must deliver."""
        child = {"node_id": node_id, "address": address}
        key = (object_id, node_id)
        if not relay_targets:
            fut = self._active.get(key)
            if fut is not None:
                return await fut
            fut = asyncio.get_event_loop().create_future()
            self._active[key] = fut
        else:
            fut = None
        result = {"ok": False, "failed": subtree_node_ids(child, relay_targets)}
        try:
            result = await self._push_once(
                object_id, node_id, address, relay_targets or [], timeout
            )
        except Exception as e:
            logger.debug("push %s -> %s failed: %s", object_id[:8], node_id[:8], e)
        finally:
            # Resolve in the finally so deduplicated waiters are released even
            # if this task is CANCELLED (CancelledError skips `except
            # Exception`; an unresolved future would hang them forever).
            if fut is not None:
                self._active.pop(key, None)
                if not fut.done():
                    fut.set_result(result)
        return result

    async def _begin_session(
        self, peer, object_id: str, size: int, relay_targets: list, timeout
    ) -> dict | None:
        """Receiver admission loop; returns the accepting begin reply, a
        reply with ``already``, or None (refused after all retries).

        The loop owns ALL retrying (per-call ``retries=0``): acall's internal
        retry would multiply the caller's timeout by rpc_retries+1 behind the
        deadline check's back. Transient transport failures retry here like a
        refusal, capped at the rpc-layer's own budget."""
        req = {"object_id": object_id, "size": size}
        if relay_targets:
            req["relay_targets"] = relay_targets
        if timeout is not None:
            req["timeout"] = timeout
        deadline = None if timeout is None else time.monotonic() + timeout
        transport_failures = 0
        for attempt in range(self.admission_retries):
            per_call = None
            if deadline is not None:
                per_call = max(0.5, deadline - time.monotonic())
            try:
                begin = await peer.acall(
                    "push_begin", req, timeout=per_call, retries=0
                )
            except (ConnectionLost, asyncio.TimeoutError):
                transport_failures += 1
                if transport_failures > 3:
                    raise
                begin = {"retry_after": 0.2}
            if begin.get("already") or begin.get("accepted"):
                return begin
            delay = begin.get("retry_after", 0.1) * (1 + attempt * 0.2)
            if deadline is not None and time.monotonic() + delay >= deadline:
                return None
            await asyncio.sleep(delay)
        return None

    async def _run_session(
        self,
        peer,
        object_id: str,
        offset: int,
        size: int,
        relay_targets: list,
        timeout,
        all_failed: list,
        available=None,
        relay_child: dict | None = None,
    ) -> dict:
        """One complete push-session protocol run against `peer`: admission
        begin (already -> delegate a broadcast of the subtree to the holder),
        raw negotiation, chunk stream, commit with subtree-outcome folding,
        abort on error. Shared by direct pushes (``available=None``, offset
        of a pinned sealed object) and cut-through relays
        (``available``=watermark over the inbound session,
        ``relay_child``=the child this relay feeds)."""
        begin = await self._begin_session(peer, object_id, size, relay_targets, timeout)
        if begin is None:
            return {"ok": False, "failed": all_failed}
        if begin.get("already"):
            if not relay_targets:
                return {"ok": True, "failed": []}
            # The peer already holds a sealed copy, so no push session (and
            # no cut-through relay) exists there: ask it to fan its copy out
            # to the subtree instead.
            resp = await peer.acall(
                "broadcast_object",
                {"object_id": object_id, "targets": relay_targets,
                 "timeout": timeout},
                timeout=timeout,
            )
            return {"ok": bool(resp.get("ok")),
                    "failed": list(resp.get("failed") or [])}
        raw = bool(begin.get("raw_ok")) and self.raw_enabled
        if relay_child is not None:
            # Recorded BEFORE the stream: the whole point is that forwarding
            # starts while the local copy is still arriving.
            flight_recorder.record(
                "transfer_relay", f"{object_id[:12]}:{relay_child['node_id'][:8]}"
            )
        try:
            # The chunk stream honors the session timeout too: a receiver
            # whose process wedges with the TCP connection still alive never
            # acks and never raises ConnectionLost — without this bound the
            # push (and the broadcast above it) would hang forever.
            stream = self._stream_chunks(
                peer, object_id, offset, size, raw, available=available
            )
            if timeout is not None:
                await asyncio.wait_for(stream, timeout)
            else:
                await stream
            # retries=1 (not the default 3): the receiver remembers the
            # commit outcome (raylet._commit_results), so ONE retry after a
            # timeout/connection blip recovers the true subtree verdict
            # without multiplying the caller's timeout budget further.
            resp = await peer.acall(
                "push_commit", {"object_id": object_id}, timeout=timeout, retries=1
            )
            ok = bool(resp.get("ok"))
            if ok:
                if relay_child is not None:
                    TRANSFER.relays += 1
                else:
                    TRANSFER.pushes += 1
                    flight_recorder.record(
                        "transfer_push",
                        f"{object_id[:12]}:{size}:{'raw' if raw else 'msgpack'}",
                    )
            # The peer sealed iff commit replied at all; a non-ok commit
            # names the subtree nodes its relays missed.
            return {"ok": ok,
                    "failed": list(resp.get("failed") or ([] if ok else all_failed))}
        except BaseException:
            try:
                await peer.acall("push_abort", {"object_id": object_id})
            except Exception:
                pass
            raise

    async def _push_once(
        self, object_id: str, node_id: str, address, relay_targets: list, timeout
    ) -> dict:
        child = {"node_id": node_id, "address": address}
        all_failed = subtree_node_ids(child, relay_targets)
        sem = self._dest_sems.setdefault(node_id, asyncio.Semaphore(self.max_per_dest))
        async with sem:
            peer = self.raylet._peer(node_id, address)
            offset, size = await self.raylet.store.get(object_id)  # pins the object
            try:
                return await self._run_session(
                    peer, object_id, offset, size, relay_targets, timeout, all_failed
                )
            finally:
                self.raylet.store.release(object_id)

    async def _stream_chunks(
        self, peer, object_id: str, offset: int, size: int, raw: bool,
        available=None,
    ):
        """Pipelined chunk stream: up to pipeline_depth chunk sends in flight
        (reference paces by chunks in flight too). ``available`` is an async
        callable(pos) -> contiguous-bytes-ready used by cut-through relays
        (None = the whole object is sealed and readable)."""
        inflight = asyncio.Semaphore(self.pipeline_depth)
        tasks: list[asyncio.Future] = []

        async def send(start: int, length: int):
            try:
                view = self.raylet.arena.read(offset + start, length)
                if raw:
                    fut = await peer.astart_raw(RAW_CHUNK, object_id, start, view)
                    TRANSFER.chunks_raw_out += 1
                else:
                    fut = await peer.astart_call(
                        "push_chunk",
                        {"object_id": object_id, "start": start,
                         "data": bytes(view)},
                    )
                    TRANSFER.chunks_msgpack_out += 1
                resp = await fut
                if not resp.get("ok"):
                    raise RuntimeError(
                        f"push_chunk {object_id[:8]}@{start} refused: "
                        f"{resp.get('error', 'session lost')}"
                    )
                TRANSFER.bytes_out += length
            finally:
                inflight.release()

        try:
            pos = 0
            while pos < size:
                if available is not None:
                    avail = await available(pos)
                else:
                    avail = size
                length = min(self.chunk, avail - pos)
                await inflight.acquire()
                # Fail the stream as soon as any in-flight chunk failed
                # rather than queuing the rest behind a dead session.
                for t in tasks:
                    if t.done() and t.exception() is not None:
                        inflight.release()
                        raise t.exception()
                tasks.append(asyncio.ensure_future(send(pos, length)))
                pos += length
            await asyncio.gather(*tasks)
        except BaseException:
            for t in tasks:
                t.cancel()
            # Reap cancellations so nothing leaks into the loop's exception
            # handler after we re-raise.
            await asyncio.gather(*tasks, return_exceptions=True)
            raise

    async def stream_from_session(
        self, sess: dict, object_id: str, child: dict, subtree: list, timeout
    ) -> dict:
        """Cut-through relay: forward an INBOUND push session's bytes to one
        child (with its own subtree) as they arrive, watermark-paced. Runs on
        the receiver; started by rpc_push_begin, awaited by rpc_push_commit.
        Returns {"ok", "failed"} like push()."""

        async def available(pos: int) -> int:
            while True:
                if sess.get("aborted"):
                    raise RuntimeError("inbound push session aborted")
                if sess["contig"] > pos:
                    return sess["contig"]
                ev = sess["event"]
                ev.clear()
                # Single-threaded loop: contig cannot advance between the
                # check above and this wait, so the set cannot be lost.
                await ev.wait()

        all_failed = subtree_node_ids(child, subtree)
        peer = self.raylet._peer(child["node_id"], child["address"])
        try:
            return await self._run_session(
                peer,
                object_id,
                sess["offset"],
                sess["size"],
                subtree,
                timeout,
                all_failed,
                available=available,
                relay_child=child,
            )
        except Exception as e:
            logger.debug(
                "relay %s -> %s failed: %s", object_id[:8], child["node_id"][:8], e
            )
            return {"ok": False, "failed": all_failed}
