"""Raylet — per-node daemon.

TPU-native analog of the reference's raylet process (src/ray/raylet/main.cc:109,
NodeManager node_manager.h:117): hosts

- the node's shared-memory object store daemon (StoreCore; reference runs
  plasma inside the raylet too, plasma/store_runner.h)
- the worker pool: spawns/pools Python worker processes
  (worker_pool.cc:426 StartWorkerProcess, :1150 PopWorker)
- the two-level scheduler: cluster-level placement with spillback to other
  raylets (cluster_task_manager.h:42) and local dispatch to leased workers
  (local_task_manager.h:58), with placement-group bundle accounting
  (placement_group_resource_manager.h)
- chunked node-to-node object transfer (object_manager.h:117, pull_manager.h:52)
- heartbeat/resource sync with GCS (ray_syncer.h:86) and worker-failure
  reporting.

TPU chips are first-class resources here: a node's resource set is
{"CPU": n, "TPU": m, "memory": bytes, ...custom}, with slice topology carried
in node labels (e.g. {"tpu_slice": "v5e-8", "ici_group": "..."}) so placement
groups can gang-schedule onto ICI domains.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import subprocess
import sys
import time
from collections import deque
from dataclasses import dataclass, field

from ray_tpu._private import flight_recorder, self_metrics
from ray_tpu._private.concurrency import loop_only
from ray_tpu._private.config import get_config
from ray_tpu._private.ids import BoundedIdSet, NodeID, WorkerID
from ray_tpu._private.rpc import (
    RAW_CHUNK,
    EventLoopThread,
    RawFrame,
    RawResult,
    RpcClient,
    RpcServer,
    addr_key,
    schema,
)
from ray_tpu._private.transfer_stats import TRANSFER
from ray_tpu._private.store.arena import create_arena
from ray_tpu._private.store.object_store import StoreCore
from ray_tpu._private.task_spec import TaskSpec

logger = logging.getLogger(__name__)

def _binomial_split(targets: list) -> list[tuple[dict, list]]:
    """Binomial-tree fan-out: peel a child off the front, hand it half the
    remainder as its subtree, repeat — the root contacts O(log N) children
    directly and every child does the same with its share."""
    splits = []
    rest = list(targets)
    while rest:
        child, rest = rest[0], rest[1:]
        subtree, rest = rest[: len(rest) // 2], rest[len(rest) // 2 :]
        splits.append((child, subtree))
    return splits


def rejoin_backoff_delay(attempt: int, cfg, rng) -> float:
    """Jittered exponential backoff before a re-register: full jitter over
    [0, min(max, base * 2^attempt)] — a GCS restart or mass partition-heal
    otherwise makes every raylet re-register in the same heartbeat interval
    (thundering herd on the register/republish fan-in)."""
    ceiling = min(cfg.rejoin_backoff_max_s, cfg.rejoin_backoff_base_s * (2 ** attempt))
    return rng.uniform(0, ceiling)


class OptimisticDebitLedger:
    """Self-healing bookkeeping for forward-time mirror debits.

    Spilling a task to a peer debits the peer's MIRRORED availability
    immediately, so a burst of picks spreads over fits-now peers instead of
    dogpiling the first one. Under the legacy full-view heartbeat the debit
    was provisional by construction — every reply overwrote the whole
    mirror. Delta sync ships only CHANGED rows, which opens a leak: when the
    peer acquires and releases entirely between its own heartbeats, its GCS
    row never changes, no delta ever arrives, and the debit sticks forever —
    the forwarder permanently under-estimates that peer (and locality
    preference starts refusing a perfectly idle holder).

    So every debit carries a deadline (a couple of heartbeat intervals): an
    authoritative row for the node clears its debits (the upsert already
    overwrote the mirror), and a debit that outlives its deadline is
    credited back. sched_core.release clamps at capacity and ignores
    unknown nodes, so a late credit after a real delta or a tombstone is
    harmless."""

    def __init__(self):
        self._pending: list[tuple[float, str, dict]] = []

    def note(self, node_id: str, resources: dict, interval_s: float):
        self._pending.append(
            (time.monotonic() + 2.5 * max(interval_s, 0.05), node_id, dict(resources))
        )

    def on_authoritative_rows(self, node_ids) -> None:
        """Rows in a heartbeat reply (changed or tombstoned) supersede any
        pending debit for those nodes."""
        if self._pending and node_ids:
            ids = set(node_ids)
            self._pending = [p for p in self._pending if p[1] not in ids]

    def expire(self, sched) -> None:
        """Credit back debits never confirmed by an authoritative row."""
        if not self._pending:
            return
        now = time.monotonic()
        due = [p for p in self._pending if p[0] <= now]
        if due:
            self._pending = [p for p in self._pending if p[0] > now]
            for _, nid, res in due:
                sched.release(nid, res)


def apply_heartbeat_view(resp: dict, node) -> None:
    """Fold a heartbeat reply's cluster view into ``node`` (a Raylet or a
    SimNode shell: anything with ``cluster_view``/``_view_version``/
    ``_sched``/``node_id``/``_synced_peers``).

    Three reply shapes: legacy full view under ``"nodes"``, delta-sync full
    resync (``view_full``), and a delta (changed rows + removal tombstones).
    Peers are mirrored into the local sched_core ledger — NEVER self: the
    local ledger is authoritative, and a stale heartbeat echo (a delta row
    for this node carrying pre-acquire availability) must not clobber
    in-flight acquires."""
    if "view" in resp:
        node._view_version = resp.get("view_version", 0)
        removed = resp.get("view_removed", ())
        if resp.get("view_full"):
            node.cluster_view = dict(resp["view"])
        else:
            for nid in removed:
                node.cluster_view.pop(nid, None)
            node.cluster_view.update(resp["view"])
        changed = resp["view"]
    elif "nodes" in resp:
        node.cluster_view = resp.get("nodes", {})
        changed = node.cluster_view
        removed = ()
    else:
        return
    for nid in changed:
        if nid == node.node_id:
            continue
        row = node.cluster_view.get(nid)
        if row is not None:
            node._sched.node_upsert(
                nid,
                row.get("resources_total", {}),
                row.get("resources_available", {}),
            )
    gone = node._synced_peers - set(node.cluster_view)
    for nid in gone:
        if nid != node.node_id:
            node._sched.node_remove(nid)
    node._synced_peers = set(node.cluster_view)
    debits = getattr(node, "_opt_debits", None)
    if debits is not None:
        debits.on_authoritative_rows(set(changed) | set(removed) | gone)


class ArgLocalityCache:
    """oid -> holder node ids for locality-aware placement, bounded + TTL.

    Reference args (``("r", oid, owner)``) are by construction plasma-sized
    — anything under ``max_direct_call_object_size`` ships inline — so the
    inline/reference split IS the large-arg threshold the Ray paper's
    data-locality policy keys on. Shared by Raylet and SimNode shells."""

    _MAX_ENTRIES = 4096

    def __init__(self, gcs: RpcClient, cfg):
        self.gcs = gcs
        self.cfg = cfg
        self._cache: dict[str, tuple[float, tuple]] = {}

    async def holders(self, spec: TaskSpec) -> dict[str, int]:
        """node_id -> how many of the task's reference args it holds."""
        oids = [
            a[1]
            for a in spec.args
            if isinstance(a, (list, tuple)) and len(a) >= 2 and a[0] == "r"
        ][: self.cfg.locality_max_args]
        if not oids:
            return {}
        now = time.monotonic()
        counts: dict[str, int] = {}
        missing = []
        for oid in oids:
            hit = self._cache.get(oid)
            if hit is not None and now - hit[0] < self.cfg.locality_cache_ttl_s:
                for nid in hit[1]:
                    counts[nid] = counts.get(nid, 0) + 1
            else:
                missing.append(oid)
        if missing:
            results = await asyncio.gather(
                *[
                    self.gcs.acall(
                        "get_object_locations",
                        {"object_id": oid},
                        timeout=2,
                        retries=0,
                    )
                    for oid in missing
                ],
                return_exceptions=True,
            )
            if len(self._cache) >= self._MAX_ENTRIES:
                # Bounded: evict the oldest-inserted half wholesale.
                for k in list(self._cache)[: self._MAX_ENTRIES // 2]:
                    self._cache.pop(k, None)
            for oid, resp in zip(missing, results):
                if isinstance(resp, BaseException):
                    continue  # lookup failure: schedule without this arg's hint
                nids = tuple(loc["node_id"] for loc in resp.get("locations", []))
                self._cache[oid] = (now, nids)
                for nid in nids:
                    counts[nid] = counts.get(nid, 0) + 1
        return counts


def _runtime_env_hash(runtime_env: dict | None) -> str | None:
    """Canonical hash for worker<->task runtime-env matching."""
    if not runtime_env:
        return None
    import hashlib

    return hashlib.md5(json.dumps(runtime_env, sort_keys=True).encode()).hexdigest()[:16]


def _worker_key(runtime_env: dict | None, language: str = "py") -> str | None:
    """Worker-pool matching key: runtime env PLUS execution language
    (reference: worker_pool.cc keys cached workers per (language,
    runtime-env hash)). language="cpp" workers are the native runtime
    (cpp/ray_tpu_worker.cc) and never serve Python tasks, and vice versa."""
    h = _runtime_env_hash(runtime_env)
    return h if language == "py" else f"lang={language}|{h}"


@dataclass
class WorkerHandle:
    worker_id: str
    pid: int
    address: tuple | None = None
    client: RpcClient | None = None
    proc: subprocess.Popen | None = None
    state: str = "starting"  # starting | idle | busy | actor | dead
    # Workers are dedicated to one runtime env (reference: worker_pool.cc
    # caches workers per runtime-env hash); None = plain environment.
    runtime_env_hash: str | None = None
    current_task: TaskSpec | None = None
    # Creation spec of the actor living in this worker; actors hold their
    # resources for life, so these are released only on worker death.
    actor_spec: TaskSpec | None = None
    actor_id: str | None = None
    last_idle: float = field(default_factory=time.monotonic)
    # Log-pipeline attribution (reference: LogMonitor tags lines by job).
    last_job_id: str | None = None
    last_task_name: str | None = None
    # Set when the memory monitor killed this worker (OOM error surfacing).
    oom_killed: bool = False
    # When the current task was dispatched (OOM victim policy: newest first).
    dispatch_ts: float = 0.0


class Raylet:
    def __init__(
        self,
        gcs_address,
        session_dir: str,
        resources: dict | None = None,
        labels: dict | None = None,
        node_ip: str = "127.0.0.1",
        object_store_memory: int | None = None,
        exit_on_dead: bool = False,
    ):
        self.cfg = get_config()
        # When the GCS declares this node dead (a partition outlived the
        # death timeout, say): standalone raylet processes fail fast and
        # exit (the reference's suicide-on-dead, main() passes True); an
        # IN-PROCESS raylet must instead REJOIN — os._exit here would kill
        # the host process, driver and sibling nodes included.
        self._exit_on_dead = exit_on_dead
        from ray_tpu._private import chaos

        chaos.maybe_install_from_env()
        self.node_id = NodeID.from_random().hex()
        self.session_dir = session_dir
        self.node_ip = node_ip
        os.makedirs(session_dir, exist_ok=True)
        # Always-on observability: crash-surviving event ring + ray_tpu_*
        # runtime instruments (store gauges feed from the heartbeat loop).
        flight_recorder.attach(session_dir, role="raylet", ident=self.node_id)
        self._metrics = self_metrics.instruments()

        self.arena_name = f"/rtpu_{self.node_id[:12]}"
        capacity = object_store_memory or self.cfg.object_store_memory
        self.arena = create_arena(self.arena_name, capacity)
        from ray_tpu._private.store.index import create_index

        # Native object index: local-get fast path for every client process
        # on this node (skipped automatically if the native build failed).
        self.object_index = create_index(self.arena_name + "_idx")
        spill_dir = self.cfg.object_spill_dir or os.path.join(session_dir, "spill", self.node_id[:8])
        self.store = StoreCore(self.arena, spill_dir, index=self.object_index)

        self.resources_total = dict(resources or {"CPU": os.cpu_count() or 1})
        self.resources_total.setdefault("memory", 4 * 1024 * 1024 * 1024)
        # Resource accounting lives in the native scheduler core (C++
        # fixed-point ledger, _native/sched_core.cc — the reference keeps
        # this math in src/ray/raylet/scheduling/); resources_available is a
        # derived property over it.
        from ray_tpu._private.sched_core import create_sched_core

        self._sched = create_sched_core()
        self._sched.node_upsert(self.node_id, self.resources_total, self.resources_total)
        self._res_keys: set[str] = set(self.resources_total)
        # Placement-group bundle CAPACITIES (metadata/view); live availability
        # is the core's pool state.
        self.bundles: dict[tuple, dict] = {}
        self.bundle_reserved: dict[tuple, dict] = {}
        self.labels = dict(labels or {})

        self.workers: dict[str, WorkerHandle] = {}
        # Worker ids abandoned after a zygote spawn fallback (the fork may
        # have produced an orphan that registers late) — registration under
        # these is refused and the orphan reaped.
        self._retired_worker_ids: set[str] = set()
        self.task_queue: deque[TaskSpec] = deque()
        # Specs currently being forwarded to a peer (out of the queue, the
        # forward RPC in flight): visible to rpc_locate_tasks so the owner's
        # lost-task sweep never mistakes a mid-spillback task for lost.
        self._forwarding: set[str] = set()
        # Tasks whose resources/pool/placement can't currently be satisfied
        # park here instead of rotating through task_queue (reference keeps a
        # separate infeasible queue too, cluster_task_manager.h). They are
        # spliced back whenever capacity or the cluster view changes.
        self._infeasible: deque[TaskSpec] = deque()
        # Cancelled-before-arrival tombstones (cancel racing a spillback or
        # an in-flight submit): matching specs are dropped at dispatch.
        self._cancelled_tasks = BoundedIdSet()
        self._last_progress = time.monotonic()
        self.cluster_view: dict = {}
        # Last cluster-view generation applied (delta heartbeat sync); 0
        # forces a full view on the first heartbeat.
        self._view_version = 0
        self._synced_peers: set[str] = set()
        self._peer_clients: dict[str, RpcClient] = {}
        # Rejoin thundering-herd damping: per-node seeded jitter so a fleet
        # rediscovering a restarted GCS staggers deterministically.
        import random

        self._rejoin_rng = random.Random(self.node_id)
        self._rejoin_attempts = 0
        self._inbound_pushes: dict[str, dict] = {}  # object_id -> push session
        # Commit outcomes, remembered briefly (see rpc_push_commit): a
        # sender retrying a timed-out/blipped commit must observe the REAL
        # subtree verdict, not a contains() guess that drops relay failures.
        self._commit_results: dict[str, asyncio.Future] = {}
        # Advertised in push_begin replies and honored for fetch responses;
        # flip off (config transfer_raw_frames / per-instance in tests) to
        # force the msgpack fallback on every session through this node.
        self.raw_frames_enabled = self.cfg.transfer_raw_frames
        from ray_tpu._private.push_manager import PushManager

        self.push_manager = PushManager(self)
        from ray_tpu._private.pull_manager import PullManager

        self.pull_manager = PullManager(self)

        self.server = RpcServer(f"raylet-{self.node_id[:8]}")
        self.server.register_all(self)
        self.server.set_raw_handler(self._on_raw_frame)
        self.server.start(node_ip, 0)
        self.address = self.server.address
        # Chaos endpoint identity: this node's address key, stamped on the
        # server and on every client this raylet owns, so a membrane
        # partition can sever the NODE's links while its node-local ones
        # (raylet <-> own workers) stay up.
        self._addr_key = addr_key(self.address)
        self.server.chaos_scope = self._addr_key

        self.gcs = RpcClient(tuple(gcs_address) if isinstance(gcs_address, (list, tuple)) else gcs_address, label="gcs")
        self.gcs.chaos_scope = self._addr_key
        # Locality-aware scheduling: bounded TTL cache of oid -> holder node
        # ids (one GCS location lookup per arg per TTL window).
        self._arg_locality = ArgLocalityCache(self.gcs, self.cfg)
        self._opt_debits = OptimisticDebitLedger()
        self._io = EventLoopThread.get()
        self._io.run(self._register())
        self._hb_task = self._io.spawn(self._heartbeat_loop())
        self._reap_task = self._io.spawn(self._reap_loop())
        from ray_tpu._private.log_monitor import LogMonitor

        self._log_monitor_task = self._io.spawn(LogMonitor(self).run())
        from ray_tpu._private.memory_monitor import MemoryMonitor

        self._memory_monitor = MemoryMonitor(self)
        from ray_tpu.dashboard.agent import NodeStatsAgent

        # Per-node stats reporter (reference runs dashboard/agent.py as its
        # own process per node; here it shares the raylet's IO loop by
        # default and is also runnable standalone — see dashboard/agent.py).
        self._stats_agent_task = self._io.spawn(NodeStatsAgent(self).run())
        self._last_memory_check = 0.0
        self._tracing_enabled = False
        self._stopped = False
        # Direct task transport (reference: direct_task_transport.cc): lease
        # requests awaiting a worker grant, granted leases by id, and
        # owner-reported backlog per (owner, shape) for autoscaler demand.
        self._lease_futures: dict[str, asyncio.Future] = {}
        self._leases: dict[str, dict] = {}
        self._lease_demand: dict[tuple, tuple] = {}

    async def _register(self):
        await self.gcs.acall(
            "register_node",
            {
                "node_id": self.node_id,
                "address": list(self.address),
                "resources": self.resources_total,
                "labels": self.labels,
                "arena_name": self.arena_name,
            },
        )

    def _update_store_gauges(self):
        """Arena gauges piggyback on the heartbeat cadence (0.5s): O(1)
        reads, no extra loop."""
        usage = self.store.usage()
        try:
            self._metrics["store_bytes"].set(usage["used"])
            self._metrics["store_capacity"].set(usage["capacity"])
            self._metrics["store_objects"].set(usage["num_objects"])
        except Exception:
            pass
        return usage

    async def _heartbeat_loop(self):
        while True:
            try:
                hb = {
                    "node_id": self.node_id,
                    "resources_available": self.resources_available,
                    "store_usage": self._update_store_gauges(),
                    # Resource demand by shape (reference: resource load
                    # reporting in ray_syncer / autoscaler demand input).
                    "load": self._pending_load(),
                    # Occupancy: actors may hold zero resources, so the
                    # autoscaler must not treat resource-idle as idle.
                    "num_active_workers": sum(
                        1
                        for w in self.workers.values()
                        if w.state in ("busy", "actor")
                    ),
                }
                if self.cfg.heartbeat_delta_sync:
                    # Versioned delta sync: carry the last view generation
                    # seen; the reply holds only newer rows + tombstones
                    # (full view only on resync) instead of the O(N) full
                    # view every interval.
                    hb["view_version"] = self._view_version
                resp = await self.gcs.acall("heartbeat", hb)
                if resp.get("dead"):
                    if self._exit_on_dead:
                        logger.error("raylet %s: GCS declared us dead; exiting", self.node_id[:8])
                        os._exit(1)
                    # In-process node (tests, partition chaos): the GCS
                    # outlived a partition/stall and wrote us off. Rejoin:
                    # re-register under the same node id and republish our
                    # sealed objects (the GCS dropped our location rows at
                    # death). Actors the GCS declared dead STAY dead — the
                    # reference's node-death semantics — but the node's
                    # capacity and store contents come back.
                    logger.warning(
                        "raylet %s: GCS declared us dead; rejoining", self.node_id[:8]
                    )
                    await self._rejoin()
                    continue
                if resp.get("unknown"):
                    # GCS restarted and lost its node table: re-register and
                    # republish our sealed objects' locations.
                    logger.warning("raylet %s: GCS restarted; re-registering", self.node_id[:8])
                    await self._rejoin()
                    continue
                apply_heartbeat_view(resp, self)
                self._opt_debits.expire(self._sched)
                self._rejoin_attempts = 0  # healthy contact resets backoff
                self._tracing_enabled = bool(resp.get("tracing"))
                self._requeue_infeasible()  # cluster view refreshed
                await self._retry_pg_tasks()
                if self.task_queue:
                    await self._dispatch()  # periodic re-check (anti-starvation)
            except Exception:
                pass
            await asyncio.sleep(self.cfg.heartbeat_interval_s)

    async def _rejoin(self):
        """Re-register with the GCS (restart recovery and post-partition
        rejoin share this) and republish every sealed object's location.
        Backs off with full jitter first: every raylet discovers a GCS
        restart in the SAME heartbeat interval, and an unstaggered storm of
        register + location-republish RPCs is exactly the fan-in spike a
        freshly restarted GCS cannot afford."""
        delay = rejoin_backoff_delay(self._rejoin_attempts, self.cfg, self._rejoin_rng)
        self._rejoin_attempts += 1
        if delay > 0:
            await asyncio.sleep(delay)
        await self._register()
        for oid in self.store.object_ids():
            try:
                await self.gcs.acall(
                    "add_object_location",
                    {"object_id": oid, "node_id": self.node_id},
                )
            except Exception:
                pass

    def _pending_load(self) -> list:
        """Aggregate queued task resource shapes for the autoscaler. Parked
        infeasible tasks are the demand that matters most (they're what new
        nodes would satisfy). The scan is EXACT — a head-only sample would
        hide resource shapes concentrated in the queue tail and starve them
        of autoscaling — but cached: at most one full walk per 5s, except
        that a queue-depth change (e.g. a freshly-parked infeasible shape)
        invalidates immediately so the autoscaler never acts on stale
        demand."""
        cached = getattr(self, "_load_cache", None)
        now = time.monotonic()
        depth = (len(self._infeasible), len(self.task_queue))
        if cached is not None and now - cached[0] < 5.0 and cached[2] == depth:
            return cached[1]
        shapes: dict[tuple, int] = {}
        for spec in list(self._infeasible) + list(self.task_queue):
            key = tuple(sorted(spec.resources.items()))
            shapes[key] = shapes.get(key, 0) + 1
        # Owner-side lease backlogs (fresh ones only): under the direct task
        # transport the deep queue lives in the owner, not here.
        for (owner, key), (count, ts) in list(self._lease_demand.items()):
            if now - ts > 30.0:
                self._lease_demand.pop((owner, key), None)
            elif count > 0:
                shapes[key] = shapes.get(key, 0) + count
        load = [{"resources": dict(k), "count": c} for k, c in shapes.items()]
        self._load_cache = (now, load, depth)
        return load

    async def _retry_pg_tasks(self):
        """Re-route queued tasks that cannot run on this node: PG tasks whose
        bundle lives elsewhere, locally-infeasible tasks awaiting spillback
        (the cluster view may have been empty at submit), and strict
        node-affinity tasks targeting another node."""
        stuck = [s for s in self.task_queue if self._must_reroute(s)]
        for spec in stuck:
            self.task_queue.remove(spec)
            self._forwarding.add(spec.task_id)
            try:
                await self._queue_and_schedule(spec)
            finally:
                self._forwarding.discard(spec.task_id)

    def _must_reroute(self, spec: TaskSpec) -> bool:
        if spec.placement_group_id:
            return not self._has_pool(spec)
        strategy = spec.scheduling_strategy or "DEFAULT"
        if strategy.startswith("node:"):
            parts = strategy.split(":")
            return parts[1] != self.node_id and not (len(parts) > 2 and parts[2] == "soft")
        feasible_here = all(
            self.resources_total.get(k, 0) >= v for k, v in spec.resources.items()
        )
        return not feasible_here

    # ------------------------------------------------------------------
    # Store RPC surface (clients on this node)
    # ------------------------------------------------------------------

    async def rpc_store_create(self, req):
        object_id = req["object_id"]
        entry = self.store.objects.get(object_id)
        if entry is not None:
            # Sealed -> idempotent no-op. Unsealed -> an in-flight pull/push
            # session owns the buffer; the producer must wait for its
            # seal-or-abort rather than co-write a buffer that can be freed
            # under it (the session's abort would pop the entry and make the
            # producer's seal fail).
            return {"offset": 0, "exists": True, "sealed": entry.sealed}
        offset = await self.store.create(object_id, req["size"])
        if offset is None:
            entry = self.store.objects.get(object_id)
            return {"offset": 0, "exists": True, "sealed": entry is not None and entry.sealed}
        return {"offset": offset, "exists": False}

    @schema(object_id=str)
    async def rpc_store_wait_seal(self, req):
        """Block until the object's in-flight entry seals or aborts.

        Used by local producers that lost the create race to a pull/push
        session: sealed=True means the bytes are in the store; False means
        the session aborted (or no entry exists) and the producer should
        retry its create."""
        entry = self.store.objects.get(req["object_id"])
        if entry is None:
            return {"sealed": False}
        try:
            await asyncio.wait_for(entry.sealed_event.wait(), req.get("timeout") or 30.0)
        except asyncio.TimeoutError:
            return {"sealed": False}
        cur = self.store.objects.get(req["object_id"])
        return {"sealed": cur is entry and entry.sealed}

    async def rpc_store_seal(self, req):
        self.store.seal(req["object_id"])
        # Location registration is fire-and-forget: every reader of the GCS
        # location table polls (pull loop, reconstruction probe), so eventual
        # registration is enough — and the raylet->GCS client is FIFO, so any
        # later lookup through this raylet still observes it. Awaiting it
        # here put a full GCS round trip inside EVERY put of a plasma-sized
        # object (the put_1mib regression flagged by VERDICT r5 #8).
        async def _announce(object_id=req["object_id"]):
            # Must EVENTUALLY land (a remote pull of an unregistered object
            # polls forever, and the owner could misread a transiently
            # unregistered object as lost): retry with capped backoff until
            # the row registers, the object is deleted locally, or the
            # raylet stops. A GCS RESTART is additionally covered by the
            # heartbeat loop's full re-publication of sealed objects.
            delay = 0.2
            while not self._stopped:
                if not self.store.contains(object_id):
                    return  # freed/aborted meanwhile; nothing to announce
                try:
                    await self.gcs.acall(
                        "add_object_location",
                        {"object_id": object_id, "node_id": self.node_id},
                    )
                    return
                except Exception:
                    await asyncio.sleep(delay)
                    delay = min(delay * 2, 5.0)

        asyncio.ensure_future(_announce())
        return {"ok": True}

    async def rpc_store_abort(self, req):
        self.store.abort(req["object_id"])
        return {"ok": True}

    async def rpc_store_get(self, req):
        object_id = req["object_id"]
        timeout = req.get("timeout")
        if object_id not in self.store.objects:
            # Not local yet: race local creation (a task on this node may be
            # about to seal it) against a remote pull (reference: PullManager).
            await self._pull_object(object_id, timeout)
        offset, size = await self.store.get(object_id, timeout)
        return {"offset": offset, "size": size}

    @schema(object_id=str)
    async def rpc_store_contains(self, req):
        return {"found": self.store.contains(req["object_id"])}

    @schema(object_id=str)
    async def rpc_store_release(self, req):
        self.store.release(req["object_id"])
        return {"ok": True}

    @schema(object_id=str)
    async def rpc_free_object(self, req):
        """Owner frees an object cluster-wide (ref count hit zero)."""
        object_id = req["object_id"]
        resp = await self.gcs.acall("get_object_locations", {"object_id": object_id})
        for loc in resp["locations"]:
            if loc["node_id"] == self.node_id:
                self.store.delete(object_id)
                await self.gcs.acall(
                    "remove_object_location", {"object_id": object_id, "node_id": self.node_id}
                )
            else:
                try:
                    await self._peer(loc["node_id"], loc["address"]).acall(
                        "delete_local_object", {"object_id": object_id}
                    )
                except Exception:
                    pass
        return {"ok": True}

    @schema(channel_id=str, size=int)
    async def rpc_channel_create(self, req):
        """Allocate a compiled-graph channel ring from this node's arena
        (experimental/channel/); freed by channel_free at DAG teardown."""
        offset = await self.store.channel_create(req["channel_id"], req["size"])
        return {"offset": offset, "arena": self.arena_name}

    @schema(channel_id=str)
    async def rpc_channel_free(self, req):
        return {"freed": self.store.channel_free(req["channel_id"])}

    @schema(object_id=str)
    async def rpc_delete_local_object(self, req):
        self.store.delete(req["object_id"])
        await self.gcs.acall(
            "remove_object_location", {"object_id": req["object_id"], "node_id": self.node_id}
        )
        return {"ok": True}

    # ---- node-to-node transfer (reference: object_manager.h push/pull) ----

    async def rpc_fetch_object_info(self, req):
        object_id = req["object_id"]
        if not self.store.contains(object_id):
            return {"found": False}
        offset, size = await self.store.get(object_id)
        self.store.release(object_id)
        return {"found": True, "size": size}

    @schema(object_id=str, start=int, length=int)
    async def rpc_fetch_object_chunk(self, req):
        object_id = req["object_id"]
        offset, size = await self.store.get(object_id)
        try:
            start = req["start"]
            end = min(start + req["length"], size)
            if start < 0 or end <= start:
                # Out-of-range request (stale/buggy peer): answer empty on
                # the msgpack path — the puller sees a short chunk and fails
                # over — instead of handing arena.read a negative length.
                return {"data": b""}
            if req.get("raw") and self.raw_frames_enabled:
                # Raw response: the arena view goes straight to the socket;
                # the pin transfers to on_sent, released once the transport
                # has taken the bytes.
                view = self.arena.read(offset + start, end - start)
                TRANSFER.chunks_raw_out += 1
                TRANSFER.bytes_out += end - start
                result = RawResult(
                    object_id,
                    start,
                    view,
                    on_sent=lambda: self.store.release(object_id),
                )
                offset = None  # pin now owned by on_sent
                return result
            TRANSFER.chunks_msgpack_out += 1
            TRANSFER.bytes_out += end - start
            return {"data": bytes(self.arena.read(offset + start, end - start))}
        finally:
            if offset is not None:
                self.store.release(object_id)

    # ---- push-side transfer (reference: push_manager.h:29 sender pacing,
    # pull_manager.h:52 admission control) ----

    @schema(object_id=str, size=int, relay_targets=[list])
    async def rpc_push_begin(self, req):
        """Receiver-side admission: open a push session or refuse (saturated /
        already present / no arena space). The pusher backs off and retries.

        ``relay_targets``: cut-through broadcast — this node starts relaying
        the session's bytes to the subtree AS THEY ARRIVE (push_manager.
        stream_from_session), not after seal; push_commit folds the subtree
        outcome into its reply. The reply advertises ``raw_ok`` when this
        node accepts raw chunk frames for the session."""
        from ray_tpu.exceptions import ObjectStoreFullError

        object_id, size = req["object_id"], req["size"]
        entry = self.store.objects.get(object_id)
        if entry is not None:
            if entry.sealed:
                return {"accepted": False, "already": True}
            # Unsealed: an in-flight pull or rival push is filling it. NOT
            # "already" — the sender must not report success (a broadcast
            # relay would then wedge on the unsealed object); it retries
            # until the entry seals or vanishes.
            return {"accepted": False, "retry_after": 0.1}
        if object_id in self._inbound_pushes:
            return {"accepted": False, "retry_after": 0.1}
        if len(self._inbound_pushes) >= self.cfg.push_max_inbound:
            return {"accepted": False, "retry_after": 0.2}
        try:
            offset = await self.store.create(object_id, size)
        except ObjectStoreFullError:
            # No arena space even after evict/spill: back-pressure the
            # sender instead of failing its push outright.
            return {"accepted": False, "retry_after": 1.0}
        if offset is None:
            # A rival creator won during create's await: sealed -> done;
            # unsealed -> let the rival finish, sender retries.
            if self.store.contains(object_id):
                return {"accepted": False, "already": True}
            return {"accepted": False, "retry_after": 0.2}
        sess = self._inbound_pushes[object_id] = {
            "offset": offset,
            "size": size,
            "ts": time.monotonic(),
            # Contiguous-prefix watermark over received chunks: cut-through
            # relays stream [0, contig) downstream while later chunks are
            # still in flight (pipelined senders may arrive out of order).
            "chunks": {},
            "contig": 0,
            "event": asyncio.Event(),
            "aborted": False,
            "relays": [],
        }
        for child, subtree in _binomial_split(list(req.get("relay_targets") or [])):
            # (task, child, subtree): commit needs the tree shape back to
            # name the nodes a dead relay took down with it.
            sess["relays"].append(
                (
                    asyncio.ensure_future(
                        self.push_manager.stream_from_session(
                            sess, object_id, child, subtree, req.get("timeout")
                        )
                    ),
                    child,
                    subtree,
                )
            )
        return {"accepted": True, "raw_ok": self.raw_frames_enabled}

    @loop_only
    def _push_session_write(self, object_id: str, start: int, data) -> dict:
        """Land one chunk (msgpack or raw path) into its session buffer and
        advance the relay watermark. Synchronous — raw frames call this while
        their payload memoryview is still valid."""
        sess = self._inbound_pushes.get(object_id)
        if sess is None or sess["aborted"]:
            return {"ok": False, "error": "no session"}
        length = len(data)
        if start < 0 or start + length > sess["size"]:
            # Out-of-range write would corrupt the neighboring arena object.
            return {"ok": False, "error": "chunk out of range"}
        self.arena.write(sess["offset"] + start, data)
        sess["ts"] = time.monotonic()
        TRANSFER.bytes_in += length
        if start >= sess["contig"]:
            chunks = sess["chunks"]
            prev = chunks.get(start, 0)
            if length > prev:
                chunks[start] = length
            while sess["contig"] in chunks:
                sess["contig"] += chunks.pop(sess["contig"])
            sess["event"].set()
        return {"ok": True}

    @loop_only
    def _on_raw_frame(self, frame: RawFrame) -> dict:
        """Server raw sink (rpc.py): chunk payloads scatter straight into the
        session's arena block — no msgpack decode, no intermediate bytes."""
        if frame.kind == RAW_CHUNK:
            TRANSFER.chunks_raw_in += 1
            return self._push_session_write(frame.oid, frame.start, frame.payload)
        return {"ok": False, "error": f"unknown raw frame kind {frame.kind}"}

    @schema(object_id=str, start=int, data=bytes)
    async def rpc_push_chunk(self, req):
        TRANSFER.chunks_msgpack_in += 1
        return self._push_session_write(req["object_id"], req["start"], req["data"])

    @schema(object_id=str)
    async def rpc_push_commit(self, req):
        object_id = req["object_id"]
        sess = self._inbound_pushes.pop(object_id, None)
        if sess is None:
            # No live session: either a RETRIED commit (the sender's first
            # reply timed out or rode a reset connection) — serve the
            # remembered outcome, which may still be gathering its relay
            # subtree; this reply is the ONLY carrier of the cut-through
            # verdict, and a bare contains() guess would report ok while
            # dropping subtree failures — or an abort raced the commit
            # (present iff sealed earlier).
            fut = self._commit_results.get(object_id)
            if fut is not None:
                return await fut
            return {"ok": self.store.contains(object_id)}
        fut = asyncio.get_event_loop().create_future()
        self._commit_results[object_id] = fut
        try:
            result = await self._finish_commit(object_id, sess)
        except Exception as e:  # noqa: BLE001
            from ray_tpu._private.push_manager import subtree_node_ids

            failed = [self.node_id]
            for _, child, subtree in sess["relays"]:
                failed.extend(subtree_node_ids(child, subtree))
            result = {"ok": False, "failed": failed, "error": repr(e)}
        fut.set_result(result)

        def _forget(oid=object_id, f=fut):
            if self._commit_results.get(oid) is f:  # never pop a successor's
                self._commit_results.pop(oid, None)

        asyncio.get_event_loop().call_later(120.0, _forget)
        return result

    async def _finish_commit(self, object_id: str, sess: dict) -> dict:
        if sess["contig"] != sess["size"]:
            # Commit without all bytes (sender bug / lost ack): refuse rather
            # than seal a hole-y object.
            self._abort_push_session(object_id, sess)
            return {"ok": False, "error": "incomplete push session"}
        self.store.seal(object_id)
        # Pin IMMEDIATELY after seal, before ANY await: a sealed, unpinned
        # object is spill/evict fair game, and the cut-through relays are
        # still reading its arena block (sess["offset"]). seal() and the
        # sealed-entry branch of get() run without suspending, so no other
        # coroutine can evict in between; awaiting the GCS announce first
        # (the original ordering) opened exactly that window.
        pinned = bool(sess["relays"])
        if pinned:
            await self.store.get(object_id)
        results = None
        try:
            try:
                await self.gcs.acall(
                    "add_object_location",
                    {"object_id": object_id, "node_id": self.node_id},
                )
            finally:
                # Drain the relays BEFORE any path can release the pin: even
                # when the announce raises, the relay tasks keep reading
                # sess["offset"], and an unpinned sealed object is evict
                # fair game — they would forward reused-block bytes and the
                # children would seal corrupt copies.
                if sess["relays"]:
                    results = await asyncio.gather(
                        *(t for t, _, _ in sess["relays"]), return_exceptions=True
                    )
            if results is None:
                return {"ok": True}
            # Cut-through subtree outcome folds into THIS reply so failures
            # propagate to the broadcast root.
        finally:
            if pinned:
                self.store.release(object_id)
        failed: list[str] = []
        for (_, child, subtree), r in zip(sess["relays"], results):
            if isinstance(r, BaseException):
                # A relay that died without reporting takes its whole
                # subtree down; name the NODES (the failed-list contract —
                # callers reconcile entries against target node ids).
                from ray_tpu._private.push_manager import subtree_node_ids

                failed.extend(subtree_node_ids(child, subtree))
            elif not r.get("ok"):
                failed.extend(r.get("failed") or [child["node_id"]])
        return {"ok": not failed, "failed": failed}

    def _abort_push_session(self, object_id: str, sess: dict):
        sess["aborted"] = True
        sess["event"].set()  # wake relay waiters so they fail fast
        self.store.abort(object_id)

    @schema(object_id=str)
    async def rpc_push_abort(self, req):
        sess = self._inbound_pushes.pop(req["object_id"], None)
        if sess is not None:
            self._abort_push_session(req["object_id"], sess)
        return {"ok": True}

    def _reap_stale_push_sessions(self):
        """A sender that died between push_begin and commit/abort must not
        leak its admission slot + unsealed arena allocation forever (8 leaks
        would wedge the node's whole inbound push plane)."""
        now = time.monotonic()
        for oid, sess in list(self._inbound_pushes.items()):
            if now - sess["ts"] > 60.0:
                self._inbound_pushes.pop(oid, None)
                self._abort_push_session(oid, sess)
                logger.warning("reaped stale inbound push session for %s", oid[:8])

    @schema(object_id=str, targets=[list])
    async def rpc_broadcast_object(self, req):
        """Fan an object out to `targets` over a binomial tree: this node
        pushes to O(log N) children, each child relays to its subtree. The
        1-GiB-to-50-nodes envelope (BASELINE.md) needs this — a flat push
        loop would serialize on the root's NIC.

        The subtree rides IN the push itself (push_begin relay_targets):
        each level starts forwarding after its first received chunk
        (cut-through), so end-to-end latency is O(size + depth × chunk)
        instead of the old store-and-forward O(depth × size)."""
        object_id = req["object_id"]
        targets = list(req.get("targets", []))
        timeout = req.get("timeout", 300.0)
        if not self.store.contains(object_id):
            # contains() is sealed-only on purpose: an unsealed entry (a
            # rival inbound session that may yet be aborted) must not make
            # us skip the pull and then block forever in push's store.get.
            await self._pull_object(object_id, timeout=timeout)
        from ray_tpu._private.push_manager import subtree_node_ids

        splits = _binomial_split(targets)
        results = await asyncio.gather(
            *(
                self.push_manager.push(
                    object_id,
                    child["node_id"],
                    child["address"],
                    relay_targets=subtree,
                    timeout=timeout,
                )
                for child, subtree in splits
            ),
            return_exceptions=True,
        )
        failed: list[str] = []
        for (child, subtree), r in zip(splits, results):
            if isinstance(r, BaseException):
                failed.extend(subtree_node_ids(child, subtree))
            elif not r.get("ok"):
                failed.extend(r.get("failed") or [child["node_id"]])
        return {"ok": not failed, "failed": failed}

    async def _pull_object(self, object_id: str, timeout: float | None):
        """Fetch a remote object into the local store (pull_manager.py:
        pipelined chunk requests striped across every known replica, ranked
        failover, and an aggregate admission byte budget)."""
        await self.pull_manager.pull(object_id, timeout)

    def _peer(self, node_id: str, address) -> RpcClient:
        client = self._peer_clients.get(node_id)
        if client is None:
            client = RpcClient(tuple(address), label=f"peer-{node_id[:8]}")
            client.chaos_scope = self._addr_key
            self._peer_clients[node_id] = client
        return client

    # ------------------------------------------------------------------
    # Placement-group bundles (2PC; reference: placement_group_resource_manager.h)
    # ------------------------------------------------------------------

    @property
    def resources_available(self) -> dict:
        """Derived view over the scheduler core's ledger."""
        return {k: self._sched.node_avail(self.node_id, k) for k in self._res_keys}

    @staticmethod
    def _bundle_pool_key(pg_id: str, idx: int) -> str:
        return f"{pg_id}:{max(idx, 0)}"

    async def rpc_prepare_bundle(self, req):
        # 2PC prepare (reference: gcs_placement_group_scheduler.h): the
        # bundle's resources move from the main pool into a reservation.
        key = (req["pg_id"], req["bundle_index"])
        res = req["resources"]
        self._res_keys.update(res)
        if not self._sched.try_acquire(self.node_id, res):
            return {"ok": False}
        self.bundle_reserved[key] = dict(res)
        return {"ok": True}

    async def rpc_commit_bundle(self, req):
        key = (req["pg_id"], req["bundle_index"])
        res = self.bundle_reserved.pop(key, None)
        if res is None:
            return {"ok": False}
        self.bundles[key] = dict(res)
        self._sched.pool_upsert(self._bundle_pool_key(*key), res)
        self._requeue_infeasible()  # tasks waiting on this bundle's pool
        await self._dispatch()
        return {"ok": True}

    async def rpc_return_bundle(self, req):
        key = (req["pg_id"], req["bundle_index"])
        res = self.bundle_reserved.pop(key, None)
        committed = self.bundles.pop(key, None)
        if committed is not None:
            self._sched.pool_remove(self._bundle_pool_key(*key))
            res = committed
        if res:
            self._sched.release(self.node_id, res)
        return {"ok": True}

    # ------------------------------------------------------------------
    # Scheduling (reference: ClusterTaskManager + LocalTaskManager)
    # ------------------------------------------------------------------

    @schema(spec=dict)
    async def rpc_submit_task(self, req):
        spec = TaskSpec.from_wire(req["spec"])
        if spec.hop_ts:
            spec.hop_ts["raylet_recv"] = time.monotonic()
        await self._queue_and_schedule(spec)
        return {"ok": True}

    @schema(task_ids=list)
    async def rpc_locate_tasks(self, req):
        """Which of these task ids does THIS raylet currently hold (queued,
        infeasible, or executing on a worker)? Owners sweep this across
        alive nodes to find tasks orphaned by server-side spillback: a spec
        forwarded to a node that died with it is held by NOBODY, and
        without the sweep the owner would wait on its returns forever
        (observed: a chaos-killed node took queued shuffle tasks with it
        and dataset.sum() hung)."""
        wanted = set(req["task_ids"])
        found = [tid for tid in self._forwarding if tid in wanted]
        for q in (self.task_queue, self._infeasible):
            for spec in q:
                if spec.task_id in wanted:
                    found.append(spec.task_id)
        for w in self.workers.values():
            cur = w.current_task
            if cur is not None and cur.task_id in wanted:
                found.append(cur.task_id)
            # Leased workers execute owner-shipped specs the raylet does not
            # see; the lease manager owns THOSE tasks' failover, and the
            # owner's sweep excludes lease-path tasks entirely.
        return {"found": found}

    # ---- task cancellation (reference: node_manager.cc HandleCancelTask +
    # cluster_task_manager.cc CancelTask) ----

    @schema(task_id=str)
    async def rpc_cancel_task(self, req):
        """Cancel a task wherever this raylet can see it: dequeue if queued
        locally, forward to the executing worker if dispatched, else
        tombstone (drop on late arrival) and fan out to peers once — a
        spillback may have moved the task off this node."""
        task_id = req["task_id"]
        for q in (self.task_queue, self._infeasible):
            for spec in q:
                if spec.task_id == task_id:
                    q.remove(spec)
                    return {"found": True, "dequeued": True}
        for worker in self.workers.values():
            spec = worker.current_task
            if spec is not None and spec.task_id == task_id and worker.client is not None:
                try:
                    await worker.client.acall(
                        "cancel_exec",
                        {
                            "task_id": task_id,
                            "force": bool(req.get("force")),
                            "recursive": req.get("recursive", True),
                        },
                        timeout=10,
                    )
                except Exception:
                    pass  # worker death surfaces via the normal failure path
                return {"found": True, "dequeued": False}
        self._tombstone_cancel(task_id)
        if req.get("fanout", True):
            # Probe all peers CONCURRENTLY: sequential probes with a 10s
            # timeout each could exceed the owner's single 30s cancel
            # budget as soon as a few peers are unreachable — gather bounds
            # the whole fan-out to ~one timeout.
            peers = [
                (nid, node)
                for nid, node in list(self.cluster_view.items())
                if nid != self.node_id  # already searched locally above
            ]
            if peers:
                results = await asyncio.gather(
                    *(
                        self._peer(nid, node["address"]).acall(
                            "cancel_task", dict(req, fanout=False), timeout=10
                        )
                        for nid, node in peers
                    ),
                    return_exceptions=True,
                )
                for resp in results:
                    if isinstance(resp, dict) and resp.get("found"):
                        return resp
        return {"found": False, "dequeued": False}

    def _tombstone_cancel(self, task_id: str):
        self._cancelled_tasks.add(task_id)

    @schema(specs=list)
    async def rpc_submit_tasks(self, req):
        """Batched submission: one RPC for a burst of specs (client-side
        coalescing in core_worker._flush_submits). Dispatch runs ONCE for
        the whole batch, and the loop yields periodically so a deep burst
        can't starve heartbeats. Failures are PER SPEC — earlier specs are
        already queued and will run, so failing the whole batch client-side
        would report errors for tasks that execute anyway."""
        failed = []
        for i, wire in enumerate(req["specs"]):
            try:
                spec = TaskSpec.from_wire(wire)
                if spec.hop_ts:
                    spec.hop_ts["raylet_recv"] = time.monotonic()
                await self._queue_and_schedule(spec, dispatch=False)
            except Exception as e:  # noqa: BLE001
                failed.append({"task_id": wire.get("task_id"), "error": repr(e)})
            if i % 200 == 199:
                await asyncio.sleep(0)
        await self._dispatch()
        return {"ok": True, "failed": failed}

    async def _queue_and_schedule(self, spec: TaskSpec, dispatch: bool = True):
        if spec.placement_group_id and not self._has_pool(spec):
            # Bundle lives elsewhere: ask GCS for its node and forward there.
            resp = await self.gcs.acall(
                "get_placement_group", {"pg_id": spec.placement_group_id}
            )
            if resp.get("found"):
                idx = max(spec.placement_group_bundle_index, 0)
                bundle_nodes = resp["info"]["bundle_nodes"]
                target_node = bundle_nodes[idx] if idx < len(bundle_nodes) else None
                if target_node and target_node != self.node_id:
                    node = self.cluster_view.get(target_node)
                    if node is not None:
                        await self._peer(target_node, node["address"]).acall(
                            "submit_task", {"spec": spec.to_wire()}
                        )
                        return
            # Bundle not placed yet: queue; dispatch retries as views update.
            self.task_queue.append(spec)
            if dispatch:
                await self._dispatch()
            return
        target = self._pick_node(spec, prefer=await self._locality_prefs(spec))
        if target is not None and target != self.node_id:
            # Spillback (reference: cluster_task_manager.cc:44 + spillback reply).
            node = self.cluster_view.get(target)
            if node is not None:
                # Optimistically debit the peer's MIRRORED availability: a
                # burst of picks would otherwise all score the same stale
                # fits-now peer and dogpile it. The debit is provisional —
                # an authoritative heartbeat row overwrites it, and the
                # debit ledger credits it back if none ever arrives.
                if self._sched.try_acquire(target, spec.resources):
                    self._opt_debits.note(
                        target, spec.resources, self.cfg.heartbeat_interval_s
                    )
                self._forwarding.add(spec.task_id)
                try:
                    await self._peer(target, node["address"]).acall("submit_task", {"spec": spec.to_wire()})
                    return
                except Exception:
                    pass
                finally:
                    self._forwarding.discard(spec.task_id)
        self.task_queue.append(spec)
        if dispatch:
            await self._dispatch()

    def _has_pool(self, spec: TaskSpec) -> bool:
        """Does the pool this task draws from exist locally?"""
        if spec.placement_group_id:
            return self._sched.pool_exists(
                self._bundle_pool_key(
                    spec.placement_group_id, spec.placement_group_bundle_index
                )
            )
        return True

    def _fits_now(self, spec: TaskSpec) -> bool:
        """Non-mutating fit check (the event loop is single-threaded, so
        check-then-acquire cannot race)."""
        if spec.placement_group_id:
            key = self._bundle_pool_key(
                spec.placement_group_id, spec.placement_group_bundle_index
            )
            get = lambda k: self._sched.pool_avail(key, k)  # noqa: E731
        else:
            get = lambda k: self._sched.node_avail(self.node_id, k)  # noqa: E731
        return all(get(k) >= v - 1e-9 for k, v in spec.resources.items())

    def _acquire_for(self, spec: TaskSpec) -> bool:
        self._res_keys.update(spec.resources)
        if spec.placement_group_id:
            return self._sched.pool_try_acquire(
                self._bundle_pool_key(
                    spec.placement_group_id, spec.placement_group_bundle_index
                ),
                spec.resources,
            )
        return self._sched.try_acquire(self.node_id, spec.resources)

    def _requeue_infeasible(self):
        """Move parked tasks back into the dispatch queue (capacity or the
        cluster view changed, so their fit must be re-evaluated)."""
        if self._infeasible:
            self.task_queue.extend(self._infeasible)
            self._infeasible.clear()

    def _release_for(self, spec: TaskSpec):
        if spec.placement_group_id:
            key = self._bundle_pool_key(
                spec.placement_group_id, spec.placement_group_bundle_index
            )
            if self._sched.pool_exists(key):
                self._sched.pool_release(key, spec.resources)
        else:
            self._sched.release(self.node_id, spec.resources)
        self._requeue_infeasible()

    def _pick_node(self, spec: TaskSpec, prefer: list | None = None) -> str | None:
        """Cluster-level placement: hybrid pack-then-spread policy
        (reference: policy/hybrid_scheduling_policy.h:50), with an optional
        locality preference list (holder nodes of the task's reference args,
        best-first) tried ahead of the policy — spilling to the policy's
        least-loaded choice when every holder is saturated."""
        strategy = spec.scheduling_strategy or "DEFAULT"
        if spec.placement_group_id:
            return self.node_id if self._has_pool(spec) else self._pg_bundle_node(spec)
        if strategy.startswith("node:"):
            parts = strategy.split(":")
            node_id = parts[1]
            soft = len(parts) > 2 and parts[2] == "soft"
            if node_id == self.node_id or node_id in self.cluster_view:
                return node_id
            return self.node_id if soft else None
        from ray_tpu._private.sched_core import HYBRID, SPREAD

        if prefer:
            for nid in prefer:
                if nid == self.node_id:
                    if self._fits_now(spec):
                        self._note_locality_hit(spec, nid)
                        return nid
                elif nid in self.cluster_view and self._sched.node_fits(
                    nid, spec.resources
                ):
                    self._note_locality_hit(spec, nid)
                    return nid
        # Both policies score over the core's cluster view (local ledger is
        # live; peers mirrored from heartbeats). Hybrid = pack the local node
        # while it fits now, spill to a fits-now peer, else queue wherever
        # the shape is at least feasible by totals (local preferred) —
        # reference policy/hybrid_scheduling_policy.h:50.
        policy = SPREAD if strategy == "SPREAD" else HYBRID
        return self._sched.best_node(spec.resources, policy, self.node_id)

    def _note_locality_hit(self, spec: TaskSpec, nid: str):
        flight_recorder.record("locality_hit", f"{spec.task_id[:8]}->{nid[:8]}")
        try:
            self._metrics["locality_hits"].inc()
        except Exception:
            pass

    async def _locality_prefs(self, spec: TaskSpec) -> list | None:
        """Holder nodes of the task's reference args, most-args-held first;
        None when locality doesn't apply (disabled, constrained strategy,
        single-node view, or no reference args)."""
        if not self.cfg.locality_aware_scheduling or spec.placement_group_id:
            return None
        if (spec.scheduling_strategy or "DEFAULT") != "DEFAULT":
            return None
        if len(self.cluster_view) <= 1:
            return None
        counts = await self._arg_locality.holders(spec)
        if not counts:
            return None
        return sorted(counts, key=lambda n: -counts[n])

    def _pg_bundle_node(self, spec: TaskSpec) -> str | None:
        # Bundle lives on another node; ask GCS which.
        return None  # handled by core_worker resolving bundle location up front

    def _self_view(self):
        return {
            "resources_total": self.resources_total,
            "resources_available": self.resources_available,
            "address": list(self.address),
        }

    async def _dispatch(self):
        """Local dispatch loop (reference: local_task_manager.cc:101).

        The inner scan is CAPPED per call: with a deep backlog (the
        100k+-queued-tasks envelope) an uncapped pass would walk the whole
        deque on every submission — O(n) per submit, O(n^2) for a burst —
        starving the event loop until the GCS health checker declares the
        node dead. Tasks that can't run yet move to self._infeasible (not
        back into the scan window), so repeated capped calls make monotonic
        progress through the queue; _requeue_infeasible() splices them back
        when capacity or the cluster view changes.
        """
        made_progress = True
        while made_progress and self.task_queue:
            made_progress = False
            for _ in range(min(len(self.task_queue), 128)):
                spec = self.task_queue.popleft()
                if spec.task_id in self._cancelled_tasks:
                    # Cancelled before it arrived here; the owner already
                    # failed it with TaskCancelledError.
                    self._cancelled_tasks.discard(spec.task_id)
                    made_progress = True
                    continue
                if self._must_reroute(spec):
                    # Wrong node for this task; the heartbeat loop re-routes it
                    # once the cluster view / PG placement catches up.
                    self._infeasible.append(spec)
                    continue
                if not self._has_pool(spec) or not self._fits_now(spec):
                    self._infeasible.append(spec)
                    continue
                spec_env_hash = _worker_key(spec.runtime_env, getattr(spec, "language", "py"))
                worker = self._pop_idle_worker(spec_env_hash)
                if worker is None:
                    # Start enough workers for the whole backlog at once
                    # (reference prestarts workers too, worker_pool.cc:426);
                    # spawning serially would add one startup latency per task.
                    starting = sum(1 for w in self.workers.values() if w.state == "starting")
                    starting_matching = sum(
                        1
                        for w in self.workers.values()
                        if w.state == "starting" and w.runtime_env_hash == spec_env_hash
                    )
                    # Workers dedicated to actors never come back to the pool;
                    # only count pool workers against the CPU-sized target.
                    pool_workers = sum(
                        1 for w in self.workers.values() if w.state in ("starting", "idle", "busy")
                    )
                    cpu_cap = max(1, int(self.resources_total.get("CPU", 1)))
                    deficit = min(
                        len(self.task_queue) + 1 - starting,
                        cpu_cap - pool_workers,
                        self.cfg.max_workers_per_node - self._num_live_workers(),
                    )
                    if deficit <= 0 and starting_matching == 0:
                        # Pool is full but no worker for THIS runtime env is
                        # idle or coming: evict one surplus idle worker of a
                        # different env to make room (reference: worker_pool
                        # kills idle workers of other envs under pressure).
                        victim = next(
                            (
                                w
                                for w in self.workers.values()
                                if w.state == "idle" and w.runtime_env_hash != spec_env_hash
                            ),
                            None,
                        )
                        if victim is not None:
                            victim.state = "dead"
                            if victim.proc is not None:
                                victim.proc.terminate()
                            deficit = 1
                    if (
                        deficit <= 0
                        and starting == 0
                        and self._num_live_workers() < self.cfg.max_workers_per_node
                        and time.monotonic() - self._last_progress > 2.0
                    ):
                        # Anti-starvation: busy workers may themselves be
                        # blocked on results of queued tasks (nested tasks);
                        # after 2s without dispatch progress, oversubscribe.
                        deficit = 1
                    # Start workers dedicated to the runtime envs of the
                    # tasks actually waiting (head of queue first). Only the
                    # first `deficit` entries are needed — materializing the
                    # whole queue here cost O(n) per submission at depth.
                    import itertools

                    pending_envs = [(spec.runtime_env, getattr(spec, "language", "py"))] + [
                        (s.runtime_env, getattr(s, "language", "py"))
                        for s in itertools.islice(self.task_queue, max(deficit, 0))
                    ]
                    for i in range(max(deficit, 0)):
                        env_i, lang_i = (
                            pending_envs[i] if i < len(pending_envs) else (None, "py")
                        )
                        self._start_worker(env_i, lang_i)
                    self.task_queue.appendleft(spec)
                    return
                if not self._acquire_for(spec):
                    # Should not happen (single-threaded loop; _fits_now was
                    # true) — requeue defensively rather than leak a worker.
                    worker.state = "idle"
                    self.task_queue.append(spec)
                    continue
                if spec.lease_id:
                    self._grant_lease(worker, spec)
                    made_progress = True
                    self._last_progress = time.monotonic()
                    continue
                worker.state = "actor" if spec.is_actor_creation() else "busy"
                worker.current_task = spec
                worker.dispatch_ts = time.monotonic()
                worker.last_job_id = spec.job_id
                worker.last_task_name = spec.name
                if spec.is_actor_creation():
                    worker.actor_id = spec.actor_id
                made_progress = True
                self._last_progress = time.monotonic()
                asyncio.ensure_future(self._push_to_worker(worker, spec))

    async def _push_to_worker(self, worker: WorkerHandle, spec: TaskSpec):
        if spec.hop_ts:
            spec.hop_ts["raylet_dispatch"] = time.monotonic()
        try:
            await worker.client.acall(
                "push_task",
                {"spec": spec.to_wire(), "assigned_resources": spec.resources},
            )
        except Exception:
            logger.exception("push_task to worker %s failed", worker.worker_id[:8])
            await self._on_worker_death(worker, "push_task failed")

    # ---- worker leases (reference: direct_task_transport.cc:304) ----

    def _grant_lease(self, worker: WorkerHandle, spec: TaskSpec):
        fut = self._lease_futures.pop(spec.lease_id, None)
        if fut is None or fut.done():
            # Requester gave up (cancel or timeout) before we could grant.
            self._release_for(spec)
            worker.state = "idle"
            worker.last_idle = time.monotonic()
            return
        worker.state = "busy"
        worker.current_task = spec
        worker.dispatch_ts = time.monotonic()
        worker.last_job_id = spec.job_id
        worker.last_task_name = "__lease__"
        self._leases[spec.lease_id] = {
            "worker_id": worker.worker_id,
            "spec": spec,
            "renewed": time.monotonic(),
        }
        fut.set_result(
            {
                "granted": True,
                "worker_id": worker.worker_id,
                "address": list(worker.address),
                # Spilled grants come from a PEER raylet: renew/return must
                # target the raylet that actually holds the lease record.
                "raylet_address": list(self.address),
            }
        )

    @schema(spec=dict)
    async def rpc_request_worker_lease(self, req):
        spec = TaskSpec.from_wire(req["spec"])
        if not spec.lease_id:
            return {"granted": False, "error": "spec.lease_id missing"}
        # Cluster-level placement for the lease itself (reference: the lease
        # request is what spills back, cluster_task_manager.cc:44): forward
        # the whole request — the granted worker address is globally
        # routable, so the owner talks straight to the remote worker. The
        # lease spec carries the first task's args, so locality preference
        # applies here too (the default transport).
        target = self._pick_node(spec, prefer=await self._locality_prefs(spec))
        if target is not None and target != self.node_id:
            node = self.cluster_view.get(target)
            if node is not None:
                try:
                    return await self._peer(target, node["address"]).acall(
                        "request_worker_lease",
                        req,
                        timeout=self.cfg.worker_lease_timeout_s + 5,
                    )
                except Exception:
                    pass
        # Owner-side queue depth as autoscaler demand (the owner's shape
        # queue replaces the raylet task queue under the lease transport).
        self._lease_demand[(spec.owner_worker_id, tuple(sorted(spec.resources.items())))] = (
            int(req.get("backlog", 0)),
            time.monotonic(),
        )
        fut = asyncio.get_event_loop().create_future()
        self._lease_futures[spec.lease_id] = fut
        self.task_queue.append(spec)
        await self._dispatch()
        try:
            return await asyncio.wait_for(fut, self.cfg.worker_lease_timeout_s)
        except asyncio.TimeoutError:
            self._lease_futures.pop(spec.lease_id, None)
            self._remove_queued_lease(spec.lease_id)
            return {"granted": False}

    def _remove_queued_lease(self, lease_id: str):
        """Best-effort: at envelope queue depths (1M+) an O(n) walk per
        abandoned lease request would stall the loop; the dispatch path
        already frees workers granted to a vanished requester
        (_grant_lease's missing-future branch), so deep queues self-heal."""
        if len(self.task_queue) + len(self._infeasible) > 10_000:
            return
        for q in (self.task_queue, self._infeasible):
            for s in list(q):
                if s.lease_id == lease_id:
                    q.remove(s)

    @schema(lease_id=str)
    async def rpc_cancel_lease_request(self, req):
        fut = self._lease_futures.pop(req["lease_id"], None)
        if fut is not None and not fut.done():
            fut.set_result({"granted": False})
        self._remove_queued_lease(req["lease_id"])
        return {"ok": True}

    @schema(lease_id=str)
    async def rpc_return_worker_lease(self, req):
        lease = self._leases.pop(req["lease_id"], None)
        if lease is None:
            return {"ok": False}
        worker = self.workers.get(lease["worker_id"])
        spec = lease["spec"]
        # A returned lease means the owner's queue for this shape drained.
        self._lease_demand.pop(
            (spec.owner_worker_id, tuple(sorted(spec.resources.items()))), None
        )
        self._release_for(spec)
        if worker is not None and worker.state == "busy":
            worker.state = "idle"
            worker.current_task = None
            worker.last_idle = time.monotonic()
        await self._dispatch()
        return {"ok": True}

    @schema(lease_ids=list)
    async def rpc_renew_worker_leases(self, req):
        now = time.monotonic()
        revoked = []
        for lid in req["lease_ids"]:
            lease = self._leases.get(lid)
            if lease is None:
                revoked.append(lid)
            else:
                lease["renewed"] = now
        # Per-shape backlog refresh piggybacked on renewal: keeps the
        # autoscaler's demand view live while leases are held warm (the
        # request-time backlog figure is otherwise frozen for the lease's
        # whole lifetime).
        owner = req.get("owner")
        if owner:
            for res, count in req.get("backlogs") or []:
                key = (owner, tuple(sorted(res.items())))
                if count:
                    self._lease_demand[key] = (int(count), now)
                else:
                    self._lease_demand.pop(key, None)
        return {"revoked": revoked}

    def _pop_idle_worker(self, runtime_env_hash: str | None = None) -> WorkerHandle | None:
        for w in self.workers.values():
            if w.state == "idle" and w.runtime_env_hash == runtime_env_hash:
                return w
        return None

    def _num_live_workers(self) -> int:
        return sum(1 for w in self.workers.values() if w.state != "dead")

    # ---- worker pool (reference: worker_pool.cc) ----

    def _worker_env_delta(self, worker_id: str, runtime_env: dict | None) -> dict:
        """The env vars a worker needs on top of this raylet's environment."""
        delta = {
            "RAY_TPU_WORKER_ID": worker_id,
            "RAY_TPU_NODE_ID": self.node_id,
            "RAY_TPU_RAYLET_ADDR": json.dumps(list(self.address)),
            "RAY_TPU_GCS_ADDR": json.dumps(list(self.gcs.address)),
            "RAY_TPU_ARENA_NAME": self.arena_name,
            "RAY_TPU_SESSION_DIR": self.session_dir,
        }
        if runtime_env:
            delta["RAY_TPU_RUNTIME_ENV"] = json.dumps(runtime_env)
        if self._tracing_enabled:
            delta["RAY_TPU_TRACING"] = "1"
        # Workers must import the same modules the driver pickles by reference
        # (cloudpickle serializes importable functions by name); ship the
        # driver-side sys.path (reference: runtime-env py_modules/working_dir).
        extra_path = os.pathsep.join(p for p in sys.path if p)
        base = os.environ.get("PYTHONPATH")
        delta["PYTHONPATH"] = extra_path + os.pathsep + base if base else extra_path
        return delta

    def _zygote_client(self):
        """Lazy fork-server handle (zygote.py). None when disabled or on TPU
        nodes — forking a process after a TPU-plugin dial is unsafe, and TPU
        workers are few and long-lived anyway."""
        if not self.cfg.worker_zygote_enabled or self.resources_total.get("TPU"):
            return None
        if getattr(self, "_zygote", None) is None:
            from ray_tpu._private.zygote import ZygoteClient

            base_env = os.environ.copy()
            base_env.pop("PALLAS_AXON_POOL_IPS", None)
            # The zygote imports ray_tpu at startup: it needs the driver's
            # sys.path just like workers do (the driver may have added the
            # package root via sys.path.insert, not PYTHONPATH).
            extra_path = os.pathsep.join(p for p in sys.path if p)
            base_env["PYTHONPATH"] = (
                extra_path + os.pathsep + base_env["PYTHONPATH"]
                if base_env.get("PYTHONPATH")
                else extra_path
            )
            self._zygote = ZygoteClient(
                self.session_dir, base_env, self._on_zygote_worker_exit
            )
        return self._zygote

    def _on_zygote_worker_exit(self, pid: int, returncode: int):
        from ray_tpu._private.zygote import ZygoteWorkerProc

        for w in self.workers.values():
            if w.pid == pid and isinstance(w.proc, ZygoteWorkerProc):
                w.proc.returncode = returncode

    def _start_worker(self, runtime_env: dict | None = None, language: str = "py"):
        worker_id = WorkerID.from_random().hex()
        delta = self._worker_env_delta(worker_id, runtime_env)
        log_path = os.path.join(self.session_dir, "logs", f"worker-{worker_id[:8]}")
        os.makedirs(os.path.dirname(log_path), exist_ok=True)
        handle = WorkerHandle(
            worker_id=worker_id,
            pid=0,
            runtime_env_hash=_worker_key(runtime_env, language),
        )
        self.workers[worker_id] = handle
        if language == "cpp":
            # Native worker runtime (cpp/ray_tpu_worker.cc): spawned
            # directly (no zygote — nothing Python to pre-fork). The first
            # ever spawn may find the binary not yet compiled: the build
            # runs in a background thread (a synchronous g++ here would
            # stall the raylet event loop for seconds) and THIS worker
            # falls back to a Python process under the SAME pool key — it
            # executes cpp specs through the ctypes path (_load_function
            # "cpp!" fallback), so behavior is identical; later spawns pick
            # up the compiled binary.
            from ray_tpu._private.cpp_worker import cpp_worker_binary_nowait

            binary = cpp_worker_binary_nowait()
            self._popen_worker(
                handle, delta, log_path, argv=[binary] if binary else None
            )
            return
        zygote = self._zygote_client()
        if zygote is not None:
            asyncio.ensure_future(
                self._spawn_via_zygote(zygote, handle, delta, log_path)
            )
        else:
            self._popen_worker(handle, delta, log_path)

    def _popen_worker(
        self, handle: WorkerHandle, delta: dict, log_path: str, argv: list | None = None
    ):
        """Spawn a worker process. Default argv is the Python worker entry;
        a custom argv spawns a native runtime (the C++ worker binary)."""
        env = os.environ.copy()
        if argv is not None or not self.resources_total.get("TPU"):
            # On a TPU host a sitecustomize hook dials the TPU plugin during
            # interpreter start (~2s); workers on CPU-only nodes never touch
            # the chip, so skip it — worker spawn drops ~10x. Native workers
            # never dial the chip at all.
            env.pop("PALLAS_AXON_POOL_IPS", None)
        env.update(delta)
        stdout = open(log_path + ".out", "ab")
        stderr = open(log_path + ".err", "ab")
        proc = subprocess.Popen(
            argv or [sys.executable, "-m", "ray_tpu._private.worker_main"],
            env=env,
            stdout=stdout,
            stderr=stderr,
            cwd=os.getcwd(),
        )
        handle.proc = proc
        handle.pid = proc.pid

    async def _spawn_via_zygote(self, zygote, handle: WorkerHandle, delta: dict, log_path: str):
        from ray_tpu._private.zygote import ZygoteWorkerProc

        try:
            pid = await zygote.spawn(delta, log_path + ".out", log_path + ".err")
        except Exception:
            logger.exception("zygote spawn failed; falling back to subprocess")
            if handle.state == "dead":
                return
            # The fork may have succeeded with the reply lost or late (zygote
            # died post-fork, wait timeout): retire this worker id and give
            # the Popen replacement a fresh one, so an orphan child that
            # registers late can't collide with the replacement. A late
            # spawn reply for the abandoned req_id kills the orphan pid
            # (ZygoteClient._read_loop).
            self.workers.pop(handle.worker_id, None)
            self._retired_worker_ids.add(handle.worker_id)
            fresh_id = WorkerID.from_random().hex()
            handle.worker_id = fresh_id
            self.workers[fresh_id] = handle
            self._popen_worker(
                handle, dict(delta, RAY_TPU_WORKER_ID=fresh_id), log_path
            )
            return
        handle.pid = pid
        handle.proc = ZygoteWorkerProc(pid)
        if handle.state == "dead":
            # Killed while the fork was in flight (eviction/stop).
            handle.proc.kill()

    @schema(worker_id=str, pid=int, address=list)
    async def rpc_register_worker(self, req):
        worker_id = req["worker_id"]
        if worker_id in self._retired_worker_ids:
            # An orphan from an abandoned zygote spawn (we already Popen'd a
            # replacement under a fresh id): tell it to exit, and reap it
            # shortly after in case it doesn't (it is a local process).
            pid = req["pid"]

            def _reap():
                try:
                    os.kill(pid, 9)
                except (ProcessLookupError, PermissionError):
                    pass

            asyncio.get_event_loop().call_later(2.0, _reap)
            return {"ok": False, "reason": "retired worker id"}
        handle = self.workers.get(worker_id)
        if handle is None:
            handle = WorkerHandle(worker_id=worker_id, pid=req["pid"])
            self.workers[worker_id] = handle
        handle.address = tuple(req["address"])
        handle.client = RpcClient(handle.address, label=f"worker-{worker_id[:8]}")
        handle.client.chaos_scope = self._addr_key
        handle.state = "idle"
        handle.last_idle = time.monotonic()
        await self._dispatch()
        return {"ok": True, "node_id": self.node_id}

    @schema(worker_id=str)
    async def rpc_task_finished(self, req):
        """Worker reports completion; release resources + lease for reuse."""
        worker = self.workers.get(req["worker_id"])
        if worker is None:
            return {"ok": False}
        spec = worker.current_task
        if spec is not None:
            self._release_for(spec)
        worker.current_task = None
        if worker.state == "busy":
            worker.state = "idle"
            worker.last_idle = time.monotonic()
        await self._dispatch()
        return {"ok": True}

    async def rpc_actor_ready(self, req):
        """Actor finished __init__; keep the worker dedicated but free to serve."""
        worker = self.workers.get(req["worker_id"])
        if worker is not None:
            worker.actor_spec = worker.current_task
            worker.current_task = None
        return {"ok": True}

    async def _reap_loop(self):
        """Monitor worker processes; report deaths (reference: worker failure path)."""
        while True:
            await asyncio.sleep(0.2)
            self._reap_stale_push_sessions()
            for worker in list(self.workers.values()):
                if worker.state == "dead":
                    continue
                if worker.proc is not None and worker.proc.poll() is not None:
                    await self._on_worker_death(
                        worker,
                        "worker killed by the node memory monitor (node memory "
                        "usage exceeded the threshold)"
                        if worker.oom_killed
                        else f"worker process exited with code {worker.proc.returncode}",
                        oom=worker.oom_killed,
                    )
            # Abort unsealed store entries orphaned by a producer killed
            # between create and seal (active push/pull sessions exempt).
            try:
                self.store.reap_orphaned_unsealed(
                    60.0,
                    exclude=set(self._inbound_pushes)
                    | self.pull_manager.inflight_ids(),
                )
            except Exception:
                pass
            # Expire leases whose owner stopped renewing (owner process died
            # without returning them): reclaim the worker via the death path
            # so resource release and owner notification stay in one place.
            now = time.monotonic()
            for lid, lease in list(self._leases.items()):
                if now - lease["renewed"] > self.cfg.worker_lease_timeout_s + 15:
                    worker = self.workers.get(lease["worker_id"])
                    logger.warning("lease %s expired; reclaiming worker", lid[:8])
                    self._leases.pop(lid, None)
                    if worker is not None and worker.proc is not None:
                        worker.proc.kill()
            # Memory pressure: kill a task worker if the node is over the
            # threshold (reference: memory_monitor + worker killing policy).
            if time.monotonic() - self._last_memory_check >= self.cfg.memory_monitor_interval_s:
                self._last_memory_check = time.monotonic()
                try:
                    self._memory_monitor.tick()
                except Exception:
                    logger.debug("memory monitor tick failed", exc_info=True)
            # Scale down long-idle workers beyond the prestart floor.
            now = time.monotonic()
            idle = [w for w in self.workers.values() if w.state == "idle"]
            for w in idle[self.cfg.prestart_workers:] if len(idle) > self.cfg.prestart_workers else []:
                if now - w.last_idle > self.cfg.worker_idle_timeout_s:
                    w.state = "dead"
                    if w.proc is not None:
                        w.proc.terminate()

    async def _on_worker_death(self, worker: WorkerHandle, reason: str, oom: bool = False):
        if worker.state == "dead":
            return
        prev_state = worker.state
        worker.state = "dead"
        spec = worker.current_task
        flight_recorder.record(
            "worker_death", f"{worker.worker_id[:8]}:{reason[:60]}"
        )
        logger.warning("worker %s died: %s", worker.worker_id[:8], reason)
        if worker.actor_spec is not None:
            # Release the actor's lifetime resource hold.
            self._release_for(worker.actor_spec)
            worker.actor_spec = None
        if spec is not None and spec.lease_id:
            # Leased worker: the owner tracks which specs were in flight on
            # it — revoke so it fails them over (lease_manager._lease_failed).
            self._release_for(spec)
            self._leases.pop(spec.lease_id, None)
            if spec.owner_addr:
                try:
                    owner = RpcClient(tuple(spec.owner_addr), label="lease-owner")
                    owner.chaos_scope = self._addr_key
                    await owner.acall(
                        "lease_revoked",
                        {"lease_id": spec.lease_id, "oom": bool(oom), "reason": reason},
                    )
                    owner.close()
                except Exception:
                    pass
        elif spec is not None:
            self._release_for(spec)
            # Tell the owner so it can retry (reference: task_manager.h:335).
            if spec.owner_addr:
                owner = None
                try:
                    owner = RpcClient(tuple(spec.owner_addr), label="owner")
                    owner.chaos_scope = self._addr_key
                    # Per-attempt timeout, retries KEPT (acall retries
                    # TimeoutError/ConnectionLost): losing this notification
                    # hangs the owner's wait() forever, so transient owner
                    # stalls (chaos load on a small box) must be retried —
                    # a single 5s shot dropped deaths and deadlocked the
                    # chaos suite. Total stays bounded (~20s) against the
                    # recycled-port black hole.
                    await owner.acall(
                        "task_failed",
                        {
                            "task_id": spec.task_id,
                            "error": "OutOfMemoryError" if oom else "WorkerCrashedError",
                            "message": reason,
                            "retriable": True,
                        },
                        timeout=5,
                    )
                except Exception:
                    pass
                finally:
                    if owner is not None:
                        owner.close()  # failed-delivery path must not leak
        if prev_state == "actor" and worker.actor_id:
            try:
                await self.gcs.acall(
                    "report_worker_death",
                    {
                        "actor_ids": [worker.actor_id],
                        "reason": reason,
                        "worker_id": worker.worker_id,
                    },
                )
            except Exception:
                pass
        worker.current_task = None
        await self._dispatch()

    # ------------------------------------------------------------------
    # Introspection
    # ------------------------------------------------------------------

    @schema(plan=[dict], seed=[int], broadcast=[bool])
    async def rpc_chaos_set_plan(self, req):
        """Install (plan=null clears) this process's chaos fault plan at
        runtime — tests flip faults mid-workload without restarting
        anything. ``broadcast`` fans the same plan out to every registered
        worker on this node (best-effort: a worker that cannot be reached
        is reported, not fatal). NOTE: in-process clusters share one
        process, so setting a plan \"on a raylet\" sets it for every
        component hosted by that process — the per-process granularity is
        real only across OS processes (workers, process-mode clusters)."""
        from ray_tpu._private import chaos

        plan = req.get("plan")
        seed = req.get("seed")
        local = True
        if plan is None:
            chaos.clear()
        else:
            # kill rules are armed only for STANDALONE raylet processes
            # (exit_on_dead marks raylet main): an in-process raylet shares
            # the driver/test process, and SIGKILLing it would take the
            # whole host down. The SKIP is decided by inspection, not by
            # catching install's ValueError — a malformed plan (unknown
            # kind, bad field) must still error out to the caller instead
            # of reading as ok=True. The broadcast below still arms kill
            # rules in the node's worker processes — the supported
            # crash-fault target.
            has_kill = any(
                r.get("kind") == "kill" for r in (plan.get("rules") or ())
            )
            if has_kill and not self._exit_on_dead:
                local = False
            else:
                chaos.install(plan, seed=seed, allow_kill=self._exit_on_dead)
        reached = failed = 0
        if req.get("broadcast"):
            for w in list(self.workers.values()):
                if w.client is None or w.state in ("starting", "dead"):
                    continue
                try:
                    await w.client.acall(
                        "chaos_set_plan", {"plan": plan, "seed": seed},
                        timeout=5, retries=0,
                    )
                    reached += 1
                except Exception:
                    failed += 1
        return {
            "ok": True,
            "local_install": local,
            "workers_reached": reached,
            "workers_failed": failed,
        }

    async def rpc_debug_dump(self, req):
        """Node-wide flight-recorder dump: every ring in this session's
        flight dir — live processes write through their mmap, and a
        SIGKILLed worker's file still holds its final events, which is the
        whole postmortem story. File scan runs off-loop (it is disk I/O)."""
        loop = asyncio.get_event_loop()
        processes = await loop.run_in_executor(
            None, flight_recorder.collect_dir, self.session_dir
        )
        return {"node_id": self.node_id, "processes": processes}

    async def rpc_get_state(self, req):
        return {
            "node_id": self.node_id,
            "resources_total": self.resources_total,
            "resources_available": self.resources_available,
            "num_workers": self._num_live_workers(),
            "queued_tasks": len(self.task_queue) + len(self._infeasible),
            "store": {**self.store.usage(), "objects": self.store.objects_info()},
            "workers": {
                wid: {"state": w.state, "pid": w.pid, "actor_id": w.actor_id}
                for wid, w in self.workers.items()
            },
        }

    def stop(self):
        if self._stopped:
            return
        self._stopped = True
        self._hb_task.cancel()
        self._reap_task.cancel()
        self._log_monitor_task.cancel()
        self._stats_agent_task.cancel()
        for w in self.workers.values():
            if w.proc is not None and w.proc.poll() is None:
                w.proc.terminate()
        for w in self.workers.values():
            if w.proc is not None:
                try:
                    w.proc.wait(timeout=2)
                except Exception:
                    w.proc.kill()
        if getattr(self, "_zygote", None) is not None:
            self._zygote.close()
        self.server.stop()
        self.gcs.close()
        for c in self._peer_clients.values():
            c.close()
        self.store.close()
        self._sched.close()


def main():
    import argparse

    logging.basicConfig(level=logging.INFO)
    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--resources", default="{}")
    parser.add_argument("--labels", default="{}")
    parser.add_argument("--object-store-memory", type=int, default=0)
    parser.add_argument("--address-file", default="")
    args = parser.parse_args()
    gcs_addr = json.loads(args.gcs_address)
    raylet = Raylet(
        gcs_addr,
        args.session_dir,
        resources=json.loads(args.resources) or None,
        labels=json.loads(args.labels),
        object_store_memory=args.object_store_memory or None,
        # Standalone process: suicide when the GCS writes us off, so the
        # operator/autoscaler replaces the node (the reference's raylet
        # behavior). In-process raylets rejoin instead — see __init__.
        exit_on_dead=True,
    )
    # Standalone raylet: no CoreWorker will ever exist in this process, so
    # point the metrics flusher at our own GCS client (in-process heads use
    # the driver CoreWorker path instead — setting both would double-export
    # the shared registry under two KV keys).
    from ray_tpu.util.metrics import set_fallback_flush_target

    set_fallback_flush_target(raylet.gcs, raylet.node_id, f"raylet-{raylet.node_id[:12]}")
    if args.address_file:
        tmp = args.address_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"address": list(raylet.address), "node_id": raylet.node_id, "arena": raylet.arena_name}, f)
        os.replace(tmp, args.address_file)
    import threading

    threading.Event().wait()


if __name__ == "__main__":
    main()
