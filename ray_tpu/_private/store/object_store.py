"""Per-node shared-memory object store.

TPU-native analog of the reference's Plasma store + object lifecycle manager
(src/ray/object_manager/plasma/store.h:55, eviction_policy.h, and spilling in
src/ray/raylet/local_object_manager.h:110):

- ``StoreCore`` runs inside the raylet (the store daemon): owns allocation
  metadata, seal states, per-object reference counts, LRU eviction and
  disk spilling. All methods are asyncio-native (called from raylet handlers).
- ``StoreClient`` lives in every worker/driver process on the node: it attaches
  the node's shm arena directly (zero-copy data plane) and performs metadata
  operations over the raylet's RPC server (control plane).

Unlike plasma there is no fd-passing: the arena segment has a per-node name.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from dataclasses import dataclass, field

from ray_tpu._private import flight_recorder

logger = logging.getLogger(__name__)


@dataclass
class ObjectEntry:
    object_id: str  # hex
    offset: int | None
    size: int
    sealed: bool = False
    ref_count: int = 0  # client pins (get without release)
    last_access: float = 0.0
    spilled_path: str | None = None
    sealed_event: asyncio.Event = field(default_factory=asyncio.Event)
    created_ts: float = field(default_factory=time.monotonic)


class StoreCore:
    """Daemon-side store state. Single-threaded (asyncio) access."""

    def __init__(self, arena, spill_dir: str, index=None):
        from ray_tpu._private.store.external_storage import create_external_storage

        self.arena = arena
        self.spill_dir = spill_dir
        os.makedirs(spill_dir, exist_ok=True)
        # Pluggable spill target (reference: external_storage.py) — local
        # filesystem by default, remote URI or custom backend via
        # RAY_TPU_OBJECT_SPILLING_CONFIG.
        self.external_storage = create_external_storage(spill_dir)
        self.objects: dict[str, ObjectEntry] = {}
        # Compiled-graph channel rings (experimental/channel/): arena blocks
        # allocated outside the object lifecycle — no seal/evict/spill; held
        # until the owning CompiledDAG's teardown frees them.
        self.channels: dict[str, tuple[int, int]] = {}  # channel_id -> (offset, size)
        # Native shm index: clients resolve local sealed objects without RPC.
        self.index = index
        # Arena blocks whose index slot still has client pins: freed once the
        # readers drain (list of (object_id, offset)).
        self._deferred_frees: list[tuple[str, int]] = []
        from ray_tpu._private import self_metrics

        self._metrics = self_metrics.instruments()

    def _index_remove_then_free(self, object_id: str, offset: int | None):
        """Tombstone the index entry; free the arena block now if no client
        pins it, else defer (drained opportunistically on later calls)."""
        busy = False
        if self.index is not None:
            busy = self.index.remove(object_id) == 1
        if offset is None:
            return
        if busy:
            self._deferred_frees.append((object_id, offset))
        else:
            self.arena.free(offset)

    def drain_deferred_frees(self):
        if not self._deferred_frees or self.index is None:
            return
        still = []
        for object_id, offset in self._deferred_frees:
            if self.index.readers(object_id) == 0:
                self.arena.free(offset)
            else:
                still.append((object_id, offset))
        self._deferred_frees = still

    # ---- creation / sealing ----

    async def create(self, object_id: str, size: int) -> int | None:
        """Allocate space; returns arena offset, or None if the object is
        already sealed here (idempotent create — lineage reconstruction may
        re-execute a task whose output still exists). Evicts/spills if needed.
        """
        if object_id in self.objects:
            entry = self.objects[object_id]
            if entry.sealed:
                return None
            return entry.offset
        self.drain_deferred_frees()
        offset = self.arena.alloc(size)
        if offset is None:
            await self._make_space(size)
            # A concurrent creator (pull racing push is routine) may have
            # inserted the entry during the await — clobbering it would leak
            # its arena block and let OUR empty allocation be sealed by THEIR
            # writer. Defer to the winner: None tells the caller to re-check
            # (sealed -> use it; unsealed -> someone else is filling it).
            if object_id in self.objects:
                return None
            offset = self.arena.alloc(size)
            if offset is None:
                from ray_tpu.exceptions import ObjectStoreFullError

                raise ObjectStoreFullError(
                    f"cannot allocate {size} bytes "
                    f"(used={self.arena.used()}, capacity={self.arena.capacity})"
                )
        self.objects[object_id] = ObjectEntry(
            object_id=object_id, offset=offset, size=size, last_access=time.monotonic()
        )
        if self.index is not None:
            self.index.put(object_id, offset, size)
        return offset

    def seal(self, object_id: str):
        entry = self.objects[object_id]
        entry.sealed = True
        entry.sealed_event.set()
        if self.index is not None:
            self.index.seal(object_id)
        flight_recorder.record("store_seal", f"{object_id[:12]}:{entry.size}")
        try:
            self._metrics["store_seals"].inc()
        except Exception:
            pass

    def abort(self, object_id: str):
        entry = self.objects.pop(object_id, None)
        if entry is not None:
            self._index_remove_then_free(object_id, entry.offset)
            # Wake any get() blocked on the seal; they re-check the table and
            # fail fast instead of waiting out their (possibly infinite)
            # timeout on an entry that will never seal.
            entry.sealed_event.set()

    # ---- channel rings (compiled graphs; experimental/channel/) ----

    async def channel_create(self, channel_id: str, size: int) -> int:
        """Allocate a channel ring from the arena (idempotent per id).
        Channel blocks are never evicted or spilled — they are live SPSC
        rings, not objects — but allocating one may evict/spill objects."""
        existing = self.channels.get(channel_id)
        if existing is not None:
            return existing[0]
        self.drain_deferred_frees()
        offset = self.arena.alloc(size)
        if offset is None:
            await self._make_space(size)
            offset = self.arena.alloc(size)
            if offset is None:
                from ray_tpu.exceptions import ObjectStoreFullError

                raise ObjectStoreFullError(
                    f"cannot allocate {size}-byte channel ring "
                    f"(used={self.arena.used()}, capacity={self.arena.capacity})"
                )
        # Zero the ring header: stale arena bytes must not read as counts.
        self.arena.write(offset, b"\x00" * min(size, 64))
        self.channels[channel_id] = (offset, size)
        return offset

    def channel_free(self, channel_id: str) -> bool:
        """Release a channel ring back to the arena (idempotent)."""
        entry = self.channels.pop(channel_id, None)
        if entry is None:
            return False
        self.arena.free(entry[0])
        return True

    # ---- access ----

    def contains(self, object_id: str) -> bool:
        e = self.objects.get(object_id)
        return e is not None and e.sealed

    async def get(self, object_id: str, timeout: float | None = None) -> tuple[int, int]:
        """Block until sealed; returns (offset, size) and pins the object."""
        entry = self.objects.get(object_id)
        if entry is None:
            raise KeyError(object_id)
        if not entry.sealed:
            await asyncio.wait_for(entry.sealed_event.wait(), timeout)
            if self.objects.get(object_id) is not entry or not entry.sealed:
                # Aborted while we waited (failed push/pull session).
                raise KeyError(object_id)
        if entry.offset is None:
            await self._restore(entry)
        entry.ref_count += 1
        entry.last_access = time.monotonic()
        return entry.offset, entry.size

    def release(self, object_id: str):
        entry = self.objects.get(object_id)
        if entry is not None and entry.ref_count > 0:
            entry.ref_count -= 1

    def delete(self, object_id: str):
        entry = self.objects.pop(object_id, None)
        if entry is None:
            return
        self._index_remove_then_free(object_id, entry.offset)
        if entry.spilled_path:
            # Off the daemon loop: a network backend's delete round trip
            # must not stall concurrent store RPCs (put/get use executors
            # in _spill/_restore for the same reason).
            path = entry.spilled_path

            def _ext_delete():
                try:
                    self.external_storage.delete(path)
                except Exception:
                    pass

            try:
                asyncio.get_running_loop().run_in_executor(None, _ext_delete)
            except RuntimeError:
                _ext_delete()  # no loop (unit tests call delete directly)

    def object_ids(self) -> list[str]:
        return [oid for oid, e in self.objects.items() if e.sealed]

    def reap_orphaned_unsealed(self, max_age_s: float = 60.0, exclude=()) -> int:
        """Abort unsealed entries nobody is filling anymore: a producer
        SIGKILLed between create and seal (memory-monitor kills do exactly
        this) leaves an entry that would otherwise block any re-producer's
        put_serialized forever. Active transfer sessions (caller passes
        their ids in `exclude`) are exempt — big chunked pulls can
        legitimately run long."""
        now = time.monotonic()
        reaped = 0
        for oid, entry in list(self.objects.items()):
            if (
                not entry.sealed
                and oid not in exclude
                and now - entry.created_ts > max_age_s
            ):
                logger.warning("aborting orphaned unsealed object %s", oid[:12])
                self.abort(oid)
                reaped += 1
        return reaped

    def usage(self) -> dict:
        """Summary only — shipped in every raylet heartbeat, so it must stay
        O(1); per-object metadata goes through objects_info()."""
        return {
            "capacity": self.arena.capacity,
            "used": self.arena.used(),
            "num_objects": len(self.objects),
            "num_spilled": sum(1 for e in self.objects.values() if e.spilled_path),
            "num_channels": len(self.channels),
        }

    def objects_info(self) -> dict:
        """Per-object metadata for the state API (list_objects)."""
        return {
            oid: {
                "size": e.size,
                "sealed": e.sealed,
                "ref_count": e.ref_count,
                "spilled": bool(e.spilled_path),
            }
            for oid, e in self.objects.items()
        }

    # ---- eviction / spilling (reference: LocalObjectManager::SpillObjects) ----

    async def _make_space(self, needed: int):
        """Spill-then-evict LRU sealed, unpinned objects until `needed` fits."""
        candidates = sorted(
            (
                e
                for e in self.objects.values()
                if e.sealed and e.ref_count == 0 and e.offset is not None
            ),
            key=lambda e: e.last_access,
        )
        for entry in candidates:
            if self.arena.largest_free() >= needed:
                return
            if self.index is not None and self.index.readers(entry.object_id) > 0:
                continue  # a client is reading it via the index right now
            await self._spill(entry)
            self._index_remove_then_free(entry.object_id, entry.offset)
            entry.offset = None
            flight_recorder.record("store_evict", f"{entry.object_id[:12]}:{entry.size}")
            try:
                self._metrics["store_evictions"].inc()
            except Exception:
                pass

    async def _spill(self, entry: ObjectEntry):
        if entry.spilled_path:
            return
        data = bytes(self.arena.read(entry.offset, entry.size))
        loop = asyncio.get_event_loop()
        entry.spilled_path = await loop.run_in_executor(
            None, self.external_storage.put, entry.object_id, data
        )
        flight_recorder.record("store_spill", f"{entry.object_id[:12]}:{entry.size}")
        try:
            self._metrics["store_spills"].inc()
            self._metrics["store_spilled_bytes"].inc(entry.size)
        except Exception:
            pass
        logger.debug("spilled %s (%d bytes)", entry.object_id, entry.size)

    async def _restore(self, entry: ObjectEntry):
        if entry.spilled_path is None:
            raise KeyError(entry.object_id)
        loop = asyncio.get_event_loop()
        data = await loop.run_in_executor(
            None, self.external_storage.get, entry.spilled_path
        )
        offset = self.arena.alloc(entry.size)
        if offset is None:
            await self._make_space(entry.size)
            offset = self.arena.alloc(entry.size)
            if offset is None:
                from ray_tpu.exceptions import ObjectStoreFullError

                raise ObjectStoreFullError("cannot restore spilled object")
        self.arena.write(offset, data)
        entry.offset = offset
        flight_recorder.record("store_restore", entry.object_id[:12])
        if self.index is not None:
            self.index.put(entry.object_id, offset, entry.size)
            self.index.seal(entry.object_id)

    def close(self):
        if self.index is not None:
            self.index.close(unlink=True)
        self.arena.close(unlink=True)


class StoreClient:
    """Client-side view: direct arena mapping + RPC metadata ops to raylet.

    Local sealed objects resolve through the native shm index (two atomic
    loads + a pin) with no RPC; everything else — unsealed waits, remote
    pulls, spilled restores — falls back to the raylet RPC path."""

    def __init__(self, arena_name: str, raylet_client):
        import threading as _threading

        from ray_tpu._private.store.arena import attach_arena
        from ray_tpu._private.store.index import attach_index

        self.arena = attach_arena(arena_name)
        self.index = attach_index(arena_name + "_idx")
        self.raylet = raylet_client
        # object_id -> stack of pins: ("idx", version) | ("rpc", None)
        self._pins: dict[str, list] = {}
        self._pins_lock = _threading.Lock()

    def put_serialized(self, object_id_hex: str, serialized) -> None:
        """create -> write payload zero-copy into arena -> seal."""
        size = serialized.total_size
        for _ in range(20):  # bounded: the raylet reaps orphaned unsealed
            # entries within ~60s, so a handful of wait+retry rounds always
            # terminates; 20 rounds of 60s wait_seal is pathological.
            resp = self.raylet.call(
                "store_create", {"object_id": object_id_hex, "size": size}
            )
            if resp.get("exists"):
                if resp.get("sealed", True):
                    return  # already sealed here (idempotent reconstruction)
                # An in-flight pull/push session owns the buffer. Wait for it
                # to seal (object materialized -> done) or abort (retry our
                # own create so the result cannot be silently dropped).
                wait = self.raylet.call(
                    "store_wait_seal", {"object_id": object_id_hex}, timeout=60
                )
                if wait.get("sealed"):
                    return
                continue
            break
        else:
            raise RuntimeError(
                f"object {object_id_hex[:12]} stuck unsealed: a rival "
                "session never sealed or aborted within the retry budget"
            )
        offset = resp["offset"]
        try:
            serialized.write_to(self.arena.read(offset, size))
        except BaseException:
            self.raylet.call("store_abort", {"object_id": object_id_hex})
            raise
        self.raylet.call("store_seal", {"object_id": object_id_hex})

    def get_view(self, object_id_hex: str, timeout: float | None = None) -> memoryview:
        """Blocks until sealed locally; returns a zero-copy READ-ONLY view
        (pinned). Read-only is load-bearing: the view aliases the node's
        shared arena, and numpy arrays deserialized zero-copy from it would
        otherwise be writable in place — one caller's mutation would corrupt
        the sealed object for every other reader on the node."""
        if self.index is not None:
            hit = self.index.get_pinned(object_id_hex)
            if hit is not None:
                offset, size, token = hit
                with self._pins_lock:
                    self._pins.setdefault(object_id_hex, []).append(("idx", token))
                return self.arena.read(offset, size).toreadonly()
        resp = self.raylet.call(
            "store_get", {"object_id": object_id_hex, "timeout": timeout}, timeout=timeout
        )
        with self._pins_lock:
            self._pins.setdefault(object_id_hex, []).append(("rpc", None))
        return self.arena.read(resp["offset"], resp["size"]).toreadonly()

    def contains(self, object_id_hex: str) -> bool:
        if self.index is not None:
            hit = self.index.get_pinned(object_id_hex)
            if hit is not None:
                # Probe only: release the pin we just took.
                self.index.release(hit[2])
                return True
            # Miss is authoritative only for sealed-local; spilled objects
            # have no index entry but still "exist" — ask the daemon.
        return self.raylet.call("store_contains", {"object_id": object_id_hex})["found"]

    def release(self, object_id_hex: str):
        with self._pins_lock:
            stack = self._pins.get(object_id_hex)
            pin = stack.pop() if stack else None
            if stack is not None and not stack:
                self._pins.pop(object_id_hex, None)
        if pin is not None and pin[0] == "idx":
            if self.index is not None:
                self.index.release(pin[1])
            return
        try:
            self.raylet.push("store_release", {"object_id": object_id_hex})
        except Exception:
            pass

    def close(self):
        if self.index is not None:
            self.index.close(unlink=False)
        self.arena.close(unlink=False)
