"""ctypes binding for the native shm object index (shm_index.cc).

Daemon (raylet) publishes object states; clients resolve local sealed
objects with atomic loads — no RPC on the local-get fast path. Returns None
from ``create/attach`` when the native library is unavailable; all callers
treat a missing index as "always miss" and use the RPC path.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

logger = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "_native"
)
_SRC = os.path.join(_NATIVE_DIR, "shm_index.cc")
_SO = os.path.join(_NATIVE_DIR, "build", "libshm_index.so")

_lib = None
_lib_lock = threading.Lock()


def _load_lib():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        try:
            os.makedirs(os.path.dirname(_SO), exist_ok=True)
            have_so = os.path.exists(_SO)
            # Rebuild only when the source exists and is newer; a prebuilt
            # .so without the .cc (wheel packaging) is used as-is.
            stale = (
                os.path.exists(_SRC)
                and (not have_so or os.path.getmtime(_SO) < os.path.getmtime(_SRC))
            )
            if stale:
                tmp = _SO + f".tmp{os.getpid()}"
                cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", _SRC, "-o", tmp, "-lrt", "-lpthread"]
                subprocess.run(cmd, check=True, capture_output=True, timeout=120)
                os.replace(tmp, _SO)
            elif not have_so:
                logger.warning("no shm index source or prebuilt library; RPC-only gets")
                return None
        except Exception as e:
            logger.warning("native shm index build failed (%s); RPC-only gets", e)
            return None
        lib = ctypes.CDLL(_SO)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        lib.idx_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.idx_create.restype = ctypes.c_int
        lib.idx_attach.argtypes = [ctypes.c_char_p]
        lib.idx_attach.restype = ctypes.c_int
        lib.idx_put.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64]
        lib.idx_put.restype = ctypes.c_int
        lib.idx_seal.argtypes = [ctypes.c_int, ctypes.c_char_p]
        lib.idx_seal.restype = ctypes.c_int
        lib.idx_remove.argtypes = [ctypes.c_int, ctypes.c_char_p]
        lib.idx_remove.restype = ctypes.c_int
        lib.idx_readers.argtypes = [ctypes.c_int, ctypes.c_char_p]
        lib.idx_readers.restype = ctypes.c_uint32
        lib.idx_get_pinned.argtypes = [ctypes.c_int, ctypes.c_char_p, u64p, u64p, u32p, u64p]
        lib.idx_get_pinned.restype = ctypes.c_int
        lib.idx_release.argtypes = [ctypes.c_int, ctypes.c_uint64, ctypes.c_uint32]
        lib.idx_release.restype = ctypes.c_int
        lib.idx_close.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.idx_close.restype = ctypes.c_int
        _lib = lib
        return _lib


def _key(object_id_hex: str) -> bytes:
    return bytes.fromhex(object_id_hex)


class ShmIndex:
    def __init__(self, lib, handle: int, name: str, owner: bool):
        self._lib = lib
        self._h = handle
        self.name = name
        self.owner = owner
        self._closed = False

    # -- daemon side ----------------------------------------------------
    def put(self, object_id_hex: str, offset: int, size: int) -> bool:
        return self._lib.idx_put(self._h, _key(object_id_hex), offset, size) == 0

    def seal(self, object_id_hex: str) -> bool:
        return self._lib.idx_seal(self._h, _key(object_id_hex)) == 0

    def remove(self, object_id_hex: str) -> int:
        """0 = removed (free now), 1 = busy (defer free), -1 = not found."""
        return self._lib.idx_remove(self._h, _key(object_id_hex))

    def readers(self, object_id_hex: str) -> int:
        return self._lib.idx_readers(self._h, _key(object_id_hex))

    # -- client side ----------------------------------------------------
    def get_pinned(self, object_id_hex: str) -> tuple[int, int, tuple] | None:
        """(offset, size, pin_token) on hit; None on miss. Pass the token to
        ``release`` exactly once."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        ver = ctypes.c_uint32()
        slot = ctypes.c_uint64()
        hit = self._lib.idx_get_pinned(
            self._h,
            _key(object_id_hex),
            ctypes.byref(off),
            ctypes.byref(size),
            ctypes.byref(ver),
            ctypes.byref(slot),
        )
        if not hit:
            return None
        return off.value, size.value, (slot.value, ver.value)

    def release(self, token: tuple):
        slot, version = token
        self._lib.idx_release(self._h, slot, version)

    def close(self, unlink: bool = False):
        if self._closed:
            return
        self._closed = True
        self._lib.idx_close(self._h, 1 if unlink else 0)


def create_index(name: str, nslots: int = 65536) -> ShmIndex | None:
    lib = _load_lib()
    if lib is None:
        return None
    h = lib.idx_create(name.encode(), nslots)
    if h < 0:
        logger.warning("idx_create(%s) failed; RPC-only gets", name)
        return None
    return ShmIndex(lib, h, name, owner=True)


def attach_index(name: str) -> ShmIndex | None:
    lib = _load_lib()
    if lib is None:
        return None
    h = lib.idx_attach(name.encode())
    if h < 0:
        return None
    return ShmIndex(lib, h, name, owner=False)
