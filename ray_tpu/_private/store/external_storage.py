"""Pluggable external storage for object spilling.

Analog of the reference's external storage seam
(python/ray/_private/external_storage.py:246): the store daemon spills
sealed objects through an ``ExternalStorage`` implementation selected by
``RAY_TPU_OBJECT_SPILLING_CONFIG`` (JSON, same shape as the reference's
``object_spilling_config``):

    {"type": "filesystem", "params": {"directory_path": "/tmp/spill"}}
    {"type": "smart_open", "params": {"uri_prefix": "s3://bucket/spill"}}

``filesystem`` is the default and fully supported. ``smart_open`` needs the
smart_open package (network storage) — not in this image, so it raises with
guidance, exactly like the reference without the extra installed. Custom
backends register via ``register_external_storage`` (the plugin seam the
reference exposes by class path).
"""

from __future__ import annotations

import json
import logging
import os
from typing import Callable

logger = logging.getLogger(__name__)


class ExternalStorage:
    """One spilled object = one handle. Implementations must be safe for
    concurrent puts of distinct objects (the daemon serializes per-object)."""

    def put(self, object_id: str, data: bytes) -> str:
        """Persist; returns an opaque handle used for get/delete."""
        raise NotImplementedError

    def get(self, handle: str) -> bytes:
        raise NotImplementedError

    def delete(self, handle: str) -> None:
        raise NotImplementedError


class FileSystemStorage(ExternalStorage):
    """Default: atomic tmp+rename files under a local directory."""

    def __init__(self, directory_path: str):
        self.directory = directory_path
        os.makedirs(directory_path, exist_ok=True)

    def put(self, object_id: str, data: bytes) -> str:
        path = os.path.join(self.directory, object_id)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
        return path

    def get(self, handle: str) -> bytes:
        with open(handle, "rb") as f:
            return f.read()

    def delete(self, handle: str) -> None:
        try:
            os.unlink(handle)
        except OSError:
            pass


class SmartOpenStorage(ExternalStorage):
    """Remote-URI spilling via smart_open (reference:
    external_storage.py:246 ExternalStorageSmartOpenImpl)."""

    def __init__(self, uri_prefix: str, **open_kwargs):
        try:
            from smart_open import open as smart_open_fn  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "object_spilling_config type 'smart_open' requires the "
                "smart_open package (and the relevant cloud SDK); it is not "
                "installed in this image. Use type 'filesystem', or install "
                "smart_open on every node."
            ) from e
        self._open = smart_open_fn
        self.uri_prefix = uri_prefix.rstrip("/")
        self.open_kwargs = open_kwargs

    def put(self, object_id: str, data: bytes) -> str:
        uri = f"{self.uri_prefix}/{object_id}"
        with self._open(uri, "wb", **self.open_kwargs) as f:
            f.write(data)
        return uri

    def get(self, handle: str) -> bytes:
        with self._open(handle, "rb", **self.open_kwargs) as f:
            return f.read()

    def delete(self, handle: str) -> None:
        # smart_open has no uniform delete; best-effort per scheme.
        try:
            if handle.startswith("file://") or os.path.exists(handle):
                os.unlink(handle.replace("file://", ""))
        except OSError:
            pass


_factories: dict[str, Callable[..., ExternalStorage]] = {
    "filesystem": FileSystemStorage,
    "smart_open": SmartOpenStorage,
}


def register_external_storage(type_name: str, factory: Callable[..., ExternalStorage]):
    """Custom backend seam (reference: custom external storage class path)."""
    _factories[type_name] = factory


def create_external_storage(default_dir: str) -> ExternalStorage:
    """Build the configured storage; default = filesystem under the session
    spill dir. ``type`` may also be a dotted class path ("pkg.mod.Class") —
    the process-safe form for store daemons running as separate OS
    processes that never executed a register_external_storage() call
    (reference: custom external storage by class path)."""
    raw = os.environ.get("RAY_TPU_OBJECT_SPILLING_CONFIG", "")
    if not raw:
        return FileSystemStorage(default_dir)
    try:
        cfg = json.loads(raw)
        type_name = cfg.get("type", "filesystem")
        factory = _factories.get(type_name)
        if factory is None and "." in type_name:
            import importlib

            module_name, _, cls_name = type_name.rpartition(".")
            factory = getattr(importlib.import_module(module_name), cls_name)
        if factory is None:
            raise ValueError(
                f"unknown object spilling storage type {type_name!r}; "
                f"registered: {sorted(_factories)} (or use a dotted class path)"
            )
        params = dict(cfg.get("params") or {})
        if type_name == "filesystem":
            params.setdefault("directory_path", default_dir)
        return factory(**params)
    except Exception as e:
        raise ValueError(
            f"invalid RAY_TPU_OBJECT_SPILLING_CONFIG ({raw!r}): {e}"
        ) from e
