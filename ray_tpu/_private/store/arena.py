"""ctypes binding for the native shm arena (ray_tpu/_native/shm_arena.cc).

The native library is built on demand with g++ and cached next to the source.
A pure-Python fallback over ``multiprocessing.shared_memory`` keeps the store
functional if no compiler is available (e.g. stripped containers).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

logger = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "_native")
_SRC = os.path.join(_NATIVE_DIR, "shm_arena.cc")
_SO = os.path.join(_NATIVE_DIR, "build", "libshm_arena.so")

_lib = None
_lib_lock = threading.Lock()


def _build_native() -> str | None:
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return _SO
    tmp = _SO + f".tmp{os.getpid()}"
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", _SRC, "-o", tmp, "-lrt", "-lpthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return _SO
    except Exception as e:
        logger.warning("native shm arena build failed (%s); using Python fallback", e)
        return None


def _load_lib():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        so = _build_native()
        if so is None:
            return None
        lib = ctypes.CDLL(so)
        lib.arena_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.arena_create.restype = ctypes.c_int
        lib.arena_attach.argtypes = [ctypes.c_char_p]
        lib.arena_attach.restype = ctypes.c_int
        lib.arena_capacity.argtypes = [ctypes.c_int]
        lib.arena_capacity.restype = ctypes.c_uint64
        lib.arena_base.argtypes = [ctypes.c_int]
        lib.arena_base.restype = ctypes.c_void_p
        lib.arena_alloc.argtypes = [ctypes.c_int, ctypes.c_uint64]
        lib.arena_alloc.restype = ctypes.c_uint64
        lib.arena_free.argtypes = [ctypes.c_int, ctypes.c_uint64]
        lib.arena_free.restype = ctypes.c_int
        lib.arena_used.argtypes = [ctypes.c_int]
        lib.arena_used.restype = ctypes.c_uint64
        lib.arena_largest_free.argtypes = [ctypes.c_int]
        lib.arena_largest_free.restype = ctypes.c_uint64
        lib.arena_close.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.arena_close.restype = ctypes.c_int
        _lib = lib
        return _lib


UINT64_MAX = (1 << 64) - 1


class NativeArena:
    """Owner-or-attacher view of the node's shared-memory arena."""

    def __init__(self, name: str, capacity: int = 0, create: bool = False):
        lib = _load_lib()
        if lib is None:
            raise RuntimeError("native arena unavailable")
        self._lib = lib
        self.name = name
        if create:
            self.handle = lib.arena_create(name.encode(), capacity)
        else:
            self.handle = lib.arena_attach(name.encode())
        if self.handle < 0:
            raise RuntimeError(f"arena_{'create' if create else 'attach'}({name}) failed")
        self.capacity = lib.arena_capacity(self.handle)
        base = lib.arena_base(self.handle)
        self._buf = (ctypes.c_char * self.capacity).from_address(base)
        self.view = memoryview(self._buf).cast("B")
        self.is_owner = create
        self._closed = False

    def alloc(self, size: int) -> int | None:
        off = self._lib.arena_alloc(self.handle, size)
        return None if off == UINT64_MAX else off

    def free(self, offset: int):
        self._lib.arena_free(self.handle, offset)

    def used(self) -> int:
        return self._lib.arena_used(self.handle)

    def largest_free(self) -> int:
        return self._lib.arena_largest_free(self.handle)

    def read(self, offset: int, size: int) -> memoryview:
        return self.view[offset : offset + size]

    def write(self, offset: int, data) -> None:
        size = len(data)
        self.view[offset : offset + size] = data

    def close(self, unlink: bool = False):
        if self._closed:
            return
        self._closed = True
        view, self.view = self.view, None
        buf, self._buf = self._buf, None
        if view is not None:
            view.release()
        del buf
        self._lib.arena_close(self.handle, 1 if unlink else 0)


class PyArena:
    """Fallback arena over multiprocessing.shared_memory (same interface)."""

    def __init__(self, name: str, capacity: int = 0, create: bool = False):
        from multiprocessing import shared_memory

        if create:
            try:
                shared_memory.SharedMemory(name=name, create=False).unlink()
            except FileNotFoundError:
                pass
            self._shm = shared_memory.SharedMemory(name=name, create=True, size=capacity)
        else:
            self._shm = shared_memory.SharedMemory(name=name, create=False)
        # Keep the segment alive even if the resource tracker complains.
        self.name = name
        self.capacity = self._shm.size
        self.view = self._shm.buf
        self.is_owner = create
        self._free: dict[int, int] = {0: self.capacity}
        self._alloc: dict[int, int] = {}
        self._used = 0
        self._lock = threading.Lock()
        self._closed = False

    def alloc(self, size: int) -> int | None:
        need = (size + 63) & ~63
        with self._lock:
            for off in sorted(self._free):
                blk = self._free[off]
                if blk >= need:
                    del self._free[off]
                    if blk > need:
                        self._free[off + need] = blk - need
                    self._alloc[off] = need
                    self._used += need
                    return off
        return None

    def free(self, offset: int):
        with self._lock:
            size = self._alloc.pop(offset, None)
            if size is None:
                return
            self._used -= size
            self._free[offset] = size
            # coalesce
            offs = sorted(self._free)
            merged: dict[int, int] = {}
            for off in offs:
                sz = self._free[off]
                if merged:
                    last = max(merged)
                    if last + merged[last] == off:
                        merged[last] += sz
                        continue
                merged[off] = sz
            self._free = merged

    def used(self) -> int:
        return self._used

    def largest_free(self) -> int:
        with self._lock:
            return max(self._free.values(), default=0)

    def read(self, offset: int, size: int) -> memoryview:
        return self.view[offset : offset + size]

    def write(self, offset: int, data) -> None:
        self.view[offset : offset + len(data)] = data

    def close(self, unlink: bool = False):
        if self._closed:
            return
        self._closed = True
        self.view = None
        try:
            self._shm.close()
            if unlink:
                self._shm.unlink()
        except Exception:
            pass


def create_arena(name: str, capacity: int):
    try:
        return NativeArena(name, capacity, create=True)
    except Exception:
        return PyArena(name, capacity, create=True)


def attach_arena(name: str):
    try:
        return NativeArena(name, create=False)
    except Exception:
        return PyArena(name, create=False)
