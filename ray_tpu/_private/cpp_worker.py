"""C++ worker runtime build helper.

Compiles ``cpp/ray_tpu_worker.cc`` (the native task executor for
language="cpp" specs — see its header comment for the protocol surface)
on demand with g++ and caches the binary next to the other native
components in ``_native/build/``, the same build-on-first-use scheme as
the shm arena (store/arena.py). Returns None when the toolchain is
unavailable so the raylet can fall back to executing cpp_function tasks
in Python workers (ctypes path in cross_language.py) — behavior is
identical, only the runtime hosting the C ABI call differs.
"""

from __future__ import annotations

import logging
import os
import subprocess
import threading

logger = logging.getLogger(__name__)

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO, "cpp", "ray_tpu_worker.cc")
_BIN = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "_native",
    "build",
    "ray_tpu_cpp_worker",
)

_NATIVE = os.path.join(_REPO, "ray_tpu", "_native")
_ALL_SRCS = [
    _SRC,
    os.path.join(_NATIVE, "shm_arena.cc"),
    os.path.join(_NATIVE, "shm_index.cc"),
]

_lock = threading.Lock()
_result: dict = {}


def _srcs_mtime() -> float:
    return max(os.path.getmtime(p) for p in _ALL_SRCS if os.path.exists(p))


def cpp_worker_binary() -> str | None:
    """Path to the compiled worker binary, building it if needed (BLOCKS
    for the g++ run on first use — do not call from an event loop)."""
    with _lock:
        if "path" in _result:
            return _result["path"]
        path = _build()
        _result["path"] = path
        return path


def cpp_worker_binary_nowait() -> str | None:
    """Non-blocking variant for the raylet's dispatch loop: returns the
    binary path if it is already built, else kicks off a background build
    and returns None (the caller falls back to a Python worker for this
    spawn; later spawns find the binary)."""
    if (
        os.path.exists(_BIN)
        and os.path.exists(_SRC)
        and os.path.getmtime(_BIN) >= _srcs_mtime()
    ):
        return _BIN
    with _lock:
        if "path" in _result:
            return _result["path"]
        if "bg" not in _result:
            _result["bg"] = threading.Thread(target=cpp_worker_binary, daemon=True)
            _result["bg"].start()
    return None


def _build() -> str | None:
    if not os.path.exists(_SRC):
        return None
    os.makedirs(os.path.dirname(_BIN), exist_ok=True)
    if os.path.exists(_BIN) and os.path.getmtime(_BIN) >= _srcs_mtime():
        return _BIN
    tmp = _BIN + f".tmp{os.getpid()}"
    cmd = ["g++", "-O2", "-std=c++17", "-o", tmp, _SRC] + _ALL_SRCS[1:] + ["-ldl"]
    try:
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=180)
        except subprocess.CalledProcessError:
            # glibc < 2.34 keeps shm_open/shm_unlink in librt; retry with it.
            subprocess.run(cmd + ["-lrt"], check=True, capture_output=True, timeout=180)
        os.replace(tmp, _BIN)
        return _BIN
    except Exception as e:
        logger.warning(
            "C++ worker build failed (%s); cpp tasks will run in Python workers", e
        )
        return None
