"""Binary IDs.

TPU-native analog of the reference's typed ID system
(src/ray/common/id.h, spec in src/ray/design_docs/id_specification.md):
JobID(4B) < ActorID(16B) = JobID + unique; TaskID(24B) = ActorID + unique;
ObjectID(28B) = TaskID + 4B index. IDs embed their lineage so ownership and
the producing task are recoverable from the object id alone.
"""

from __future__ import annotations

import os
import struct
import threading

JOB_ID_SIZE = 4
UNIQUE_ID_SIZE = 12
ACTOR_ID_SIZE = JOB_ID_SIZE + UNIQUE_ID_SIZE  # 16
TASK_ID_SIZE = ACTOR_ID_SIZE + 8  # 24
OBJECT_ID_SIZE = TASK_ID_SIZE + 4  # 28
NODE_ID_SIZE = 16
WORKER_ID_SIZE = 16
PLACEMENT_GROUP_ID_SIZE = 16


class BaseID:
    SIZE = 0
    __slots__ = ("_bytes", "_hash")

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(binary)}"
            )
        self._bytes = binary
        self._hash = hash((type(self).__name__, binary))

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\xff" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\xff" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"


class JobID(BaseID):
    SIZE = JOB_ID_SIZE
    _counter = 0
    _lock = threading.Lock()

    @classmethod
    def from_int(cls, value: int) -> "JobID":
        return cls(struct.pack(">I", value))

    @classmethod
    def next(cls) -> "JobID":
        with cls._lock:
            cls._counter += 1
            return cls.from_int(cls._counter)


class NodeID(BaseID):
    SIZE = NODE_ID_SIZE


class WorkerID(BaseID):
    SIZE = WORKER_ID_SIZE


class PlacementGroupID(BaseID):
    SIZE = PLACEMENT_GROUP_ID_SIZE


class ActorID(BaseID):
    SIZE = ACTOR_ID_SIZE

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(job_id.binary() + os.urandom(UNIQUE_ID_SIZE))

    def job_id(self) -> JobID:
        return JobID(self._bytes[:JOB_ID_SIZE])


class TaskID(BaseID):
    SIZE = TASK_ID_SIZE

    @classmethod
    def for_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(actor_id.binary() + os.urandom(8))

    @classmethod
    def for_driver(cls, job_id: JobID) -> "TaskID":
        return cls(ActorID(job_id.binary() + b"\x00" * UNIQUE_ID_SIZE).binary() + b"\x00" * 8)

    def actor_id(self) -> ActorID:
        return ActorID(self._bytes[:ACTOR_ID_SIZE])

    def job_id(self) -> JobID:
        return JobID(self._bytes[:JOB_ID_SIZE])


class ObjectID(BaseID):
    SIZE = OBJECT_ID_SIZE

    @classmethod
    def for_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + struct.pack(">I", index))

    @classmethod
    def for_put(cls, task_id: TaskID) -> "ObjectID":
        # Puts get a random index with the high bit set to avoid colliding
        # with return-value indices.
        idx = int.from_bytes(os.urandom(4), "big") | 0x8000_0000
        return cls(task_id.binary() + struct.pack(">I", idx))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:TASK_ID_SIZE])

    def return_index(self) -> int:
        return struct.unpack(">I", self._bytes[TASK_ID_SIZE:])[0]


class BoundedIdSet:
    """Insertion-ordered bounded set of id strings (cancel tombstones —
    reference: CoreWorker's cancelled-task bookkeeping in CancelTask).
    O(1) membership; evicts oldest-first past ``cap``. The trim walks an
    unbounded order deque on purpose: a maxlen deque would silently drop
    the true oldest id on append (stranding it in the set forever) while
    a manual pop then discarded a newer, still-needed entry."""

    def __init__(self, cap: int = 4096):
        import collections

        self._cap = cap
        self._set: set = set()
        self._order = collections.deque()

    def add(self, item) -> None:
        if item in self._set:
            return
        self._set.add(item)
        self._order.append(item)
        while len(self._order) > self._cap:
            self._set.discard(self._order.popleft())

    def discard(self, item) -> None:
        self._set.discard(item)

    def __contains__(self, item) -> bool:
        return item in self._set

    def __len__(self) -> int:
        return len(self._set)
