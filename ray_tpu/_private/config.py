"""Config/flag registry.

TPU-native analog of the reference's ``RAY_CONFIG`` macro registry
(src/ray/common/ray_config_def.h:22, materialised in ray_config.h:60): a single
source of truth for runtime tunables, each overridable per-process via a
``RAY_TPU_<NAME>`` environment variable and cluster-wide via the ``_system_config``
dict handed to ``ray_tpu.init``.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field, fields
from typing import Any

_ENV_PREFIX = "RAY_TPU_"


def _coerce(value: str, typ: type) -> Any:
    if typ is bool:
        return value.lower() in ("1", "true", "yes", "on")
    if typ is int:
        return int(value)
    if typ is float:
        return float(value)
    if typ in (dict, list):
        return json.loads(value)
    return value


@dataclass
class Config:
    """All runtime tunables. Defaults match single-host development use."""

    # --- object store ---
    object_store_memory: int = 512 * 1024 * 1024  # arena capacity per node
    object_store_min_alloc: int = 64  # smallest arena block
    # objects <= this many bytes live in the owner's in-process store and are
    # shipped inline in RPCs (reference: 100KB in-process memory store cutoff).
    max_direct_call_object_size: int = 100 * 1024
    object_transfer_chunk_bytes: int = 4 * 1024 * 1024
    object_spill_dir: str = ""  # empty -> <session_dir>/spill
    object_spill_threshold: float = 0.8  # arena fullness ratio triggering spill
    # push-side transfer (reference: push_manager.h in-flight caps,
    # pull_manager.h admission control)
    push_pipeline_depth: int = 4        # concurrent chunk RPCs per push
    push_max_concurrent_per_dest: int = 2
    push_max_inbound: int = 8           # receiver-side concurrent push sessions
    push_admission_retries: int = 50    # sender retries while receiver is saturated
    # pull-side transfer (pull_manager.py; reference: pull_manager.h:52)
    pull_pipeline_depth: int = 4        # concurrent chunk RPCs per pull, per source
    pull_max_sources: int = 4           # replicas a single pull stripes across
    # Aggregate byte cap across concurrent inbound pulls on a node: past it,
    # new pulls queue (admission_stall flight event) instead of over-
    # committing the arena. A pull larger than the whole budget still admits
    # alone. 0 = unbounded (the pre-PR-10 behavior).
    pull_admission_budget_bytes: int = 256 * 1024 * 1024
    # Raw-frame wire path for chunk transfer (rpc.py RAW_*): headers+payload
    # straight from/into the arena, no msgpack encode of multi-MiB bytes.
    # Negotiated per session; disabling forces the msgpack fallback
    # everywhere (A/B lever for microbench --transfer).
    transfer_raw_frames: bool = True

    # --- scheduling / raylet ---
    worker_lease_timeout_s: float = 30.0
    # Direct task transport (lease_manager.py): owners lease workers and ship
    # normal tasks straight to them, bypassing per-task raylet round trips
    # (reference: direct_task_transport.cc lease pipelining).
    direct_task_leases: bool = True
    lease_max_inflight: int = 32   # specs in flight per leased worker
    lease_max_per_shape: int = 8   # concurrent leases per (env, resources)
    lease_idle_release_s: float = 0.5  # linger before returning an idle lease
    worker_idle_timeout_s: float = 300.0  # idle workers kept warm for reuse
    # Lost-task sweep (core_worker._sweep_lost_tasks): raylet-path specs can
    # die WITH a spilled-to node; owners locate aged pending tasks across
    # alive raylets and resubmit ones held by nobody.
    lost_task_sweep_interval_s: float = 15.0
    lost_task_age_s: float = 30.0
    max_workers_per_node: int = 64
    worker_startup_timeout_s: float = 60.0
    scheduler_spread_threshold: float = 0.5  # hybrid policy pack->spread knob
    prestart_workers: int = 0
    # Fork-server worker spawn (zygote.py): turns per-worker interpreter boot
    # (~200ms of CPU) into a few-ms fork. Auto-disabled on nodes holding a
    # TPU resource (forking after a TPU-plugin dial is unsafe).
    worker_zygote_enabled: bool = True

    # --- scheduling: data locality (reference: the Ray paper's
    # data-locality-aware placement claim; locality_aware_scheduling in
    # scheduling_policy.h) ---
    # Prefer nodes already holding a task's reference (plasma-sized) args —
    # inline args are below max_direct_call_object_size by construction, so
    # reference args ARE the large ones. Off = the measured no-locality
    # baseline arm.
    locality_aware_scheduling: bool = True
    # Raylet-side object-location cache for locality lookups (bounded, TTL):
    # one GCS round trip per arg per TTL window, not per task.
    locality_cache_ttl_s: float = 3.0
    # At most this many reference args consulted per task.
    locality_max_args: int = 8

    # --- health / failure detection ---
    heartbeat_interval_s: float = 0.5
    node_death_timeout_s: float = 5.0
    health_check_failure_threshold: int = 5
    # Versioned delta cluster-view sync on heartbeat replies: raylets send
    # their last seen view version and receive only changed rows + removal
    # tombstones (full O(N) view only on resync). Off = legacy full-view
    # replies — the measured "before" arm for the scale bench.
    heartbeat_delta_sync: bool = True
    # Jittered exponential backoff before a raylet re-registers in _rejoin:
    # a GCS restart or mass partition-heal otherwise makes every raylet
    # re-register in the same heartbeat interval (thundering herd).
    rejoin_backoff_base_s: float = 0.05
    rejoin_backoff_max_s: float = 2.0

    # --- GCS fan-in hardening ---
    # Per-node reverse index over object locations: node death touches only
    # that node's rows instead of scanning the whole directory. Off = legacy
    # full scan (bench baseline arm).
    gcs_location_index: bool = True

    # After a GCS restart, wait this long for in-flight actor creations on
    # surviving raylets to land before re-driving PENDING creations.
    gcs_actor_recovery_grace_s: float = 2.0

    # --- memory monitor (reference: memory_monitor.py:94 + raylet worker
    # killing policies worker_killing_policy*.h) ---
    memory_monitor_enabled: bool = True
    # Node memory fraction above which the raylet kills a task worker to
    # relieve pressure; the killed task retries elsewhere/later.
    memory_usage_threshold: float = 0.95
    memory_monitor_interval_s: float = 1.0

    # --- RPC ---
    rpc_connect_timeout_s: float = 10.0
    rpc_retries: int = 3
    # acall retry pacing: capped exponential backoff (base * 2^(attempt-1),
    # capped at max) with a [0.5, 1.0) jitter factor — a partitioned or
    # recovering peer is probed at a decaying, decorrelated rate instead of
    # the old fixed-pause hammering. retries=0 callers are unaffected.
    rpc_retry_backoff_base_ms: float = 100.0
    rpc_retry_backoff_max_ms: float = 2000.0
    # Bounded wait for the ack of a one-way completion report
    # (task_done/tasks_done send_nowait frames): a silently lost frame —
    # receiver dropped it, or chaos did — re-delivers through the acked
    # retrying path (owner dedupes by cid) instead of hanging the owner's
    # get() until the lost-task sweep (or forever, on the lease path).
    task_done_ack_timeout_s: float = 10.0

    # --- chaos fault-injection plane (chaos.py; see CHAOS.md) ---
    # JSON fault-plan spec installed at process boot (workers inherit the
    # env var); empty = disabled. The per-frame cost when disabled is one
    # is-None check at the rpc seam. Env: RAY_TPU_CHAOS_PLAN /
    # RAY_TPU_CHAOS_SEED (also seeds acall backoff jitter).
    chaos_plan: str = ""

    # --- tasks / actors ---
    default_max_retries: int = 3
    default_actor_max_restarts: int = 0
    actor_call_queue_depth: int = 10_000
    # Calls to an actor still being created buffer this long (creation =
    # worker spawn + user __init__, slow under load) before giving up.
    actor_creation_timeout_s: float = 180.0

    # --- hop-level dispatch instrumentation ---
    # When on, every task submission carries monotonic per-hop timestamps
    # (owner submit -> ship -> [raylet] -> worker recv -> exec -> reply ->
    # owner recv -> future wake) in the existing msgpack frames; the owner
    # aggregates them into a per-hop latency budget (util/tracing.py
    # summarize_hop_records, microbench.py --hop-budget). Off by default:
    # the stamps are cheap but non-zero on the 1k+/s dispatch hot path.
    hop_timing: bool = False
    # Always-on production sampling: 1-in-N submissions carry hop stamps even
    # with hop_timing off, feeding the ray_tpu_dispatch_latency_s histogram
    # (self_metrics.py) and `ray_tpu timeline` flow spans at ~1/N of the
    # full-tracing cost. 0 disables sampling. Env: RAY_TPU_HOP_SAMPLE_N.
    hop_sample_n: int = 64

    # --- flight recorder (always-on observability; flight_recorder.py) ---
    # Ring capacity in events per process. The ring is mmap-backed under
    # <session_dir>/flight/ so a SIGKILLed process's final events survive
    # for `ray_tpu debug dump`. Disable with RAY_TPU_FLIGHT_RECORDER=0.
    flight_ring_slots: int = 4096

    # --- logging / events ---
    log_to_driver: bool = True
    event_stats: bool = True
    task_events_buffer_size: int = 10_000
    task_events_enabled: bool = True
    task_events_flush_interval_s: float = 1.0

    # --- metrics ---
    metrics_flush_interval_s: float = 5.0

    # --- compiled-graph channel plane (experimental/channel/) ---
    # Blocked channel readers are woken by the producer's doorbell frame;
    # this is the FALLBACK re-poll cap for a lost doorbell. Readers back off
    # exponentially from a few ms up to this cap while idle, so resident
    # loops waiting on descriptor resolution don't burn a busy 1-CPU box,
    # and a doorbell always wakes them immediately regardless of the cap.
    # Env: RAY_TPU_CHANNEL_POLL_INTERVAL_MS.
    channel_poll_interval_ms: int = 50

    # --- collectives ---
    collective_rendezvous_timeout_s: float = 60.0

    # --- device object plane (experimental/device_object/) ---
    # Per-process ceiling on device-resident object bytes; past it the
    # holder spills LRU arrays device->host into the shm arena (restored on
    # the next local resolve). 0 = no ceiling. Env: RAY_TPU_DEVOBJ_RESIDENT_LIMIT_BYTES.
    devobj_resident_limit_bytes: int = 0

    # --- GCS durability ---
    # WAL sync policy: "0" = flush only (page cache: survives process kill),
    # "1" = fsync per mutation (survives host crash, slowest), "everysec" =
    # batched fdatasync at most once per second (redis appendfsync-everysec
    # class: bounded ~1s loss window on host crash). Env: RAY_TPU_WAL_FSYNC.
    wal_fsync: str = "everysec"

    # --- misc ---
    session_dir_root: str = "/tmp/ray_tpu"

    def apply_overrides(self, system_config: dict | None = None) -> None:
        """Env vars take precedence over _system_config, which beats defaults."""
        if system_config:
            for key, value in system_config.items():
                if not hasattr(self, key):
                    raise ValueError(f"Unknown system config key: {key}")
                setattr(self, key, value)
        for f in fields(self):
            env = os.environ.get(_ENV_PREFIX + f.name.upper())
            if env is not None:
                setattr(self, f.name, _coerce(env, f.type if isinstance(f.type, type) else type(getattr(self, f.name))))

    def to_dict(self) -> dict:
        return {f.name: getattr(self, f.name) for f in fields(self)}


_config_lock = threading.Lock()
_config: Config | None = None


def get_config() -> Config:
    global _config
    with _config_lock:
        if _config is None:
            _config = Config()
            _config.apply_overrides()
        return _config


def init_config(system_config: dict | None = None) -> Config:
    global _config
    with _config_lock:
        _config = Config()
        _config.apply_overrides(system_config)
        return _config
