"""Multi-raylet single-host test cluster.

Analog of the reference's cluster_utils.Cluster (python/ray/cluster_utils.py:99,
add_node :165, remove_node :238): additional raylets on the same host, each
pretending to be a distinct node (own resources, own shm arena, shared GCS) —
the key multi-node-without-a-cluster trick the reference's failure tests rely
on. ``remove_node`` simulates node death for chaos tests.
"""

from __future__ import annotations

import os
import time

from ray_tpu._private.config import init_config
from ray_tpu._private.gcs import GcsServer
from ray_tpu._private.raylet import Raylet


class Cluster:
    def __init__(self, _system_config: dict | None = None):
        init_config(_system_config)
        self.gcs = GcsServer()
        self.session_dir = os.path.join("/tmp/ray_tpu", f"cluster_{os.getpid()}_{int(time.time())}")
        self.nodes: list[Raylet] = []
        self._connected = False
        # node_id -> (membrane id, workers severed) for partition_node.
        self._partitions: dict[str, tuple] = {}

    @property
    def gcs_address(self):
        return self.gcs.address

    def add_node(
        self,
        num_cpus: int = 1,
        num_tpus: int = 0,
        resources: dict | None = None,
        labels: dict | None = None,
        object_store_memory: int = 64 * 1024 * 1024,
    ) -> Raylet:
        node_resources = dict(resources or {})
        node_resources.setdefault("CPU", num_cpus)
        if num_tpus:
            node_resources.setdefault("TPU", num_tpus)
        raylet = Raylet(
            self.gcs.address,
            self.session_dir,
            resources=node_resources,
            labels=labels,
            object_store_memory=object_store_memory,
        )
        self.nodes.append(raylet)
        return raylet

    def connect(self, namespace: str = ""):
        """Attach the current process as a driver to the first node."""
        from ray_tpu._private import worker_context
        from ray_tpu._private.core_worker import DRIVER, CoreWorker

        assert self.nodes, "add_node() first"
        head = self.nodes[0]
        cw = CoreWorker(
            mode=DRIVER,
            gcs_address=self.gcs.address,
            raylet_address=head.address,
            arena_name=head.arena_name,
            node_id=head.node_id,
            session_dir=self.session_dir,
            namespace=namespace,
        )
        worker_context.set_core_worker(cw)
        self._connected = True
        return cw

    def remove_node(self, raylet: Raylet):
        """Simulate node death (reference: Cluster.remove_node for chaos tests)."""
        self.nodes.remove(raylet)
        raylet.stop()

    # ------------------------------------------------------------------
    # Crash faults (ISSUE 14): SIGKILL a process by ROLE. The in-process
    # raylets/GCS share the test process and cannot be SIGKILLed; worker
    # processes (plain workers, actors, serve replicas/proxies) are real
    # OS processes and can. The killer side stamps a ``chaos_kill`` flight
    # event so the injection shows up in the node postmortem exactly like
    # a plan-driven self-kill.
    # ------------------------------------------------------------------

    def _live_workers(self, raylet: Raylet | None = None):
        nodes = [raylet] if raylet is not None else self.nodes
        out = []
        for n in nodes:
            for w in n.workers.values():
                if not w.pid or w.state in ("starting", "dead"):
                    continue
                try:
                    # The raylet's monitor lags a SIGKILL by a poll tick;
                    # probe the pid so an already-dead worker (a previous
                    # cell's victim) is never picked again.
                    os.kill(w.pid, 0)
                except (ProcessLookupError, PermissionError):
                    continue
                out.append((n, w))
        return out

    def find_actor_worker(self, actor_name: str):
        """(raylet, WorkerHandle) hosting the named actor, or None. The
        GCS name registry maps name -> actor_id; raylets stamp actor_id on
        the worker the creation task landed in."""
        actor_id = next(
            (
                aid
                for (_ns, name), aid in self.gcs.named_actors.items()
                if name == actor_name
            ),
            None,
        )
        if actor_id is None:
            return None
        for n, w in self._live_workers():
            if w.actor_id == actor_id:
                return n, w
        return None

    def kill_role(self, role: str, raylet: Raylet | None = None, index: int = 0) -> int:
        """SIGKILL one process by role; returns the pid killed.

        - ``"worker"``: the ``index``-th live worker process (of ``raylet``
          when given, else cluster-wide, in node order).
        - ``"actor:<name>"``: the worker process hosting the named actor —
          serve replicas (``SERVE_REPLICA::<deployment>#<id>``) and proxies
          are actors, so this is the replica/proxy crash lever.
        """
        import signal

        from ray_tpu._private import chaos, flight_recorder

        if role.startswith("actor:"):
            found = self.find_actor_worker(role[6:])
            if found is None:
                raise ValueError(f"no live worker hosts actor {role[6:]!r}")
            _, w = found
        else:
            if role != "worker":
                raise ValueError(f"unknown role {role!r} (worker | actor:<name>)")
            workers = self._live_workers(raylet)
            if not workers:
                raise ValueError("no live worker processes to kill")
            _, w = workers[index % len(workers)]
        flight_recorder.record("chaos_kill", f"{role[:24]}:pid{w.pid}")
        chaos.CHAOS_STATS.injected += 1
        chaos.CHAOS_STATS.kills += 1
        os.kill(w.pid, signal.SIGKILL)
        return w.pid

    def install_plan_in_actor(
        self, actor_name: str, plan: dict | None, seed: int | None = None
    ) -> bool:
        """Push a chaos plan (None clears) into the worker PROCESS hosting
        the named actor — the seeded-kill lever for serve replicas: a
        ``kill`` rule on e.g. ``("next_stream_chunk", side="resp")`` makes
        the replica SIGKILL itself at the Nth streamed chunk."""
        from ray_tpu._private.rpc import EventLoopThread

        found = self.find_actor_worker(actor_name)
        if found is None or found[1].client is None:
            return False
        io = EventLoopThread.get()
        io.run(
            found[1].client.acall(
                "chaos_set_plan", {"plan": plan, "seed": seed},
                timeout=5, retries=0,
            ),
            timeout=6,
        )
        return True

    def partition_node(self, raylet: Raylet, include_workers: bool = True):
        """In-process NETWORK TEAR: sever `raylet` from the rest of the
        cluster WITHOUT killing it (ROADMAP item 5's missing chaos lever —
        remove_node models death, this models a switch losing a port).

        Built on the chaos plane's membrane partition (chaos.py): the
        membrane's inside set is the node's endpoints (raylet + its
        registered workers), and any link crossing it fails with
        ConnectionLost — while node-LOCAL links (raylet <-> its own
        workers) stay up, like a real rack partition. Worker processes get
        their own membrane plan pushed first (they are separate OS
        processes; a plan here cannot see their sockets), with
        local_inside=True since they sit inside the membrane.

        Heal with heal_node() and the node rejoins: heartbeats resume, and
        if the partition outlived node_death_timeout_s the raylet
        re-registers + republishes its object locations (actors the GCS
        declared dead stay dead, per node-death semantics)."""
        from ray_tpu._private import chaos, rpc
        from ray_tpu._private.rpc import EventLoopThread

        inside = [rpc.addr_key(raylet.address)]
        workers = [
            w for w in raylet.workers.values()
            if w.address is not None and w.client is not None
            and w.state not in ("starting", "dead")
        ]
        inside += [rpc.addr_key(w.address) for w in workers]
        worker_plan = {
            "rules": [{"kind": "partition", "inside": inside, "local_inside": True}]
        }
        if include_workers:
            # Push the workers' plans BEFORE severing the driver side —
            # afterwards they are unreachable by construction.
            io = EventLoopThread.get()
            for w in workers:
                try:
                    io.run(
                        w.client.acall(
                            "chaos_set_plan", {"plan": worker_plan},
                            timeout=5, retries=0,
                        ),
                        timeout=6,
                    )
                except Exception:
                    pass  # a wedged worker is already chaos
        plan = chaos.ensure_plan()
        mid = plan.add_membrane(inside, local_inside=False)
        self._partitions[raylet.node_id] = (mid, workers)
        return mid

    def heal_node(self, raylet: Raylet):
        """Reverse partition_node: drop the membrane and clear the node's
        worker plans (reachable again). The raylet rejoins on its next
        heartbeat (or re-registers if it was declared dead meanwhile)."""
        from ray_tpu._private import chaos
        from ray_tpu._private.rpc import EventLoopThread

        entry = self._partitions.pop(raylet.node_id, None)
        if entry is None:
            return
        mid, workers = entry
        plan = chaos.active()
        if plan is not None:
            plan.remove_membrane(mid)
        io = EventLoopThread.get()
        for w in workers:
            try:
                io.run(
                    w.client.acall("chaos_set_plan", {"plan": None}, timeout=5, retries=0),
                    timeout=6,
                )
            except Exception:
                pass

    def restart_gcs(self) -> GcsServer:
        """Stop the GCS and bring a fresh one up on the SAME address (no
        persistence: the node table is gone). Every raylet's next heartbeat
        returns ``unknown`` and it re-registers with jittered backoff,
        republishing its object locations — the rejoin-storm path."""
        host, port = self.gcs.address
        self.gcs.stop()
        deadline = time.monotonic() + 10
        while True:
            try:
                self.gcs = GcsServer(host, port)
                return self.gcs
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.1)

    def wait_for_nodes(self, timeout: float = 10.0):
        deadline = time.monotonic() + timeout
        want = len(self.nodes)
        while time.monotonic() < deadline:
            from ray_tpu._private.rpc import EventLoopThread

            alive = sum(
                1 for n in self.gcs.nodes.values() if n["state"] == "ALIVE"
            )
            if alive >= want:
                return
            time.sleep(0.05)
        raise TimeoutError("cluster nodes did not come up")

    def shutdown(self):
        from ray_tpu._private import chaos, worker_context

        # A lingering fault plan (a test that partitioned and never healed)
        # must not outlive its cluster into the next test's traffic.
        if self._partitions or chaos.active() is not None:
            self._partitions.clear()
            chaos.clear()

        if self._connected:
            cw = worker_context.get_core_worker_if_initialized()
            if cw is not None:
                cw.shutdown()
                worker_context.set_core_worker(None)
        for raylet in self.nodes:
            raylet.stop()
        self.nodes.clear()
        self.gcs.stop()
