"""Attention kernels.

The hot op of every transformer in models/: a Pallas TPU flash-attention
kernel (blockwise online-softmax, VMEM-resident accumulators, MXU-shaped
tiles) with a pure-XLA fallback for CPU/debug.

The reference has no attention kernels at all (it delegates model math to
torch; SURVEY.md §5.7) — this module is where the TPU-native build spends the
FLOPs the reference hands to external frameworks.

Design notes (per /opt/skills/guides/pallas_guide.md):
- grid = (batch*heads, q_blocks); the k-loop runs inside the kernel as a
  fori_loop so the running max/denominator stay in VMEM scratch.
- block sizes default to (128, 128): MXU-shaped, and multiples of the
  (8,128)/f32, (16,128)/bf16 tile constraints.
- causal masking prunes fully-masked k-blocks via the loop upper bound
  (no wasted MXU work past the diagonal).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp


def _xla_attention(q, k, v, causal: bool, sm_scale: float, bias=None):
    """Reference implementation (XLA fuses this fine on CPU; used for
    correctness tests and non-TPU fallback)."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * sm_scale
    if bias is not None:
        logits = logits + bias
    if causal:
        mask = jnp.tril(jnp.ones((Tq, Tk), dtype=bool), k=Tk - Tq)
        logits = jnp.where(mask[None, None], logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool, sm_scale: float, seq_k: int, block_q: int):
    from jax.experimental import pallas as pl

    q = q_ref[...]  # [block_q, d]
    q_idx = pl.program_id(1)
    d = q.shape[-1]

    m0 = jnp.full((q.shape[0],), -jnp.inf, dtype=jnp.float32)
    l0 = jnp.zeros((q.shape[0],), dtype=jnp.float32)
    acc0 = jnp.zeros((q.shape[0], d), dtype=jnp.float32)

    num_k_blocks = pl.cdiv(seq_k, block_k)
    if causal:
        # K blocks strictly after this Q block's last row are fully masked.
        last_q_row = (q_idx + 1) * block_q - 1
        num_k_blocks = jnp.minimum(num_k_blocks, (last_q_row // block_k) + 1)

    def body(kb, carry):
        m_prev, l_prev, acc_prev = carry
        k_blk = k_ref[pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # [block_q, block_k]
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        m_cur = jnp.maximum(m_prev, s.max(axis=-1))
        correction = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur[:, None])
        l_cur = l_prev * correction + p.sum(axis=-1)
        pv = jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_cur = acc_prev * correction[:, None] + pv
        return m_cur, l_cur, acc_cur

    m, l, acc = jax.lax.fori_loop(0, num_k_blocks, body, (m0, l0, acc0))
    o_ref[...] = (acc / l[:, None]).astype(o_ref.dtype)


def _pallas_flash(q, k, v, causal: bool, sm_scale: float, block_q: int, block_k: int, interpret: bool):
    from jax.experimental import pallas as pl

    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    # Fold batch and heads into the grid's first axis; layout [BH, T, D].
    qf = q.transpose(0, 2, 1, 3).reshape(B * H, Tq, D)
    kf = k.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)

    grid = (B * H, pl.cdiv(Tq, block_q))
    kernel = functools.partial(
        _flash_kernel,
        block_k=block_k,
        causal=causal,
        sm_scale=sm_scale,
        seq_k=Tk,
        block_q=block_q,
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda bh, qb: (bh, qb, 0)),
            pl.BlockSpec((None, Tk, D), lambda bh, qb: (bh, 0, 0)),
            pl.BlockSpec((None, Tk, D), lambda bh, qb: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda bh, qb: (bh, qb, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(B, H, Tq, D).transpose(0, 2, 1, 3)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = False,
    sm_scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    bias=None,
    force_pallas: bool | None = None,
    interpret: bool = False,
):
    """Multi-head attention, [B, T, H, D] layout.

    Pallas on TPU; XLA reference elsewhere (or with a bias, which the kernel
    does not support yet).
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    use_pallas = force_pallas if force_pallas is not None else (_on_tpu() or interpret)
    if bias is not None or not use_pallas:
        return _xla_attention(q, k, v, causal, sm_scale, bias)
    Tq, Tk = q.shape[1], k.shape[1]
    bq = min(block_q, Tq)
    bk = min(block_k, Tk)
    return _pallas_flash(q, k, v, causal, sm_scale, bq, bk, interpret)
