"""Attention kernels.

The hot op of every transformer in models/: a Pallas TPU flash-attention
kernel (blockwise online-softmax, VMEM-resident accumulators, MXU-shaped
tiles) with a pure-XLA fallback for CPU/debug.

The reference has no attention kernels at all (it delegates model math to
torch; SURVEY.md §5.7) — this module is where the TPU-native build spends the
FLOPs the reference hands to external frameworks.

Design notes (per /opt/skills/guides/pallas_guide.md):
- grid = (batch*heads, q_blocks); the k-loop runs inside the kernel as a
  fori_loop so the running max/denominator stay in VMEM scratch.
- block sizes default to (128, 128): MXU-shaped, and multiples of the
  (8,128)/f32, (16,128)/bf16 tile constraints.
- causal masking prunes fully-masked k-blocks via the loop upper bound
  (no wasted MXU work past the diagonal).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp


def _xla_attention(q, k, v, causal: bool, sm_scale: float, bias=None, window: int = 0):
    """Reference implementation (XLA fuses this fine on CPU; used for
    correctness tests and non-TPU fallback). ``window`` > 0: sliding-window
    causal attention — row i sees keys (i-window, i]."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * sm_scale
    if bias is not None:
        logits = logits + bias
    if causal:
        mask = jnp.tril(jnp.ones((Tq, Tk), dtype=bool), k=Tk - Tq)
        if window > 0:
            q_pos = (Tk - Tq) + jnp.arange(Tq)[:, None]
            k_pos = jnp.arange(Tk)[None, :]
            mask = mask & (q_pos - k_pos < window)
        logits = jnp.where(mask[None, None], logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# LSE (and the in-kernel running max/denominator) carry a replicated
# 128-lane trailing dim: Mosaic tiles the last two dims as (8, 128), so a
# 1-D [block_q] vector (or a [BH, Tq] output with a squeezed block dim)
# cannot be laid out. Same layout as jax's reference TPU flash kernel
# (jax/experimental/pallas/ops/tpu/flash_attention.py, MIN_BLOCK_SIZE).
_LSE_LANES = 128


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref=None, *, block_k: int, causal: bool, sm_scale: float, seq_k: int, block_q: int, window: int = 0):
    from jax.experimental import pallas as pl

    q = q_ref[...]  # [block_q, d]
    q_idx = pl.program_id(1)
    d = q.shape[-1]

    m0 = jnp.full((q.shape[0], 1), -jnp.inf, dtype=jnp.float32)
    l0 = jnp.zeros((q.shape[0], 1), dtype=jnp.float32)
    acc0 = jnp.zeros((q.shape[0], d), dtype=jnp.float32)

    num_k_blocks = pl.cdiv(seq_k, block_k)
    # Bottom-right-aligned causal mask (matches _xla_attention's
    # tril(k=Tk-Tq)): query row i sees keys 0..i+(Tk-Tq). Identical to the
    # usual mask when Tq == Tk; for Tq < Tk (decode with cache) the tail of
    # the keys is what's visible.
    causal_offset = seq_k - block_q * pl.num_programs(1)
    start_block = 0
    if causal:
        # K blocks strictly after this Q block's last visible key are masked.
        last_q_row = (q_idx + 1) * block_q - 1 + causal_offset
        num_k_blocks = jnp.minimum(num_k_blocks, (last_q_row // block_k) + 1)
        num_k_blocks = jnp.maximum(num_k_blocks, 0)
        if window > 0:
            # Sliding window: K blocks entirely before the FIRST q row's
            # window are skipped — the FLOPs saving that makes long-context
            # windowed attention O(T*W) instead of O(T^2).
            first_q_row = q_idx * block_q + causal_offset
            start_block = jnp.maximum(0, (first_q_row - window + 1) // block_k)

    def make_body(masked: bool):
        def body(kb, carry):
            m_prev, l_prev, acc_prev = carry
            k_blk = k_ref[pl.ds(kb * block_k, block_k), :]
            v_blk = v_ref[pl.ds(kb * block_k, block_k), :]
            s = jax.lax.dot_general(
                q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            ) * sm_scale  # [block_q, block_k]
            if masked:
                q_pos = q_idx * block_q + causal_offset + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
                k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
                visible = q_pos >= k_pos
                if window > 0:
                    visible &= q_pos - k_pos < window
                s = jnp.where(visible, s, -jnp.inf)
            m_cur = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            # Fully-masked-so-far rows (possible under a sliding window: early
            # k-blocks can be entirely outside a late row's window) have
            # m_cur = -inf; exp(-inf - -inf) would be NaN. Substituting 0 for
            # the max keeps correction = p = exp(-inf) = 0 — the correct
            # "contributes nothing" behavior.
            safe_m = jnp.where(jnp.isneginf(m_cur), 0.0, m_cur)
            correction = jnp.exp(m_prev - safe_m)
            p = jnp.exp(s - safe_m)
            l_cur = l_prev * correction + p.sum(axis=-1, keepdims=True)
            pv = jax.lax.dot_general(
                p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc_cur = acc_prev * correction + pv
            return m_cur, l_cur, acc_cur

        return body

    if causal and window == 0:
        # Split the k-loop at the diagonal: blocks entirely below it (every
        # k_pos visible to every row of this q block) skip the iota/compare/
        # select mask — pure VPU work that at (1024,1024)-class tiles costs
        # the same order as the score matmul itself. Only diagonal-crossing
        # blocks pay for masking. (Windowed attention keeps the uniform
        # masked loop: its left edge re-masks early blocks too.)
        first_q_row = q_idx * block_q + causal_offset
        full_end = jnp.clip((first_q_row + 1) // block_k, start_block, num_k_blocks)
        carry = jax.lax.fori_loop(start_block, full_end, make_body(False), (m0, l0, acc0))
        m, l, acc = jax.lax.fori_loop(full_end, num_k_blocks, make_body(True), carry)
    else:
        m, l, acc = jax.lax.fori_loop(
            start_block, num_k_blocks, make_body(causal), (m0, l0, acc0)
        )
    o_ref[...] = (acc / l).astype(o_ref.dtype)
    if lse_ref is not None:
        # Log-sum-exp per row: the residual the backward pass needs to
        # reconstruct P = exp(S - lse) blockwise without re-running the
        # online softmax. Replicated across the lane dim (see _LSE_LANES).
        # Only materialized on the VJP forward — the primal path skips the
        # HBM write entirely.
        lse_ref[...] = jnp.broadcast_to(m + jnp.log(l), lse_ref.shape).astype(lse_ref.dtype)


def _pallas_flash_with_lse(q, k, v, causal: bool, sm_scale: float, block_q: int, block_k: int, interpret: bool, save_lse: bool = True, window: int = 0):
    from jax.experimental import pallas as pl

    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    # Fold batch and heads into the grid's first axis; layout [BH, T, D].
    qf = q.transpose(0, 2, 1, 3).reshape(B * H, Tq, D)
    kf = k.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)

    grid = (B * H, pl.cdiv(Tq, block_q))
    kernel = functools.partial(
        _flash_kernel,
        block_k=block_k,
        causal=causal,
        sm_scale=sm_scale,
        seq_k=Tk,
        block_q=block_q,
        window=window,
    )
    out_specs = [pl.BlockSpec((None, block_q, D), lambda bh, qb: (bh, qb, 0))]
    out_shape = [jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype)]
    if save_lse:
        out_specs.append(pl.BlockSpec((None, block_q, _LSE_LANES), lambda bh, qb: (bh, qb, 0)))
        out_shape.append(jax.ShapeDtypeStruct((B * H, Tq, _LSE_LANES), jnp.float32))
    res = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda bh, qb: (bh, qb, 0)),
            pl.BlockSpec((None, Tk, D), lambda bh, qb: (bh, 0, 0)),
            pl.BlockSpec((None, Tk, D), lambda bh, qb: (bh, 0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(qf, kf, vf)
    out = res[0].reshape(B, H, Tq, D).transpose(0, 2, 1, 3)
    lse = res[1][..., 0].reshape(B, H, Tq) if save_lse else None
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _pallas_flash(q, k, v, causal: bool, sm_scale: float, block_q: int, block_k: int, interpret: bool, window: int = 0):
    out, _ = _pallas_flash_with_lse(q, k, v, causal, sm_scale, block_q, block_k, interpret, save_lse=False, window=window)
    return out


def _pallas_flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret, window=0):
    out, lse = _pallas_flash_with_lse(q, k, v, causal, sm_scale, block_q, block_k, interpret, window=window)
    return out, (q, k, v, out, lse)


def _xla_blockwise_bwd(causal, sm_scale, block_q, block_k, window, res, dout):
    """Memory-efficient flash backward, expressed in XLA (lax.fori_loop over
    K blocks — the compiler tiles the matmuls onto the MXU; peak memory is
    one [B,H,Tq,block_k] logits block instead of the full [Tq,Tk] matrix).
    CPU/debug fallback for the Pallas backward kernels below.

    Standard flash-attention backward (Dao et al. 2022):
        D  = rowsum(dO * O)
        P  = exp(S - lse)
        dV = P^T dO;  dP = dO V^T;  dS = P * (dP - D) * sm_scale
        dQ = dS K;    dK = dS^T Q
    """
    q, k, v, out, lse = res
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    # Inputs stay in their storage dtype (bf16 on TPU): every matmul below
    # asks for f32 accumulation via preferred_element_type, which is the
    # MXU's native mode. An upfront .astype(f32) would instead force f32
    # matmuls (multi-pass on the MXU, ~4x slower) — measured 89.8k -> 97k+
    # tok/s on the v5e bench when the casts were dropped.
    qT = q.transpose(0, 2, 1, 3)                       # [B,H,Tq,D]
    kT = k.transpose(0, 2, 1, 3)                       # [B,H,Tk,D]
    vT = v.transpose(0, 2, 1, 3)
    oT = out.transpose(0, 2, 1, 3)
    doT = dout.transpose(0, 2, 1, 3)
    delta = jnp.sum(doT.astype(jnp.float32) * oT.astype(jnp.float32), axis=-1)  # [B,H,Tq]

    def mm(a, b, pat):
        return jnp.einsum(pat, a, b, preferred_element_type=jnp.float32)

    bk = min(block_k, Tk)
    num_kb = (Tk + bk - 1) // bk
    # Sliding window: only q rows with k_pos <= q_pos < k_pos + window can
    # attend a given k block, so the q range touching block [start,
    # start+bk) spans at most bk + window - 1 rows. Slicing q to that
    # (static) width keeps the backward O(T·window) like the forward
    # kernel, instead of scoring all Tq rows per block.
    qw = min(Tq, bk + window - 1) if (causal and window > 0) else Tq
    # Same bottom-right causal alignment as forward kernel/_xla_attention.
    q_row = jax.lax.broadcasted_iota(jnp.int32, (qw, bk), 0)

    def body(kb, carry):
        dq_acc, dk_acc, dv_acc = carry
        start = kb * bk
        qs_start = (
            jnp.clip(start - (Tk - Tq), 0, Tq - qw) if qw < Tq else jnp.int32(0)
        )
        ks = jax.lax.dynamic_slice_in_dim(kT, start, bk, axis=2)   # [B,H,bk,D]
        vs = jax.lax.dynamic_slice_in_dim(vT, start, bk, axis=2)
        qs = jax.lax.dynamic_slice_in_dim(qT, qs_start, qw, axis=2)
        dos = jax.lax.dynamic_slice_in_dim(doT, qs_start, qw, axis=2)
        lses = jax.lax.dynamic_slice_in_dim(lse, qs_start, qw, axis=2)
        deltas = jax.lax.dynamic_slice_in_dim(delta, qs_start, qw, axis=2)
        s = mm(qs, ks, "bhqd,bhkd->bhqk") * sm_scale
        if causal:
            q_pos = (Tk - Tq) + qs_start + q_row
            k_pos = start + jax.lax.broadcasted_iota(jnp.int32, (qw, bk), 1)
            visible = q_pos >= k_pos
            if window > 0:
                visible &= q_pos - k_pos < window
            s = jnp.where(visible[None, None], s, -jnp.inf)
        p = jnp.exp(s - lses[..., None])                # f32; masked rows -> 0
        dp = mm(dos, vs, "bhqd,bhkd->bhqk")
        ds = (p * (dp - deltas[..., None]) * sm_scale).astype(qT.dtype)
        pb = p.astype(qT.dtype)
        dq_slice = jax.lax.dynamic_slice_in_dim(dq_acc, qs_start, qw, axis=2)
        dq_acc = jax.lax.dynamic_update_slice_in_dim(
            dq_acc, dq_slice + mm(ds, ks, "bhqk,bhkd->bhqd"), qs_start, axis=2
        )
        dk_b = mm(ds, qs, "bhqk,bhqd->bhkd")
        dv_b = mm(pb, dos, "bhqk,bhqd->bhkd")
        dk_acc = jax.lax.dynamic_update_slice_in_dim(dk_acc, dk_b, start, axis=2)
        dv_acc = jax.lax.dynamic_update_slice_in_dim(dv_acc, dv_b, start, axis=2)
        return dq_acc, dk_acc, dv_acc

    dq0 = jnp.zeros((B, H, Tq, D), jnp.float32)
    dk0 = jnp.zeros((B, H, Tk, D), jnp.float32)
    dv0 = jnp.zeros((B, H, Tk, D), jnp.float32)
    dq, dk, dv = jax.lax.fori_loop(0, num_kb, body, (dq0, dk0, dv0))
    return (
        dq.transpose(0, 2, 1, 3).astype(q.dtype),
        dk.transpose(0, 2, 1, 3).astype(k.dtype),
        dv.transpose(0, 2, 1, 3).astype(v.dtype),
    )


# --- Pallas backward kernels -------------------------------------------------
#
# Two kernels (Dao et al. 2022 split): dkv iterates the grid over K blocks
# accumulating [block_k, D] dK/dV in VMEM; dq iterates over Q blocks
# accumulating [block_q, D] dQ. Both compute scores in the TRANSPOSED
# orientation s[block_k, block_q] = (K·Qᵀ)·scale so the per-row softmax
# residuals (lse) and delta = rowsum(dO·O) broadcast in as [1, block_q]
# lane-major rows — no 128-lane replication blowup and no [block_q, 1]
# layouts Mosaic can't tile. Causal + sliding-window pruning bound the inner
# loop exactly like the forward kernel, so the backward does ~half the MXU
# work of a full-score XLA backward (and never materializes a [Tq, Tk]
# tensor in HBM: measured 90.1k -> 109k tok/s on the v5e single-chip bench).


def _bwd_tile(q_blk, do_blk, k_blk, v_blk, lse_row, delta_row, q_pos0, k_pos0, causal, sm_scale, window):
    """Shared inner body: one (k-block, q-block) score tile, transposed
    orientation. Returns (p, ds) as [block_k, block_q] f32."""
    s = jax.lax.dot_general(
        k_blk, q_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * sm_scale  # [bk, bq]
    if causal:
        k_pos = k_pos0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        q_pos = q_pos0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        visible = q_pos >= k_pos
        if window > 0:
            visible &= q_pos - k_pos < window
        s = jnp.where(visible, s, -jnp.inf)
    p = jnp.exp(s - lse_row)  # [1, bq] broadcasts over k rows; masked -> 0
    dp = jax.lax.dot_general(
        v_blk, do_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - delta_row) * sm_scale
    return p, ds


def _flash_bwd_dkv_kernel(q_ref, do_ref, k_ref, v_ref, lse_ref, delta_ref, dk_ref, dv_ref, *, block_q: int, block_k: int, causal: bool, sm_scale: float, seq_q: int, seq_k: int, window: int):
    from jax.experimental import pallas as pl

    kb = pl.program_id(1)
    k_blk = k_ref[...]
    v_blk = v_ref[...]
    offset = seq_k - seq_q  # bottom-right causal alignment
    num_qb = pl.cdiv(seq_q, block_q)
    qb_start = 0
    qb_end = num_qb
    if causal:
        # First q block whose LAST row reaches this k block's first key.
        qb_start = jnp.maximum(0, (kb * block_k - offset) // block_q)
        if window > 0:
            # Last q block whose FIRST row is still inside the window of
            # this k block's last key.
            kmax = kb * block_k + block_k - 1
            qb_end = jnp.minimum(num_qb, (kmax + window - 1 - offset) // block_q + 1)
            qb_end = jnp.maximum(qb_end, qb_start)

    def body(qb, carry):
        dk_acc, dv_acc = carry
        q_blk = q_ref[pl.ds(qb * block_q, block_q), :]
        do_blk = do_ref[pl.ds(qb * block_q, block_q), :]
        lse_row = lse_ref[:, pl.ds(qb * block_q, block_q)]
        delta_row = delta_ref[:, pl.ds(qb * block_q, block_q)]
        p, ds = _bwd_tile(
            q_blk, do_blk, k_blk, v_blk, lse_row, delta_row,
            qb * block_q + offset, kb * block_k, causal, sm_scale, window,
        )
        dv_acc += jax.lax.dot_general(
            p.astype(do_blk.dtype), do_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_acc += jax.lax.dot_general(
            ds.astype(q_blk.dtype), q_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk_acc, dv_acc

    z = jnp.zeros((k_blk.shape[0], k_blk.shape[1]), jnp.float32)
    dk, dv = jax.lax.fori_loop(qb_start, qb_end, body, (z, z))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, do_ref, k_ref, v_ref, lse_ref, delta_ref, dq_ref, *, block_q: int, block_k: int, causal: bool, sm_scale: float, seq_q: int, seq_k: int, window: int):
    from jax.experimental import pallas as pl

    qb = pl.program_id(1)
    q_blk = q_ref[...]
    do_blk = do_ref[...]
    lse_row = lse_ref[...]
    delta_row = delta_ref[...]
    offset = seq_k - seq_q
    num_kb = pl.cdiv(seq_k, block_k)
    kb_start = 0
    kb_end = num_kb
    if causal:
        last_q_row = (qb + 1) * block_q - 1 + offset
        kb_end = jnp.clip((last_q_row // block_k) + 1, 0, num_kb)
        if window > 0:
            first_q_row = qb * block_q + offset
            kb_start = jnp.maximum(0, (first_q_row - window + 1) // block_k)

    def body(kb, dq_acc):
        k_blk = k_ref[pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[pl.ds(kb * block_k, block_k), :]
        _, ds = _bwd_tile(
            q_blk, do_blk, k_blk, v_blk, lse_row, delta_row,
            qb * block_q + offset, kb * block_k, causal, sm_scale, window,
        )
        # dQ += dSᵀ K : contract over the k rows of the transposed tile.
        return dq_acc + jax.lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    z = jnp.zeros((q_blk.shape[0], q_blk.shape[1]), jnp.float32)
    dq = jax.lax.fori_loop(kb_start, kb_end, body, z)
    dq_ref[...] = dq.astype(dq_ref.dtype)


def _pallas_bwd_impl(q, k, v, out, lse, dout, causal, sm_scale, block_q, block_k, interpret, window):
    from jax.experimental import pallas as pl

    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    qf = q.transpose(0, 2, 1, 3).reshape(B * H, Tq, D)
    kf = k.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    of = out.transpose(0, 2, 1, 3).reshape(B * H, Tq, D)
    dof = dout.transpose(0, 2, 1, 3).reshape(B * H, Tq, D)
    # delta = rowsum(dO · O): tiny [BH, Tq] f32; lane-major [BH, 1, Tq] so
    # kernels can slice [1, block_q] rows without layout tricks.
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32), axis=-1)
    delta = delta[:, None, :]
    lsef = lse.reshape(B * H, 1, Tq)

    kw = dict(block_q=block_q, block_k=block_k, causal=causal,
              sm_scale=sm_scale, seq_q=Tq, seq_k=Tk, window=window)
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, **kw),
        grid=(B * H, pl.cdiv(Tk, block_k)),
        in_specs=[
            pl.BlockSpec((None, Tq, D), lambda bh, kb: (bh, 0, 0)),
            pl.BlockSpec((None, Tq, D), lambda bh, kb: (bh, 0, 0)),
            pl.BlockSpec((None, block_k, D), lambda bh, kb: (bh, kb, 0)),
            pl.BlockSpec((None, block_k, D), lambda bh, kb: (bh, kb, 0)),
            pl.BlockSpec((None, 1, Tq), lambda bh, kb: (bh, 0, 0)),
            pl.BlockSpec((None, 1, Tq), lambda bh, kb: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, D), lambda bh, kb: (bh, kb, 0)),
            pl.BlockSpec((None, block_k, D), lambda bh, kb: (bh, kb, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tk, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, Tk, D), v.dtype),
        ],
        interpret=interpret,
    )(qf, dof, kf, vf, lsef, delta)
    (dq,) = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, **kw),
        grid=(B * H, pl.cdiv(Tq, block_q)),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda bh, qb: (bh, qb, 0)),
            pl.BlockSpec((None, block_q, D), lambda bh, qb: (bh, qb, 0)),
            pl.BlockSpec((None, Tk, D), lambda bh, qb: (bh, 0, 0)),
            pl.BlockSpec((None, Tk, D), lambda bh, qb: (bh, 0, 0)),
            pl.BlockSpec((None, 1, block_q), lambda bh, qb: (bh, 0, qb)),
            pl.BlockSpec((None, 1, block_q), lambda bh, qb: (bh, 0, qb)),
        ],
        out_specs=[pl.BlockSpec((None, block_q, D), lambda bh, qb: (bh, qb, 0))],
        out_shape=[jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype)],
        interpret=interpret,
    )(qf, dof, kf, vf, lsef, delta)
    unfold = lambda x, T: x.reshape(B, H, T, D).transpose(0, 2, 1, 3)
    return unfold(dq, Tq), unfold(dk, Tk), unfold(dv, Tk)


def _pallas_flash_bwd(causal, sm_scale, block_q, block_k, interpret, window, res, dout):
    import os

    q, k, v, out, lse = res
    Tq, Tk = q.shape[1], k.shape[1]
    want = int(os.environ.get("RAY_TPU_FLASH_BWD_BLOCK", "512"))
    bq, bk = _fit_block(want, Tq), _fit_block(want, Tk)
    use_pallas = (_on_tpu() or interpret) and os.environ.get(
        "RAY_TPU_FLASH_XLA_BWD", "0"
    ) != "1" and Tq % bq == 0 and Tk % bk == 0
    if not use_pallas:
        return _xla_blockwise_bwd(causal, sm_scale, block_q, block_k, window, (q, k, v, out, lse), dout)
    return _pallas_bwd_impl(q, k, v, out, lse, dout, causal, sm_scale, bq, bk, interpret, window)


_pallas_flash.defvjp(_pallas_flash_fwd, _pallas_flash_bwd)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = False,
    sm_scale: float | None = None,
    # Default block size: env RAY_TPU_FLASH_FWD_BLOCK (read at trace time),
    # else 1024. Measured on v5e at bench shapes (r4: B8/H8/T1024/D128, full
    # train step): (128,128) << (256,512) < (1024,1024) for the UNsplit
    # causal loop — bigger blocks mean fewer grid steps and less per-block
    # overhead, and _fit_block clamps them to the sequence, so short
    # sequences degrade gracefully to block == seq.
    # VMEM bound: a (1024, 1024) fp32 score tile is 4 MiB of the ~16 MiB
    # budget, leaving room for the q/k/v/o tiles at head_dim <= 256.
    # With the split-at-the-diagonal mask loop, smaller blocks also PRUNE:
    # at (512,512) causal T=1024 skips 1/4 of the score tiles entirely.
    block_q: int | None = None,
    block_k: int | None = None,
    bias=None,
    force_pallas: bool | None = None,
    interpret: bool = False,
    window: int = 0,
):
    """Multi-head attention, [B, T, H, D] layout.

    Pallas on TPU; XLA reference elsewhere (or with a bias, which the kernel
    does not support yet). ``window`` > 0 (requires causal) is Mistral-style
    sliding-window attention: row i attends keys (i-window, i]; the kernel
    SKIPS k-blocks entirely outside the window, so long-context cost is
    O(T·window), not O(T²).
    """
    if window and not causal:
        raise ValueError("sliding window requires causal=True")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if block_q is None or block_k is None:
        import os

        dflt = int(os.environ.get("RAY_TPU_FLASH_FWD_BLOCK", "1024"))
        block_q = dflt if block_q is None else block_q
        block_k = dflt if block_k is None else block_k
    use_pallas = force_pallas if force_pallas is not None else (_on_tpu() or interpret)
    Tq, Tk = q.shape[1], k.shape[1]
    bq = _fit_block(block_q, Tq)
    bk = _fit_block(block_k, Tk)
    # Block sizes must tile the sequence exactly: a clamped tail slice would
    # read overlapping rows (and the backward would double-count them).
    if bias is not None or not use_pallas or Tq % bq or Tk % bk:
        return _xla_attention(q, k, v, causal, sm_scale, bias, window=window)
    return _pallas_flash(q, k, v, causal, sm_scale, bq, bk, interpret, window)


def _fit_block(want: int, t: int) -> int:
    """Largest 128-multiple <= want that divides t (so a sequence divisible
    by 128 but not by the preferred block still rides the kernel at a
    smaller block). For t <= 128 the block is t itself (block == full dim is
    Mosaic-legal); for larger non-128-multiple t the result is 128, and the
    caller's divisibility guard then routes to the XLA fallback — a 136-wide
    block would violate the (8, 128) tile constraint."""
    if t <= 128:
        return min(want, t)
    b = min(want, t)
    b -= b % 128
    while b > 128 and t % b:
        b -= 128
    return max(b, 128)
