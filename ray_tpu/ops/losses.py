"""Loss kernels.

``fused_lm_loss``: next-token cross-entropy fused with the LM-head matmul,
computed over sequence chunks so the full ``[B*T, V]`` f32 logits tensor is
never materialized. At bench shapes (B8 T1024 V32k) the unfused loss writes
~1 GiB of f32 logits + log-softmax intermediates to HBM in the forward and
reads them back in the backward — pure bandwidth, no MXU work. The chunked
form keeps one ``[chunk, V]`` tile live at a time (64 MiB at chunk=512) and
recomputes it in the backward: classic flash-style trade of FLOPs for HBM,
the same rematerialisation XLA cannot do on its own across the
matmul+softmax+gather boundary.

Forward per chunk: ``logits = x_c @ head; lse = logsumexp(logits);
nll_c = lse - logits[target]``. Backward per chunk:
``p = exp(logits - lse); p[target] -= 1; dx_c = g/N * (p @ head^T);
dhead += x_c^T @ (g/N * p)`` — the standard softmax-CE gradient, rebuilt
blockwise from the saved (tiny) ``lse`` rather than saved logits.

(The reference delegates LM losses to torch/HF — SURVEY.md §5.7; this is
the TPU-native hot-path equivalent, same role as ops/attention.py.)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _pick_chunk(n: int, want: int) -> int:
    """Largest divisor of n that is <= want (prefer multiples of 128 for
    clean MXU tiling; n is B*T which is 128-aligned in practice)."""
    want = max(1, min(want, n))
    for c in range(want, 0, -1):
        if n % c == 0:
            return c
    return n


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused_lm_loss_sum(x, head, targets, chunk):
    """sum of per-token NLL. x: [N, D] (model dtype), head: [D, V],
    targets: [N] int32. Returns f32 scalar."""
    s, _ = _fused_fwd_scan(x, head, targets, chunk)
    return s


def _fused_fwd_scan(x, head, targets, chunk):
    N, D = x.shape
    xc = x.reshape(N // chunk, chunk, D)
    tc = targets.reshape(N // chunk, chunk)

    def body(total, ct):
        xb, tb = ct
        logits = jnp.dot(xb, head, preferred_element_type=jnp.float32)  # [c, V]
        lse = jax.scipy.special.logsumexp(logits, axis=-1)  # [c]
        tgt = jnp.take_along_axis(logits, tb[:, None], axis=-1)[:, 0]
        return total + jnp.sum(lse - tgt), lse

    total, lses = lax.scan(body, jnp.float32(0.0), (xc, tc))
    return total, lses.reshape(N)


def _fused_lm_loss_fwd(x, head, targets, chunk):
    total, lse = _fused_fwd_scan(x, head, targets, chunk)
    return total, (x, head, targets, lse)


def _fused_lm_loss_bwd(chunk, res, g):
    x, head, targets, lse = res
    N, D = x.shape
    xc = x.reshape(N // chunk, chunk, D)
    tc = targets.reshape(N // chunk, chunk)
    lc = lse.reshape(N // chunk, chunk)

    def body(dhead_acc, ct):
        xb, tb, lb = ct
        logits = jnp.dot(xb, head, preferred_element_type=jnp.float32)  # [c, V]
        p = jnp.exp(logits - lb[:, None])  # softmax, rebuilt from saved lse
        p = p - jax.nn.one_hot(tb, logits.shape[-1], dtype=p.dtype)
        pg = (p * g).astype(x.dtype)
        dxb = jnp.dot(pg, head.T, preferred_element_type=jnp.float32).astype(x.dtype)
        dhead_acc = dhead_acc + jnp.dot(
            xb.T, pg, preferred_element_type=jnp.float32
        )
        return dhead_acc, dxb

    dhead, dxc = lax.scan(body, jnp.zeros(head.shape, jnp.float32), (xc, tc, lc))
    return dxc.reshape(N, D), dhead.astype(head.dtype), None


_fused_lm_loss_sum.defvjp(_fused_lm_loss_fwd, _fused_lm_loss_bwd)


def fused_lm_loss(
    x,
    head,
    targets,
    *,
    chunk_size: int = 512,
    mean: bool = True,
):
    """Cross-entropy LM loss fused with the head projection.

    x: [B, T, D] or [N, D] final hidden states (bf16 fine — the matmul
    accumulates f32); head: [D, V]; targets: [B, T] or [N] int32.
    Numerically identical (f32 accumulation, logsumexp-stable) to
    ``log_softmax(x @ head)`` gathering, without ever holding [N, V].
    """
    if x.ndim == 3:
        B, T, D = x.shape
        x = x.reshape(B * T, D)
        targets = targets.reshape(B * T)
    N = x.shape[0]
    chunk = _pick_chunk(N, chunk_size)
    total = _fused_lm_loss_sum(x, head.astype(x.dtype), targets, chunk)
    return total / N if mean else total
