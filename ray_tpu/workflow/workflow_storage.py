"""Durable workflow storage.

Analog of the reference's workflow storage (python/ray/workflow/
workflow_storage.py): every step result is durably persisted (atomic
tmp+rename) under ``<storage_dir>/<workflow_id>/``, together with the pickled
DAG and a status file, so an interrupted workflow can be resumed from the log
by a fresh driver.
"""

from __future__ import annotations

import json
import os
import pickle
import tempfile

_DEFAULT_STORAGE = os.path.join(tempfile.gettempdir(), "ray_tpu", "workflows")
_storage_dir = None


def set_storage(path: str | None):
    global _storage_dir
    _storage_dir = path


def get_storage_dir() -> str:
    d = _storage_dir or os.environ.get("RAY_TPU_WORKFLOW_STORAGE") or _DEFAULT_STORAGE
    os.makedirs(d, exist_ok=True)
    return d


def _atomic_write(path: str, data: bytes):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


class WorkflowStorage:
    def __init__(self, workflow_id: str, storage_dir: str | None = None):
        self.workflow_id = workflow_id
        self.root = os.path.join(storage_dir or get_storage_dir(), workflow_id)

    # -- DAG ---------------------------------------------------------------
    def save_dag(self, dag):
        import cloudpickle  # vendored by the env's jax/flax deps

        _atomic_write(os.path.join(self.root, "dag.pkl"), cloudpickle.dumps(dag))

    def load_dag(self):
        with open(os.path.join(self.root, "dag.pkl"), "rb") as f:
            return pickle.load(f)

    def has_dag(self) -> bool:
        return os.path.exists(os.path.join(self.root, "dag.pkl"))

    # -- status ------------------------------------------------------------
    def save_status(self, status: str, extra: dict | None = None):
        payload = {"status": status, **(extra or {})}
        _atomic_write(os.path.join(self.root, "status.json"), json.dumps(payload).encode())

    def load_status(self) -> dict:
        p = os.path.join(self.root, "status.json")
        if not os.path.exists(p):
            return {"status": "NOT_FOUND"}
        with open(p) as f:
            return json.load(f)

    # -- cancellation ------------------------------------------------------
    def _cancel_path(self) -> str:
        return os.path.join(self.root, "cancel")

    def request_cancel(self):
        """Durable cancel marker: the executor checks it between events and
        aborts; it survives the requesting process."""
        _atomic_write(self._cancel_path(), b"1")

    def cancel_requested(self) -> bool:
        return os.path.exists(self._cancel_path())

    def clear_cancel(self):
        try:
            os.unlink(self._cancel_path())
        except FileNotFoundError:
            pass

    # -- step results ------------------------------------------------------
    def _step_path(self, step_id: str) -> str:
        return os.path.join(self.root, "steps", f"{step_id}.pkl")

    def list_step_ids(self) -> list[str]:
        """Ids of every persisted (completed) step, sub-DAG steps included."""
        steps_root = os.path.join(self.root, "steps")
        out = []
        for root, _dirs, names in os.walk(steps_root):
            for name in names:
                if name.endswith(".pkl"):
                    full = os.path.join(root, name)
                    out.append(os.path.relpath(full, steps_root)[: -len(".pkl")])
        return sorted(out)

    def step_metadata(self, step_id: str) -> dict | None:
        p = self._step_path(step_id)
        if not os.path.exists(p):
            return None
        return {
            "task_id": step_id,
            "status": "SUCCESSFUL",
            "end_time": os.path.getmtime(p),
        }

    def save_step_result(self, step_id: str, value):
        import cloudpickle

        _atomic_write(self._step_path(step_id), cloudpickle.dumps(value))

    def has_step_result(self, step_id: str) -> bool:
        return os.path.exists(self._step_path(step_id))

    def load_step_result(self, step_id: str):
        with open(self._step_path(step_id), "rb") as f:
            return pickle.load(f)

    # -- output ------------------------------------------------------------
    def save_output(self, value):
        import cloudpickle

        _atomic_write(os.path.join(self.root, "output.pkl"), cloudpickle.dumps(value))

    def load_output(self):
        with open(os.path.join(self.root, "output.pkl"), "rb") as f:
            return pickle.load(f)

    def has_output(self) -> bool:
        return os.path.exists(os.path.join(self.root, "output.pkl"))

    def delete(self):
        import shutil

        shutil.rmtree(self.root, ignore_errors=True)


def list_workflows(storage_dir: str | None = None):
    root = storage_dir or get_storage_dir()
    out = []
    if not os.path.isdir(root):
        return out
    for wid in sorted(os.listdir(root)):
        st = WorkflowStorage(wid, root).load_status()
        if st["status"] != "NOT_FOUND":
            out.append((wid, st["status"]))
    return out
