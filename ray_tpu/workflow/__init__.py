"""ray_tpu.workflow — durable DAG execution (reference: python/ray/workflow/).

API analog of the reference (api.py:120 run, :232 resume): ``workflow.run``
executes a ``ray_tpu.dag`` graph with every step result durably logged;
``workflow.resume`` replays an interrupted workflow from the log, re-running
only steps whose results were not persisted.
"""

from __future__ import annotations

import threading

from ray_tpu.workflow import workflow_storage as _storage_mod
from ray_tpu.workflow.event_listener import (  # noqa: F401
    EventListener,
    KVEventListener,
    deliver_event,
    run_listener_method,
)
from ray_tpu.workflow.workflow_executor import (
    WorkflowCancellationError,
    execute_workflow,
)
from ray_tpu.workflow.workflow_storage import WorkflowStorage, list_workflows

__all__ = [
    "init",
    "run",
    "run_async",
    "resume",
    "cancel",
    "get_status",
    "get_output",
    "get_metadata",
    "list_all",
    "delete",
    "wait",
    "sleep",
    "continuation",
    "wait_for_event",
    "EventListener",
    "KVEventListener",
    "WorkflowCancellationError",
    "deliver_event",
]

_counter_lock = threading.Lock()
_counter = [0]


def init(storage: str | None = None):
    """Set the durable storage root (default /tmp/ray_tpu/workflows or
    $RAY_TPU_WORKFLOW_STORAGE)."""
    _storage_mod.set_storage(storage)


def _auto_id() -> str:
    import time

    with _counter_lock:
        _counter[0] += 1
        return f"workflow-{int(time.time())}-{_counter[0]}"


def run(
    dag,
    *args,
    workflow_id: str | None = None,
    max_retries: int = 0,
    catch_exceptions: bool = False,
    **kwargs,
):
    """Execute the DAG durably and return its output.

    ``max_retries``/``catch_exceptions`` are run-level defaults for every
    step; per-step values via ``node.options(max_retries=...,
    catch_exceptions=...)`` win (reference: workflow.options)."""
    import time

    wid = workflow_id or _auto_id()
    storage = WorkflowStorage(wid)
    if storage.has_output():
        # idempotent re-run of a finished workflow returns the stored output
        return storage.load_output()
    storage.save_dag((dag, args, kwargs, {"max_retries": max_retries, "catch_exceptions": catch_exceptions}))
    prev = storage.load_status()
    if prev["status"] == "CANCELED":
        # The previous run's cancel fully landed (terminal status): its
        # marker is stale and this run supersedes it. An IN-FLIGHT cancel
        # (marker written, status not yet CANCELED) is deliberately NOT
        # cleared — clearing unconditionally would silently discard a cancel
        # that raced this run's start.
        storage.clear_cancel()
    start = prev.get("start_time") or time.time()
    storage.save_status("RUNNING", {"start_time": start})
    try:
        result = execute_workflow(
            storage, dag, args, kwargs,
            max_retries=max_retries, catch_exceptions=catch_exceptions,
        )
    except WorkflowCancellationError:
        storage.save_status("CANCELED", {"start_time": start, "end_time": time.time()})
        raise
    except BaseException:
        storage.save_status("FAILED", {"start_time": start, "end_time": time.time()})
        raise
    storage.save_status("SUCCESSFUL", {"start_time": start, "end_time": time.time()})
    return result


def run_async(dag, *args, workflow_id: str | None = None, **kwargs):
    """Execute durably in a background thread; returns (workflow_id, thread)."""
    wid = workflow_id or _auto_id()

    def _run():
        try:
            run(dag, *args, workflow_id=wid, **kwargs)
        except WorkflowCancellationError:
            pass  # expected exit: workflow.cancel() was called

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    return wid, t


def resume(workflow_id: str):
    """Resume an interrupted (or cancelled) workflow from its durable log:
    persisted steps replay, unfinished ones re-run."""
    import time

    storage = WorkflowStorage(workflow_id)
    if storage.has_output():
        return storage.load_output()
    if not storage.has_dag():
        raise ValueError(f"workflow '{workflow_id}' not found in storage")
    loaded = storage.load_dag()
    # Older logs stored (dag, args, kwargs); newer ones append run options.
    if len(loaded) == 4:
        dag, args, kwargs, opts = loaded
    else:
        dag, args, kwargs = loaded
        opts = {}
    storage.clear_cancel()  # resuming a cancelled workflow restarts it
    start = storage.load_status().get("start_time") or time.time()
    storage.save_status("RUNNING", {"start_time": start})
    try:
        result = execute_workflow(storage, dag, args, kwargs, **opts)
    except WorkflowCancellationError:
        storage.save_status("CANCELED", {"start_time": start, "end_time": time.time()})
        raise
    except BaseException:
        storage.save_status("FAILED", {"start_time": start, "end_time": time.time()})
        raise
    storage.save_status("SUCCESSFUL", {"start_time": start, "end_time": time.time()})
    return result


def wait(workflows: list, *, num_returns: int = 1, timeout: float | None = None):
    """A workflow step resolving once ``num_returns`` of the given workflow
    nodes have finished (reference api.py ``workflow.wait``): its value is
    ``(ready_values, num_remaining)``. Divergence from the reference noted:
    the remaining entries are reported as a COUNT, not as resumable workflow
    handles — consumers that need every result wait for all of them."""
    import ray_tpu

    workflows = list(workflows)
    if num_returns < 1 or num_returns > len(workflows):
        raise ValueError(
            f"num_returns must be in [1, {len(workflows)}], got {num_returns}"
        )

    @ray_tpu.remote(num_cpus=0)
    def __workflow_wait__(refs, k, to):
        import ray_tpu as _r
        from ray_tpu.object_ref import ObjectRef

        # On resume, already-persisted upstream steps arrive as VALUES (the
        # executor replays them from the log), live ones as ObjectRefs.
        pending = [r for r in refs if isinstance(r, ObjectRef)]
        ready_vals = [r for r in refs if not isinstance(r, ObjectRef)]
        need = max(0, k - len(ready_vals))
        remaining = len(pending)
        if need and pending:
            ready, rest = _r.wait(
                pending, num_returns=min(need, len(pending)), timeout=to
            )
            ready_vals += [_r.get(r) for r in ready]
            remaining = len(rest)
        return (ready_vals, remaining)

    # The upstream nodes ride inside a list, so the executor passes their
    # ObjectRefs through unresolved (nested refs are not auto-materialized)
    # and the wait step sees refs it can ray_tpu.wait on.
    return __workflow_wait__.bind(workflows, num_returns, timeout)


def sleep(duration: float):
    """A workflow step that resolves after ``duration`` seconds (reference
    api.py:585). Durable like any step: a resume AFTER it completed does not
    sleep again."""
    import ray_tpu

    @ray_tpu.remote(num_cpus=0)
    def __workflow_sleep__(d):
        import time

        time.sleep(d)
        return None

    return __workflow_sleep__.bind(duration)


def wait_for_event(event_listener_type, *args, **kwargs):
    """Two-step poll->commit DAG for an external event (reference api.py:557).
    The poll step blocks in ``listener.poll_for_event``; after the event
    value exists, the commit step runs ``listener.event_checkpointed``. A
    driver killed mid-poll resumes by re-polling (at-least-once delivery,
    exactly-once consumption via the durable step log)."""
    import ray_tpu
    from ray_tpu.workflow.event_listener import EventListener as _EL
    from ray_tpu.workflow.event_listener import run_listener_method

    if not (isinstance(event_listener_type, type) and issubclass(event_listener_type, _EL)):
        raise TypeError(
            "wait_for_event expects an EventListener subclass, got "
            f"{event_listener_type!r}"
        )

    @ray_tpu.remote(num_cpus=0)
    def __workflow_poll_event__(*a, **kw):
        listener = event_listener_type()
        return run_listener_method(listener.poll_for_event, *a, **kw)

    @ray_tpu.remote(num_cpus=0)
    def __workflow_event_committed__(event):
        listener = event_listener_type()
        run_listener_method(listener.event_checkpointed, event)
        return event

    return __workflow_event_committed__.bind(
        __workflow_poll_event__.bind(*args, **kwargs)
    )


def continuation(dag_node):
    """Convert a DAG into a continuation (reference api.py:712): inside a
    workflow step, return it to extend the workflow dynamically (the
    executor runs the sub-DAG durably under the step's namespace); outside
    workflow execution it simply executes the DAG and returns the result."""
    import os

    from ray_tpu.dag.dag_node import DAGNode

    if not isinstance(dag_node, DAGNode):
        raise TypeError("workflow.continuation expects a DAG node")
    if os.environ.get("RAY_TPU_IN_WORKFLOW") == "1":
        return dag_node
    import ray_tpu
    from ray_tpu.object_ref import ObjectRef

    out = dag_node.execute()
    return ray_tpu.get(out) if isinstance(out, ObjectRef) else out


def cancel(workflow_id: str) -> None:
    """Cancel a running workflow (reference api.py ``workflow.cancel``):
    writes a durable cancel marker the executor honors within ~1s — pending
    steps are ``ray_tpu.cancel``-ed best-effort, completed step results stay
    persisted, and the status becomes CANCELED. Works cross-process (any
    driver sharing the storage root can cancel). A cancelled workflow can be
    restarted later with ``workflow.resume``."""
    import time

    storage = WorkflowStorage(workflow_id)
    if not storage.has_dag():
        raise ValueError(f"workflow '{workflow_id}' not found in storage")
    prev = storage.load_status()
    if storage.has_output() or prev["status"] in ("FAILED", "CANCELED"):
        return  # terminal already; don't clobber SUCCESSFUL/FAILED records
    storage.request_cancel()
    storage.save_status(
        "CANCELED",
        {
            "start_time": prev.get("start_time"),
            "end_time": time.time(),
        },
    )


def get_metadata(workflow_id: str, task_id: str | None = None) -> dict:
    """Workflow- or task-level metadata (reference api.py ``get_metadata``).

    Without ``task_id``: the workflow's status, timing stats, and the ids of
    every persisted (completed) step. With ``task_id`` (a step id as listed
    in ``tasks``): that step's completion record. Raises ``ValueError`` for
    an unknown workflow or a task with no persisted result yet."""
    storage = WorkflowStorage(workflow_id)
    if not storage.has_dag():
        raise ValueError(f"workflow '{workflow_id}' not found in storage")
    if task_id is not None:
        meta = storage.step_metadata(task_id)
        if meta is None:
            raise ValueError(
                f"workflow '{workflow_id}' has no completed task {task_id!r}"
            )
        return meta
    st = storage.load_status()
    stats = {
        k: st[k] for k in ("start_time", "end_time") if st.get(k) is not None
    }
    return {
        "workflow_id": workflow_id,
        "status": st["status"],
        "stats": stats,
        "tasks": storage.list_step_ids(),
    }


def get_status(workflow_id: str) -> str:
    return WorkflowStorage(workflow_id).load_status()["status"]


def get_output(workflow_id: str):
    storage = WorkflowStorage(workflow_id)
    if not storage.has_output():
        raise ValueError(f"workflow '{workflow_id}' has no output (status={get_status(workflow_id)})")
    return storage.load_output()


def list_all():
    return list_workflows()


def delete(workflow_id: str):
    WorkflowStorage(workflow_id).delete()
