"""ray_tpu.workflow — durable DAG execution (reference: python/ray/workflow/).

API analog of the reference (api.py:120 run, :232 resume): ``workflow.run``
executes a ``ray_tpu.dag`` graph with every step result durably logged;
``workflow.resume`` replays an interrupted workflow from the log, re-running
only steps whose results were not persisted.
"""

from __future__ import annotations

import threading

from ray_tpu.workflow import workflow_storage as _storage_mod
from ray_tpu.workflow.workflow_executor import execute_workflow
from ray_tpu.workflow.workflow_storage import WorkflowStorage, list_workflows

__all__ = [
    "init",
    "run",
    "run_async",
    "resume",
    "get_status",
    "get_output",
    "list_all",
    "delete",
]

_counter_lock = threading.Lock()
_counter = [0]


def init(storage: str | None = None):
    """Set the durable storage root (default /tmp/ray_tpu/workflows or
    $RAY_TPU_WORKFLOW_STORAGE)."""
    _storage_mod.set_storage(storage)


def _auto_id() -> str:
    import time

    with _counter_lock:
        _counter[0] += 1
        return f"workflow-{int(time.time())}-{_counter[0]}"


def run(
    dag,
    *args,
    workflow_id: str | None = None,
    max_retries: int = 0,
    catch_exceptions: bool = False,
    **kwargs,
):
    """Execute the DAG durably and return its output.

    ``max_retries``/``catch_exceptions`` are run-level defaults for every
    step; per-step values via ``node.options(max_retries=...,
    catch_exceptions=...)`` win (reference: workflow.options)."""
    wid = workflow_id or _auto_id()
    storage = WorkflowStorage(wid)
    if storage.has_output():
        # idempotent re-run of a finished workflow returns the stored output
        return storage.load_output()
    storage.save_dag((dag, args, kwargs, {"max_retries": max_retries, "catch_exceptions": catch_exceptions}))
    storage.save_status("RUNNING")
    try:
        return execute_workflow(
            storage, dag, args, kwargs,
            max_retries=max_retries, catch_exceptions=catch_exceptions,
        )
    except BaseException:
        storage.save_status("FAILED")
        raise


def run_async(dag, *args, workflow_id: str | None = None, **kwargs):
    """Execute durably in a background thread; returns (workflow_id, thread)."""
    wid = workflow_id or _auto_id()
    t = threading.Thread(target=run, args=(dag, *args), kwargs={"workflow_id": wid, **kwargs}, daemon=True)
    t.start()
    return wid, t


def resume(workflow_id: str):
    """Resume an interrupted workflow from its durable log."""
    storage = WorkflowStorage(workflow_id)
    if storage.has_output():
        return storage.load_output()
    if not storage.has_dag():
        raise ValueError(f"workflow '{workflow_id}' not found in storage")
    loaded = storage.load_dag()
    # Older logs stored (dag, args, kwargs); newer ones append run options.
    if len(loaded) == 4:
        dag, args, kwargs, opts = loaded
    else:
        dag, args, kwargs = loaded
        opts = {}
    storage.save_status("RUNNING")
    try:
        return execute_workflow(storage, dag, args, kwargs, **opts)
    except BaseException:
        storage.save_status("FAILED")
        raise


def get_status(workflow_id: str) -> str:
    return WorkflowStorage(workflow_id).load_status()["status"]


def get_output(workflow_id: str):
    storage = WorkflowStorage(workflow_id)
    if not storage.has_output():
        raise ValueError(f"workflow '{workflow_id}' has no output (status={get_status(workflow_id)})")
    return storage.load_output()


def list_all():
    return list_workflows()


def delete(workflow_id: str):
    WorkflowStorage(workflow_id).delete()
