"""Durable DAG executor.

Analog of the reference's WorkflowExecutor (python/ray/workflow/
workflow_executor.py:32): submits every unfinished FunctionNode eagerly
(independent branches run CONCURRENTLY), persists each step result the
moment it completes (completion order, not submission order — a crash
mid-run loses only unfinished steps), and skips steps whose results are
already in storage, which is what makes ``workflow.resume`` a replay of
the log.

Step identity is CONTENT-DERIVED (reference: workflow step names +
checkpoint identity): a hash of the step function's source, its static
arguments, its options, and its dependencies' step ids. Editing the DAG
(different code, args, or wiring) therefore changes the id and re-runs the
step — a positional id would silently replay a stale result into new code.

Failure semantics (reference: workflow error handling, api.py options):
- ``max_retries`` — application exceptions re-run the step N times (rides
  the task layer's retry_exceptions machinery);
- ``catch_exceptions`` — the step's consumers receive ``(result, None)``
  or ``(None, exception)`` instead of the workflow failing.
Both are per-step via ``node.options(...)`` with run-level defaults.
"""

from __future__ import annotations

import hashlib
import inspect

from ray_tpu.dag.dag_node import ClassMethodNode, ClassNode, DAGNode, FunctionNode, InputNode
from ray_tpu.workflow.workflow_storage import WorkflowStorage


class WorkflowCancellationError(RuntimeError):
    """The workflow was cancelled via ``workflow.cancel`` while running."""


_catch_task = None


def _get_catch_task():
    """A tiny task that boxes a step's outcome as (result, error)."""
    global _catch_task
    if _catch_task is None:
        import ray_tpu

        @ray_tpu.remote(num_cpus=0)
        def __workflow_catch__(boxed):
            import ray_tpu as _r
            from ray_tpu.exceptions import TaskError

            try:
                return (_r.get(boxed[0]), None)
            except TaskError as e:
                # The consumer wants the APPLICATION exception (reference:
                # catch_exceptions yields the original error).
                return (None, e.cause if e.cause is not None else e)
            except Exception as e:  # noqa: BLE001 — the caught value IS the product
                return (None, e)

        _catch_task = __workflow_catch__
    return _catch_task


def _fingerprint(value, ids: dict) -> bytes:
    """Stable-ish bytes for a bound argument. DAGNodes — INCLUDING nodes
    nested inside lists/tuples/dicts, which _resolved_args supports — map
    to their step ids so a changed dependency propagates into every
    consumer's id. Leaves pickle (not repr: default reprs embed object
    ADDRESSES and truncate arrays); unpicklable leaves fall back to the
    type name — coarse, but deterministic."""
    if isinstance(value, DAGNode):
        return b"node:" + ids[id(value)].encode()
    if isinstance(value, (list, tuple)):
        return (
            type(value).__name__.encode()
            + b"["
            + b",".join(_fingerprint(v, ids) for v in value)
            + b"]"
        )
    if isinstance(value, dict):
        return b"{" + b",".join(
            _fingerprint(k, ids) + b":" + _fingerprint(v, ids)
            for k, v in sorted(value.items(), key=lambda kv: repr(kv[0]))
        ) + b"}"
    import cloudpickle

    try:
        return cloudpickle.dumps(value)
    except Exception:
        return f"<{type(value).__module__}.{type(value).__qualname__}>".encode()


def _content_ids(order: list) -> dict:
    """id(node) -> content-derived step id, deterministic for a given DAG."""
    ids: dict[int, str] = {}
    seen: dict[str, int] = {}
    for node in order:
        h = hashlib.sha1()
        if isinstance(node, FunctionNode):
            fn = node._remote_fn.underlying_function
            try:
                h.update(inspect.getsource(fn).encode())
            except (OSError, TypeError):
                h.update(getattr(fn, "__qualname__", "fn").encode())
            name = fn.__name__
        else:
            name = type(node).__name__
            h.update(name.encode())
        for value in node._bound_args:
            h.update(_fingerprint(value, ids))
        for key, value in sorted(node._bound_kwargs.items()):
            h.update(f"{key}=".encode() + _fingerprint(value, ids))
        for key, value in sorted(getattr(node, "_options", {}).items()):
            h.update(f"opt:{key}={value!r}".encode())
        base = f"{name}-{h.hexdigest()[:12]}"
        # Two textually identical steps are distinct executions: suffix by
        # occurrence so both run (and both checkpoint).
        n = seen.get(base, 0)
        seen[base] = n + 1
        ids[id(node)] = base if n == 0 else f"{base}-{n}"
    return ids


def execute_workflow(
    storage: WorkflowStorage,
    dag,
    input_args,
    input_kwargs,
    max_retries: int = 0,
    catch_exceptions: bool = False,
    _namespace: str = "",
):
    """Run (or resume) the DAG durably; returns the final output.

    ``_namespace`` prefixes step ids — continuations (a step returning a
    DAGNode, reference workflow.continuation / api.py:712) execute their
    sub-DAG under ``<parent-step-id>/`` so sub-step results persist and
    replay independently of the parent's log.
    """
    import ray_tpu

    order = dag.topological_order()
    for node in order:
        if isinstance(node, (ClassNode, ClassMethodNode)):
            raise TypeError(
                "workflows support function nodes only (durable replay of "
                "actor state is not defined); got " + type(node).__name__
            )
    step_ids = {
        nid: _namespace + sid for nid, sid in _content_ids(order).items()
    }

    ctx = {"input_args": tuple(input_args), "input_kwargs": dict(input_kwargs)}
    results = {}  # id(node) -> ObjectRef (pending step) or final value
    final = {}  # id(node) -> final (continuation-resolved) value
    ctx["_results"] = results

    def _submit(node, sid):
        args, kwargs = node._resolved_args(results)
        opts = {k: v for k, v in node._options.items() if k != "catch_exceptions"}
        catch = bool(node._options.get("catch_exceptions", catch_exceptions))
        retries = opts.get("max_retries", max_retries)
        if retries:
            opts["max_retries"] = retries
            opts.setdefault("retry_exceptions", True)
        # Steps run under RAY_TPU_IN_WORKFLOW=1 so workflow.continuation
        # can tell workflow execution (defer: return the DAG) from plain
        # driver use (execute eagerly) — reference workflow_context.
        renv = dict(opts.get("runtime_env") or {})
        renv["env_vars"] = dict(renv.get("env_vars") or {}, RAY_TPU_IN_WORKFLOW="1")
        opts["runtime_env"] = renv
        ref = node._remote_fn.options(**opts).remote(*args, **kwargs)
        if catch:
            # Consumers see (result, error); boxing the ref defers its
            # materialization into the catch task itself.
            ref = _get_catch_task().remote([ref])
        return ref, catch

    def _deps_ready(node) -> bool:
        """Submission gate. TOP-LEVEL DAGNode args must hold their FINAL
        values — a pending ref could resolve to a continuation DAG, and
        piping that raw DAG into a consumer corrupts it. NESTED nodes
        (inside lists/dicts: the workflow.wait / catch idioms) deliberately
        flow as live ObjectRefs, so merely-submitted is enough for them —
        this is what keeps independent branches and wait() concurrent."""
        top = [v for v in node._bound_args if isinstance(v, DAGNode)]
        top += [v for v in node._bound_kwargs.values() if isinstance(v, DAGNode)]
        for child in node._children():
            if isinstance(child, FunctionNode):
                if any(child is t for t in top):
                    if id(child) not in final:
                        return False
                elif id(child) not in results:
                    return False
            elif id(child) not in results:
                return False
        return True

    # Completion-driven scheduling: every READY unfinished step is in
    # flight concurrently; results persist in COMPLETION order — a crash
    # mid-run keeps every step that finished, whatever branch it was on.
    todo = list(order)
    pending: dict = {}  # ref -> (sid, node)
    first_error = None
    while todo or pending:
        # Cancellation gate (workflow.cancel writes a durable marker): abort
        # in-flight steps best-effort and stop scheduling. Completed steps
        # stay persisted — a later resume replays them.
        if storage.cancel_requested():
            for ref in list(pending):
                try:
                    ray_tpu.cancel(ref)
                except Exception:
                    pass
            raise WorkflowCancellationError(
                f"workflow '{storage.workflow_id}' was cancelled"
            )
        progressed = False
        for node in list(todo):
            if isinstance(node, FunctionNode):
                sid = step_ids[id(node)]
                if storage.has_step_result(sid):
                    value = storage.load_step_result(sid)
                    results[id(node)] = final[id(node)] = value
                elif _deps_ready(node):
                    ref, catch = _submit(node, sid)
                    pending[ref] = (sid, node, catch)
                    results[id(node)] = ref
                else:
                    continue
            elif _deps_ready(node):
                args, kwargs = node._resolved_args(results)
                results[id(node)] = node._execute_impl(args, kwargs, ctx)
            else:
                continue
            todo.remove(node)
            progressed = True
        if not pending:
            if first_error is not None:
                break  # a failed step starves its consumers; surface it
            if todo and not progressed:
                raise RuntimeError(
                    "workflow made no progress (cyclic or unresolvable deps): "
                    + ", ".join(type(n).__name__ for n in todo)
                )
            continue
        # Bounded wait so a cancel landing mid-wait is noticed within ~1s
        # (a blocking wait would pin the executor until some step finished).
        done, _ = ray_tpu.wait(list(pending.keys()), num_returns=1, timeout=1.0)
        if not done:
            continue
        ref = done[0]
        sid, node, catch = pending.pop(ref)
        try:
            value = ray_tpu.get(ref)
        except Exception as e:  # noqa: BLE001 — recorded, then re-raised below
            if first_error is None:
                first_error = e
            continue
        # Continuation detection must see THROUGH the catch box: a caught
        # step's value is (result, error), and a returned sub-DAG rides in
        # the result slot.
        cont = None
        if isinstance(value, DAGNode):
            cont = value
        elif catch and isinstance(value, tuple) and len(value) == 2 and isinstance(value[0], DAGNode):
            cont = value[0]
        if cont is not None:
            # Continuation: the step returned a sub-DAG (dynamic workflow).
            # Execute it durably under this step's namespace; its output IS
            # the step's value. A crash mid-sub-DAG leaves the parent step
            # unpersisted, so resume re-runs the (deterministic) parent step
            # and replays the sub-DAG from its own persisted steps.
            try:
                sub = execute_workflow(
                    storage, cont, (), {},
                    max_retries=max_retries,
                    catch_exceptions=catch_exceptions,
                    _namespace=sid + "/",
                )
                value = (sub, None) if cont is not value else sub
            except WorkflowCancellationError:
                raise  # cancellation is not a step error; propagate
            except Exception as e:  # noqa: BLE001 — same contract as above
                if catch:
                    value = (None, e)  # the catch contract applies to the sub-DAG too
                else:
                    if first_error is None:
                        first_error = e
                    continue
            # Consumers need the MATERIALIZED sub-output (there is no ref
            # for it) — continuation steps forgo ref pass-through.
            results[id(node)] = value
        storage.save_step_result(sid, value)
        final[id(node)] = value
    if first_error is not None:
        raise first_error

    # Pass 3: non-function nodes (input projections, MultiOutput) captured
    # refs during scheduling; recompute them over MATERIALIZED values
    # (steps kept refs in `results` for pass-through; `final` holds their
    # completed values).
    view = dict(results)
    view.update(final)
    for node in order:
        if not isinstance(node, (FunctionNode, InputNode)):
            args, kwargs = node._resolved_args(view)
            view[id(node)] = node._execute_impl(args, kwargs, ctx)

    output = view[id(order[-1])]
    if not _namespace:  # sub-DAGs persist via their parent step, not as the
        storage.save_output(output)  # workflow's final output
        storage.save_status("SUCCESSFUL")
    return output
