"""Durable DAG executor.

Analog of the reference's WorkflowExecutor (python/ray/workflow/
workflow_executor.py:32): walks a ``ray_tpu.dag`` graph in deterministic
topological order, submits each FunctionNode as a task, materializes and
persists every step result before its dependents consume it, and skips steps
whose results are already in storage — which is exactly what makes
``workflow.resume`` a replay of the log.

Step identity is (topological index, function name): stable for the same DAG
because ``DAGNode.topological_order`` is a deterministic post-order.
"""

from __future__ import annotations

from ray_tpu.dag.dag_node import ClassMethodNode, ClassNode, FunctionNode, InputNode
from ray_tpu.workflow.workflow_storage import WorkflowStorage


def _step_id(index: int, node) -> str:
    if isinstance(node, FunctionNode):
        name = node._remote_fn.underlying_function.__name__
    else:
        name = type(node).__name__
    return f"{index}_{name}"


def execute_workflow(storage: WorkflowStorage, dag, input_args, input_kwargs):
    """Run (or resume) the DAG durably; returns the final output."""
    import ray_tpu

    order = dag.topological_order()
    for node in order:
        if isinstance(node, (ClassNode, ClassMethodNode)):
            raise TypeError(
                "workflows support function nodes only (durable replay of "
                "actor state is not defined); got " + type(node).__name__
            )

    ctx = {"input_args": tuple(input_args), "input_kwargs": dict(input_kwargs)}
    results = {}
    ctx["_results"] = results
    # Pass 1: submit every unfinished step eagerly, passing ObjectRefs of
    # earlier steps straight through — independent branches run concurrently
    # (a crash loses only results not yet persisted; resume re-runs those,
    # i.e. at-least-once execution, same as the reference).
    submitted = []
    for idx, node in enumerate(order):
        sid = _step_id(idx, node)
        if isinstance(node, FunctionNode) and storage.has_step_result(sid):
            results[id(node)] = storage.load_step_result(sid)
            continue
        args, kwargs = node._resolved_args(results)
        value = node._execute_impl(args, kwargs, ctx)
        if isinstance(node, FunctionNode):
            submitted.append((sid, node, value))
        results[id(node)] = value

    # Pass 2: materialize + persist each step result in submission order.
    for sid, node, ref in submitted:
        value = ray_tpu.get(ref)
        storage.save_step_result(sid, value)
        results[id(node)] = value

    # Pass 3: non-function nodes (input projections, MultiOutput) captured
    # refs during pass 1; recompute them over materialized values (pure).
    for node in order:
        if not isinstance(node, (FunctionNode, InputNode)):
            args, kwargs = node._resolved_args(results)
            results[id(node)] = node._execute_impl(args, kwargs, ctx)

    output = results[id(order[-1])]
    storage.save_output(output)
    storage.save_status("SUCCESSFUL")
    return output
