"""Durable DAG executor.

Analog of the reference's WorkflowExecutor (python/ray/workflow/
workflow_executor.py:32): submits every unfinished FunctionNode eagerly
(independent branches run CONCURRENTLY), persists each step result the
moment it completes (completion order, not submission order — a crash
mid-run loses only unfinished steps), and skips steps whose results are
already in storage, which is what makes ``workflow.resume`` a replay of
the log.

Step identity is CONTENT-DERIVED (reference: workflow step names +
checkpoint identity): a hash of the step function's source, its static
arguments, its options, and its dependencies' step ids. Editing the DAG
(different code, args, or wiring) therefore changes the id and re-runs the
step — a positional id would silently replay a stale result into new code.

Failure semantics (reference: workflow error handling, api.py options):
- ``max_retries`` — application exceptions re-run the step N times (rides
  the task layer's retry_exceptions machinery);
- ``catch_exceptions`` — the step's consumers receive ``(result, None)``
  or ``(None, exception)`` instead of the workflow failing.
Both are per-step via ``node.options(...)`` with run-level defaults.
"""

from __future__ import annotations

import hashlib
import inspect

from ray_tpu.dag.dag_node import ClassMethodNode, ClassNode, DAGNode, FunctionNode, InputNode
from ray_tpu.workflow.workflow_storage import WorkflowStorage

_catch_task = None


def _get_catch_task():
    """A tiny task that boxes a step's outcome as (result, error)."""
    global _catch_task
    if _catch_task is None:
        import ray_tpu

        @ray_tpu.remote(num_cpus=0)
        def __workflow_catch__(boxed):
            import ray_tpu as _r
            from ray_tpu.exceptions import TaskError

            try:
                return (_r.get(boxed[0]), None)
            except TaskError as e:
                # The consumer wants the APPLICATION exception (reference:
                # catch_exceptions yields the original error).
                return (None, e.cause if e.cause is not None else e)
            except Exception as e:  # noqa: BLE001 — the caught value IS the product
                return (None, e)

        _catch_task = __workflow_catch__
    return _catch_task


def _fingerprint(value, ids: dict) -> bytes:
    """Stable-ish bytes for a bound argument. DAGNodes — INCLUDING nodes
    nested inside lists/tuples/dicts, which _resolved_args supports — map
    to their step ids so a changed dependency propagates into every
    consumer's id. Leaves pickle (not repr: default reprs embed object
    ADDRESSES and truncate arrays); unpicklable leaves fall back to the
    type name — coarse, but deterministic."""
    if isinstance(value, DAGNode):
        return b"node:" + ids[id(value)].encode()
    if isinstance(value, (list, tuple)):
        return (
            type(value).__name__.encode()
            + b"["
            + b",".join(_fingerprint(v, ids) for v in value)
            + b"]"
        )
    if isinstance(value, dict):
        return b"{" + b",".join(
            _fingerprint(k, ids) + b":" + _fingerprint(v, ids)
            for k, v in sorted(value.items(), key=lambda kv: repr(kv[0]))
        ) + b"}"
    import cloudpickle

    try:
        return cloudpickle.dumps(value)
    except Exception:
        return f"<{type(value).__module__}.{type(value).__qualname__}>".encode()


def _content_ids(order: list) -> dict:
    """id(node) -> content-derived step id, deterministic for a given DAG."""
    ids: dict[int, str] = {}
    seen: dict[str, int] = {}
    for node in order:
        h = hashlib.sha1()
        if isinstance(node, FunctionNode):
            fn = node._remote_fn.underlying_function
            try:
                h.update(inspect.getsource(fn).encode())
            except (OSError, TypeError):
                h.update(getattr(fn, "__qualname__", "fn").encode())
            name = fn.__name__
        else:
            name = type(node).__name__
            h.update(name.encode())
        for value in node._bound_args:
            h.update(_fingerprint(value, ids))
        for key, value in sorted(node._bound_kwargs.items()):
            h.update(f"{key}=".encode() + _fingerprint(value, ids))
        for key, value in sorted(getattr(node, "_options", {}).items()):
            h.update(f"opt:{key}={value!r}".encode())
        base = f"{name}-{h.hexdigest()[:12]}"
        # Two textually identical steps are distinct executions: suffix by
        # occurrence so both run (and both checkpoint).
        n = seen.get(base, 0)
        seen[base] = n + 1
        ids[id(node)] = base if n == 0 else f"{base}-{n}"
    return ids


def execute_workflow(
    storage: WorkflowStorage,
    dag,
    input_args,
    input_kwargs,
    max_retries: int = 0,
    catch_exceptions: bool = False,
):
    """Run (or resume) the DAG durably; returns the final output."""
    import ray_tpu

    order = dag.topological_order()
    for node in order:
        if isinstance(node, (ClassNode, ClassMethodNode)):
            raise TypeError(
                "workflows support function nodes only (durable replay of "
                "actor state is not defined); got " + type(node).__name__
            )
    step_ids = _content_ids(order)

    ctx = {"input_args": tuple(input_args), "input_kwargs": dict(input_kwargs)}
    results = {}
    ctx["_results"] = results
    # Pass 1: submit every unfinished step eagerly, passing ObjectRefs of
    # earlier steps straight through — independent branches run concurrently.
    pending: dict = {}  # ref -> (sid, node)
    for node in order:
        if isinstance(node, FunctionNode):
            sid = step_ids[id(node)]
            if storage.has_step_result(sid):
                results[id(node)] = storage.load_step_result(sid)
                continue
            args, kwargs = node._resolved_args(results)
            opts = {k: v for k, v in node._options.items() if k != "catch_exceptions"}
            catch = bool(node._options.get("catch_exceptions", catch_exceptions))
            retries = opts.get("max_retries", max_retries)
            if retries:
                opts["max_retries"] = retries
                opts.setdefault("retry_exceptions", True)
            fn = node._remote_fn.options(**opts) if opts else node._remote_fn
            ref = fn.remote(*args, **kwargs)
            if catch:
                # Consumers see (result, error); boxing the ref defers its
                # materialization into the catch task itself.
                ref = _get_catch_task().remote([ref])
            pending[ref] = (sid, node)
            results[id(node)] = ref
        else:
            args, kwargs = node._resolved_args(results)
            results[id(node)] = node._execute_impl(args, kwargs, ctx)

    # Pass 2: persist step results in COMPLETION order — a crash mid-run
    # keeps every step that finished, whatever branch it was on.
    first_error = None
    remaining = dict(pending)
    while remaining:
        done, _ = ray_tpu.wait(list(remaining.keys()), num_returns=1)
        ref = done[0]
        sid, node = remaining.pop(ref)
        try:
            value = ray_tpu.get(ref)
        except Exception as e:  # noqa: BLE001 — recorded, then re-raised below
            if first_error is None:
                first_error = e
            continue
        storage.save_step_result(sid, value)
        results[id(node)] = value
    if first_error is not None:
        raise first_error

    # Pass 3: non-function nodes (input projections, MultiOutput) captured
    # refs during pass 1; recompute them over materialized values (pure).
    for node in order:
        if not isinstance(node, (FunctionNode, InputNode)):
            args, kwargs = node._resolved_args(results)
            results[id(node)] = node._execute_impl(args, kwargs, ctx)

    output = results[id(order[-1])]
    storage.save_output(output)
    storage.save_status("SUCCESSFUL")
    return output
