"""Workflow event listeners (analog of reference
python/ray/workflow/event_listener.py:11 ``EventListener`` and
http_event_provider.py's pollable HTTP provider).

``workflow.wait_for_event(ListenerType, *args)`` builds a two-step DAG
(poll -> commit, reference api.py:557): the poll step blocks until the
listener returns an event; once the event value is DURABLY persisted by the
executor, ``event_checkpointed`` fires so an external provider can commit
(e.g. ack a queue offset). A driver killed mid-poll leaves no persisted
result, so ``workflow.resume`` re-polls — delivery is effectively
at-least-once with exactly-once workflow consumption.

``KVEventListener`` is the built-in pollable provider: it watches a GCS KV
key that external systems set either directly (``kv_put``) or over HTTP via
the dashboard route ``POST /api/workflows/events/<key>`` — the HTTP event
provider analog.
"""

from __future__ import annotations

import asyncio
import inspect
import json
import time

EVENT_KV_PREFIX = "workflow:event:"


class EventListener:
    """Subclass with ``poll_for_event`` (sync or async) and optionally
    ``event_checkpointed``. Listeners must be stateless — they are
    re-instantiated (possibly in a different process) on resume."""

    def __init__(self):
        pass

    def poll_for_event(self, *args, **kwargs):
        """Return only when the event has arrived."""
        raise NotImplementedError

    def event_checkpointed(self, event) -> None:
        """Called after the event is durably checkpointed; commit side
        effects (e.g. ack the message) here."""


def run_listener_method(method, *args, **kwargs):
    """Call a listener method, awaiting it if it is async."""
    result = method(*args, **kwargs)
    if inspect.iscoroutine(result):
        return asyncio.run(result)
    return result


class KVEventListener(EventListener):
    """Polls the cluster KV for ``workflow:event:<key>`` (JSON payload).

    Producers: ``ray_tpu.workflow.deliver_event(key, payload)`` from any
    driver/worker, or ``POST /api/workflows/events/<key>`` on the dashboard.
    """

    poll_interval_s = 0.25

    def poll_for_event(self, key: str):
        from ray_tpu._private import worker_context

        cw = worker_context.get_core_worker()
        full = EVENT_KV_PREFIX + key
        while True:
            resp = cw.gcs.call("kv_get", {"key": full})
            if resp.get("found"):
                return json.loads(bytes(resp["value"]).decode())
            time.sleep(self.poll_interval_s)


def deliver_event(key: str, payload) -> None:
    """Publish an event for ``KVEventListener(key)`` pollers (what the
    dashboard's POST /api/workflows/events/<key> route calls)."""
    from ray_tpu._private import worker_context

    cw = worker_context.get_core_worker()
    cw.gcs.call(
        "kv_put",
        {
            "key": EVENT_KV_PREFIX + key,
            "value": json.dumps(payload).encode(),
            "overwrite": True,
        },
    )
