"""Event-driven actor lifecycle manager for the AIR execution layer.

Analog of the reference's ``RayActorManager``
(python/ray/air/execution/_internal/actor_manager.py): library controllers
(Tune's trial loop, Train's BackendExecutor) hand actor lifecycle to ONE
audited component instead of each hand-rolling restart/leak semantics.

Model:

- ``add_actor(cls, kwargs, resource_request, ...) -> TrackedActor`` tracks a
  logical actor. Resources are acquired through the ``ResourceManager``
  (refcounted per request instance, so a gang of N actors sharing one
  N-bundle request holds exactly one placement group); the actor process is
  created once the request is ready and ``on_actor_start`` fires when the
  GCS reports it ALIVE.
- ``schedule_actor_task(tracked, method, ...)`` schedules a method call with
  per-task ``on_result``/``on_error`` callbacks. Tasks scheduled before the
  actor is up are queued and submitted on start.
- Process-level death (``ActorDiedError``/``WorkerCrashedError``/...) is an
  ACTOR failure: in-flight tasks are swallowed, ``on_actor_failure(tracked,
  error, will_restart)`` fires, and if the tracked restart budget allows,
  the manager recreates the actor after an exponential backoff —
  ``restart_count`` increments, ``kwargs_fn`` (if given) re-resolves the
  constructor kwargs so a restart can pick up e.g. the latest checkpoint,
  and ``on_actor_start`` fires again. Application exceptions raised by the
  method are TASK errors: ``on_error`` fires, the actor stays alive.
- ``remove_actor`` cleanly cancels in-flight tasks (their callbacks never
  fire), kills the process, fires ``on_actor_stop``, and releases the
  resource acquisition once its last user is gone — guaranteed even when
  the actor died mid-start or mid-task.
- ``next(timeout)`` drives everything: starts due/pending actors, waits on
  in-flight task futures, dispatches callbacks. Callbacks run on the caller
  thread and may reentrantly call manager methods (remove/add/schedule).
"""

from __future__ import annotations

import itertools
import logging
import time
from typing import Any, Callable, Optional

from ray_tpu.air.execution.resources import (
    AcquiredResources,
    FixedResourceManager,
    ResourceManager,
    ResourceRequest,
)

logger = logging.getLogger(__name__)

# TrackedActor states
PENDING = "PENDING"  # waiting for resources
STARTING = "STARTING"  # actor creation submitted, not ALIVE yet
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"  # failed, waiting out the backoff
STOPPED = "STOPPED"  # removed by the consumer
FAILED = "FAILED"  # failed with no restart budget left

_FAILURE_EXC_NAMES = (
    "ActorDiedError",
    "ActorUnavailableError",
    "ActorError",
    "WorkerCrashedError",
    "NodeDiedError",
    "OwnerDiedError",
    # The memory monitor kills the whole worker process hosting the actor,
    # so an OOM surfacing from an actor task implies process death.
    "OutOfMemoryError",
)


def _is_actor_failure(exc: BaseException) -> bool:
    """Process-level death vs an application exception raised by the method."""
    from ray_tpu import exceptions as exc_mod

    for name in _FAILURE_EXC_NAMES:
        cls = getattr(exc_mod, name, None)
        if cls is not None and isinstance(exc, cls):
            return True
    return False


class TrackedActorTask:
    """Handle for one scheduled method call."""

    __slots__ = ("tracked_actor", "method", "args", "kwargs", "on_result", "on_error", "ref")

    def __init__(self, tracked_actor, method, args, kwargs, on_result, on_error):
        self.tracked_actor = tracked_actor
        self.method = method
        self.args = args
        self.kwargs = kwargs
        self.on_result = on_result
        self.on_error = on_error
        self.ref = None  # ObjectRef once submitted


class TrackedActor:
    """A logical actor whose identity survives process restarts."""

    _ids = itertools.count()

    def __init__(
        self,
        cls,
        kwargs: dict,
        *,
        resource_request: ResourceRequest,
        bundle_index: int = 0,
        kwargs_fn: Optional[Callable[[], dict]] = None,
        on_start: Optional[Callable[["TrackedActor"], None]] = None,
        on_stop: Optional[Callable[["TrackedActor"], None]] = None,
        on_failure: Optional[Callable[["TrackedActor", BaseException, bool], None]] = None,
        max_restarts: int = 0,
        restart_backoff_s: float = 0.5,
        graceful_stop_method: str | None = None,
        actor_options: dict | None = None,
    ):
        self.tracked_id = next(self._ids)
        self.state = PENDING
        self.actor_handle = None
        self.actor_id: str | None = None
        self.restart_count = 0
        self.last_error: BaseException | None = None
        self._cls = cls
        self._kwargs = dict(kwargs or {})
        self._kwargs_fn = kwargs_fn
        self.resource_request = resource_request
        self.bundle_index = bundle_index
        self.on_start = on_start
        self.on_stop = on_stop
        self.on_failure = on_failure
        self.max_restarts = max_restarts
        self.restart_backoff_s = restart_backoff_s
        self.graceful_stop_method = graceful_stop_method
        # Extra .options() entries (name=, max_concurrency=, runtime_env=,
        # ...) overlaid on the acquisition-derived scheduling options —
        # library controllers with named actors (Serve) need both.
        self.actor_options = dict(actor_options or {})
        self._restart_due = 0.0  # monotonic time the next restart may run
        self._queued_tasks: list[TrackedActorTask] = []

    @property
    def is_live(self) -> bool:
        return self.state in (PENDING, STARTING, ALIVE, RESTARTING)

    def _constructor_kwargs(self) -> dict:
        return dict(self._kwargs_fn()) if self._kwargs_fn is not None else dict(self._kwargs)

    def __repr__(self):
        return (
            f"TrackedActor(#{self.tracked_id}, {self.state}, "
            f"restarts={self.restart_count})"
        )


class ActorManager:
    """Tracks pooled actors, their tasks, and their resource acquisitions."""

    def __init__(self, resource_manager: ResourceManager | None = None):
        self.resource_manager = resource_manager or FixedResourceManager()
        self._tracked: list[TrackedActor] = []
        # resource refcounting: request instance -> (AcquiredResources, users)
        self._acquisitions: dict[int, list] = {}  # id(request) -> [acq, set(tracked)]
        self._inflight: dict[Any, TrackedActorTask] = {}  # ObjectRef -> task
        self._last_state_poll = 0.0

    # -- introspection -----------------------------------------------------

    @property
    def all_actors(self) -> list[TrackedActor]:
        return list(self._tracked)

    @property
    def num_live_actors(self) -> int:
        return sum(1 for t in self._tracked if t.state == ALIVE)

    @property
    def num_pending_actors(self) -> int:
        return sum(1 for t in self._tracked if t.state in (PENDING, STARTING, RESTARTING))

    @property
    def num_tracked_actors(self) -> int:
        return sum(1 for t in self._tracked if t.is_live)

    # -- actor lifecycle ---------------------------------------------------

    def add_actor(
        self,
        cls,
        kwargs: dict | None = None,
        *,
        resource_request: ResourceRequest | None = None,
        bundle_index: int = 0,
        kwargs_fn: Optional[Callable[[], dict]] = None,
        on_start=None,
        on_stop=None,
        on_failure=None,
        max_restarts: int = 0,
        restart_backoff_s: float = 0.5,
        graceful_stop_method: str | None = None,
        actor_options: dict | None = None,
    ) -> TrackedActor:
        """Track a new actor. Creation is asynchronous: the actor process
        starts once ``resource_request`` is ready (driven by ``next()``)."""
        if resource_request is None:
            resource_request = ResourceRequest([{"CPU": 1}])
        tracked = TrackedActor(
            cls,
            kwargs or {},
            resource_request=resource_request,
            bundle_index=bundle_index,
            kwargs_fn=kwargs_fn,
            on_start=on_start,
            on_stop=on_stop,
            on_failure=on_failure,
            max_restarts=max_restarts,
            restart_backoff_s=restart_backoff_s,
            graceful_stop_method=graceful_stop_method,
            actor_options=actor_options,
        )
        self._tracked.append(tracked)
        if id(resource_request) not in self._acquisitions:
            self.resource_manager.request_resources(resource_request)
        self._try_create(tracked)
        return tracked

    def remove_actor(self, tracked: TrackedActor, kill: bool = True) -> None:
        """Stop tracking: cancel in-flight tasks (no callbacks), kill the
        process, fire ``on_actor_stop``, release resources."""
        if tracked.state in (STOPPED, FAILED):
            return
        was_alive = tracked.state == ALIVE
        tracked.state = STOPPED
        self._cancel_inflight(tracked)
        tracked._queued_tasks.clear()
        if kill and tracked.actor_handle is not None:
            import ray_tpu

            if tracked.graceful_stop_method:
                # Best-effort, fire-and-forget (matches the pre-manager Tune
                # behavior: stop.remote() immediately followed by kill).
                try:
                    getattr(tracked.actor_handle, tracked.graceful_stop_method).remote()
                except Exception:
                    pass
            try:
                ray_tpu.kill(tracked.actor_handle)
            except Exception:
                pass
        tracked.actor_handle = None
        self._release_resources(tracked)
        self._forget(tracked)
        if was_alive and tracked.on_stop is not None:
            self._safe_callback(tracked.on_stop, tracked)

    def restart_actor(self, tracked: TrackedActor) -> None:
        """Consumer-initiated restart (e.g. retry an errored trial from a
        checkpoint): kill the current process, keep the acquisition, recreate
        immediately (no backoff) with freshly-resolved kwargs. Increments
        ``restart_count``, and that IS the counter the automatic restart
        budget checks — explicit and failure-driven restarts share one
        budget, so a consumer retrying app errors spends the same
        ``max_restarts`` allowance as process deaths (what Tune's
        ``max_failures`` semantics require)."""
        if not tracked.is_live:
            raise ValueError(f"cannot restart {tracked}: not live")
        self._cancel_inflight(tracked)
        if tracked.actor_handle is not None:
            import ray_tpu

            try:
                ray_tpu.kill(tracked.actor_handle)
            except Exception:
                pass
            tracked.actor_handle = None
        tracked.restart_count += 1
        tracked._restart_due = 0.0
        tracked.state = PENDING
        self._try_create(tracked)

    def clear(self) -> None:
        """Remove every tracked actor and release every acquisition."""
        for tracked in list(self._tracked):
            if tracked.is_live:
                self.remove_actor(tracked)
        self._tracked.clear()
        self._inflight.clear()
        self._acquisitions.clear()
        self.resource_manager.clear()

    def _forget(self, tracked: TrackedActor) -> None:
        """Stop scanning a terminally dead actor. The TrackedActor object
        stays valid for its holder; the manager just drops it so a long-lived
        controller (thousands of completed trials) doesn't accumulate dead
        entries in every _start_phase pass."""
        try:
            self._tracked.remove(tracked)
        except ValueError:
            pass

    # -- task scheduling ---------------------------------------------------

    def schedule_actor_task(
        self,
        tracked: TrackedActor,
        method: str,
        args: tuple = (),
        kwargs: dict | None = None,
        *,
        on_result: Optional[Callable[[Any], None]] = None,
        on_error: Optional[Callable[[BaseException], None]] = None,
    ) -> TrackedActorTask:
        """Schedule ``method`` on the tracked actor. If the actor is not up
        yet (or is restarting), the task is queued and submitted on start."""
        if not tracked.is_live:
            raise ValueError(f"cannot schedule task on {tracked}: not live")
        task = TrackedActorTask(tracked, method, args, dict(kwargs or {}), on_result, on_error)
        if tracked.state == ALIVE and tracked.actor_handle is not None:
            self._submit(task)
        else:
            tracked._queued_tasks.append(task)
        return task

    def _submit(self, task: TrackedActorTask) -> None:
        handle = task.tracked_actor.actor_handle
        ref = getattr(handle, task.method).remote(*task.args, **task.kwargs)
        task.ref = ref
        self._inflight[ref] = task

    def _cancel_inflight(self, tracked: TrackedActor) -> None:
        for ref, task in list(self._inflight.items()):
            if task.tracked_actor is tracked:
                del self._inflight[ref]

    # -- event loop --------------------------------------------------------

    def next(self, timeout: float | None = 5.0) -> bool:
        """Drive one batch of events: start ready/due actors, then wait up
        to ``timeout`` for a task future and dispatch callbacks. Returns
        True if any event (start, result, error, failure) was processed."""
        import ray_tpu

        progressed = self._start_phase()

        refs = list(self._inflight.keys())
        if not refs:
            if not progressed and self._has_unstarted():
                # Nothing in flight and actors still coming up: yield briefly
                # instead of a hot spin in caller loops.
                time.sleep(min(0.05, timeout or 0.05))
                progressed = self._start_phase() or progressed
            return progressed
        ready, _ = ray_tpu.wait(
            refs, num_returns=1, timeout=0 if progressed else timeout
        )
        # Grab every already-finished future in one sweep (cheap second wait).
        if ready:
            more, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=0)
            ready = more or ready
        for ref in ready:
            task = self._inflight.pop(ref, None)
            if task is None:
                continue  # cancelled while we were waiting
            tracked = task.tracked_actor
            try:
                value = ray_tpu.get(ref)
            except Exception as e:  # noqa: BLE001 — classified below
                if _is_actor_failure(e):
                    self._handle_actor_failure(tracked, e)
                elif task.on_error is not None:
                    self._safe_callback(task.on_error, e)
                progressed = True
                continue
            if task.on_result is not None:
                self._safe_callback(task.on_result, value)
            progressed = True
        return progressed

    def wait_for_actors(
        self, actors: list[TrackedActor], timeout: float = 300.0
    ) -> None:
        """Block until every listed actor is ALIVE. Raises TimeoutError on
        timeout and RuntimeError if one terminally fails while waiting."""
        deadline = time.monotonic() + timeout
        while True:
            if all(t.state == ALIVE for t in actors):
                return
            dead = [t for t in actors if t.state in (FAILED, STOPPED)]
            if dead:
                raise RuntimeError(f"actor(s) failed during start: {dead}")
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"actors not up after {timeout}s: "
                    f"{[t for t in actors if t.state != ALIVE]}"
                )
            self.next(timeout=0.5)

    # -- internals ---------------------------------------------------------

    def _has_unstarted(self) -> bool:
        return any(t.state in (PENDING, STARTING, RESTARTING) for t in self._tracked)

    def _start_phase(self) -> bool:
        progressed = False
        now = time.monotonic()
        for tracked in list(self._tracked):
            if tracked.state == RESTARTING and now >= tracked._restart_due:
                tracked.state = PENDING
            if tracked.state == PENDING:
                progressed = self._try_create(tracked) or progressed
            if tracked.state == STARTING:
                progressed = self._poll_starting(tracked) or progressed
        # Periodic liveness poll for idle ALIVE actors: an actor with no
        # in-flight task has no error channel, so its death would otherwise
        # go unnoticed until the next task.
        if now - self._last_state_poll >= 0.5:
            self._last_state_poll = now
            busy = {t.tracked_actor for t in self._inflight.values()}
            for tracked in list(self._tracked):
                if tracked.state == ALIVE and tracked not in busy:
                    progressed = self._poll_alive(tracked) or progressed
        return progressed

    def _acquire_for(self, tracked: TrackedActor) -> AcquiredResources | None:
        key = id(tracked.resource_request)
        entry = self._acquisitions.get(key)
        if entry is not None:
            entry[1].add(tracked)
            return entry[0]
        if not self.resource_manager.has_resources_ready(tracked.resource_request):
            return None
        acq = self.resource_manager.acquire_resources(tracked.resource_request)
        if acq is None:
            return None
        self._acquisitions[key] = [acq, {tracked}]
        return acq

    def _release_resources(self, tracked: TrackedActor) -> None:
        key = id(tracked.resource_request)
        entry = self._acquisitions.get(key)
        if entry is None:
            # Never acquired: drop the outstanding request (refcount it too —
            # a gang shares one request, cancel only when no live user left).
            if not any(
                t.is_live and id(t.resource_request) == key for t in self._tracked
            ):
                self.resource_manager.cancel_resource_request(tracked.resource_request)
            return
        acq, users = entry
        users.discard(tracked)
        if not users:
            del self._acquisitions[key]
            self.resource_manager.free_resources(acq)

    def _try_create(self, tracked: TrackedActor) -> bool:
        acq = self._acquire_for(tracked)
        if acq is None:
            return False
        from ray_tpu.actor import ActorClass

        cls = tracked._cls
        if not isinstance(cls, ActorClass):
            import ray_tpu

            cls = ray_tpu.remote(cls)
        opts = acq.actor_options(tracked.bundle_index)
        opts.update(tracked.actor_options)
        # GCS-level restart stays OFF: restarts are manager-tracked so
        # callbacks fire and constructor kwargs re-resolve (a GCS restart
        # would silently hand back a fresh instance with stale state).
        opts["max_restarts"] = 0
        try:
            tracked.actor_handle = cls.options(**opts).remote(
                **tracked._constructor_kwargs()
            )
            tracked.actor_id = tracked.actor_handle.actor_id
            tracked.state = STARTING
        except Exception as e:  # noqa: BLE001 — creation failure is actor failure
            self._handle_actor_failure(tracked, e)
        return True

    def _actor_state(self, tracked: TrackedActor) -> dict | None:
        from ray_tpu._private import worker_context

        try:
            cw = worker_context.get_core_worker()
            resp = cw.gcs.call("get_actor", {"actor_id": tracked.actor_id})
        except Exception:
            return None
        if not resp.get("found"):
            return None
        return resp["info"]

    def _poll_starting(self, tracked: TrackedActor) -> bool:
        info = self._actor_state(tracked)
        if info is None:
            return False
        state = info.get("state")
        if state == "ALIVE":
            tracked.state = ALIVE
            queued, tracked._queued_tasks = tracked._queued_tasks, []
            if tracked.on_start is not None:
                self._safe_callback(tracked.on_start, tracked)
            # on_start may have scheduled tasks or removed the actor; only
            # flush the pre-start queue if the actor is still alive.
            if tracked.state == ALIVE:
                for task in queued:
                    self._submit(task)
            return True
        if state == "DEAD":
            from ray_tpu.exceptions import ActorDiedError

            self._handle_actor_failure(
                tracked,
                ActorDiedError(
                    f"actor died during start: {info.get('death_cause') or 'unknown'}",
                ),
            )
            return True
        return False

    def _poll_alive(self, tracked: TrackedActor) -> bool:
        info = self._actor_state(tracked)
        if info is None:
            return False
        if info.get("state") == "DEAD":
            from ray_tpu.exceptions import ActorDiedError

            self._handle_actor_failure(
                tracked,
                ActorDiedError(
                    f"actor process died: {info.get('death_cause') or 'unknown'}",
                ),
            )
            return True
        return False

    def _handle_actor_failure(self, tracked: TrackedActor, error: BaseException) -> None:
        if tracked.state in (STOPPED, FAILED):
            return
        tracked.last_error = error
        self._cancel_inflight(tracked)
        tracked.actor_handle = None
        will_restart = (
            tracked.max_restarts < 0 or tracked.restart_count < tracked.max_restarts
        )
        if will_restart:
            tracked.restart_count += 1
            tracked.state = RESTARTING
            tracked._restart_due = time.monotonic() + tracked.restart_backoff_s * (
                2 ** max(0, tracked.restart_count - 1)
            )
            logger.warning(
                "tracked actor #%d failed (%s); restart %d scheduled in %.1fs",
                tracked.tracked_id,
                error,
                tracked.restart_count,
                tracked._restart_due - time.monotonic(),
            )
        else:
            tracked.state = FAILED
            self._release_resources(tracked)
            self._forget(tracked)
        if tracked.on_failure is not None:
            self._safe_callback(tracked.on_failure, tracked, error, will_restart)

    @staticmethod
    def _safe_callback(cb, *args) -> None:
        try:
            cb(*args)
        except Exception:
            logger.exception("actor manager callback %r raised", cb)
