"""Resource acquisition for the AIR execution layer.

Analog of the reference's ``python/ray/air/execution/resources/`` —
``ResourceRequest`` describes what an execution unit needs (one or more
bundles plus a placement strategy), a ``ResourceManager`` turns requests into
``AcquiredResources`` that annotate actors with the right scheduling options,
and — the robustness point of this layer — guarantees release: every
acquisition is tracked until freed, ``clear()`` force-releases everything,
and the placement-group manager removes its PGs even when an actor died
mid-start or mid-task (the pre-existing Train restart path leaked one PG per
gang restart precisely because release lived in consumer code).

Two implementations:

- ``FixedResourceManager`` — plain-resource bookkeeping against a fixed
  budget (defaults to the cluster totals). Acquired bundles translate to
  per-actor ``num_cpus``/``num_tpus``/``resources`` options; the raylet
  enforces them, the manager only tracks the budget so callers can gate
  how much work they launch.
- ``PlacementGroupResourceManager`` — each request is backed by a placement
  group (gang reservation; STRICT_PACK = one ICI domain for TPU gangs).
  Bundles map to ``PlacementGroupSchedulingStrategy(pg, bundle_index)``.

Requests are compared by IDENTITY, not value: two equal-looking requests are
two reservations. A multi-bundle request is acquired and released as a unit
(gang semantics), which is what lets the ActorManager refcount one placement
group across a whole worker gang.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field

logger = logging.getLogger(__name__)


@dataclass(eq=False)
class ResourceRequest:
    """What one execution unit (trial actor, worker gang) needs.

    ``bundles`` is a list of resource dicts — one per actor that will be
    scheduled against this request. ``strategy`` only matters for
    placement-group-backed managers.
    """

    bundles: list[dict]
    strategy: str = "PACK"

    def __post_init__(self):
        if not self.bundles or any(not isinstance(b, dict) or not b for b in self.bundles):
            raise ValueError("ResourceRequest needs non-empty resource-dict bundles")
        self.bundles = [dict(b) for b in self.bundles]

    @property
    def required_resources(self) -> dict:
        total: dict[str, float] = {}
        for b in self.bundles:
            for k, v in b.items():
                total[k] = total.get(k, 0) + v
        return total

    def __repr__(self):
        return f"ResourceRequest({self.bundles}, strategy={self.strategy!r})"


@dataclass(eq=False)
class AcquiredResources:
    """A satisfied request. ``actor_options(i)`` yields the ``.options()``
    dict that pins an actor to bundle ``i`` of this acquisition."""

    request: ResourceRequest
    placement_group: object | None = None
    _freed: bool = field(default=False, repr=False)

    def actor_options(self, bundle_index: int = 0) -> dict:
        if not 0 <= bundle_index < len(self.request.bundles):
            raise IndexError(
                f"bundle_index {bundle_index} out of range for "
                f"{len(self.request.bundles)} bundles"
            )
        bundle = dict(self.request.bundles[bundle_index])
        opts: dict = {}
        if self.placement_group is not None:
            from ray_tpu.util.scheduling_strategies import (
                PlacementGroupSchedulingStrategy,
            )

            opts["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                self.placement_group, bundle_index
            )
            # The PG bundle already reserved the resources; the actor still
            # declares them so the raylet accounts its usage inside the bundle.
        ncpu = bundle.pop("CPU", None)
        ntpu = bundle.pop("TPU", None)
        if ncpu:
            opts["num_cpus"] = ncpu
        if ntpu:
            opts["num_tpus"] = ntpu
        if bundle:
            opts["resources"] = bundle
        return opts


class ResourceManager:
    """Base interface. Lifecycle of one request:

    ``request_resources(req)`` (idempotent) -> poll ``has_resources_ready``
    -> ``acquire_resources(req) -> AcquiredResources`` -> eventually
    ``free_resources(acquired)``. ``cancel_resource_request`` abandons a
    request that was never acquired. ``clear()`` releases everything this
    manager handed out or still has pending — the guaranteed-release hook
    consumers call from their own teardown paths.
    """

    def request_resources(self, request: ResourceRequest) -> None:
        raise NotImplementedError

    def cancel_resource_request(self, request: ResourceRequest) -> None:
        raise NotImplementedError

    def has_resources_ready(self, request: ResourceRequest) -> bool:
        raise NotImplementedError

    def acquire_resources(self, request: ResourceRequest) -> AcquiredResources | None:
        raise NotImplementedError

    def free_resources(self, acquired: AcquiredResources) -> None:
        raise NotImplementedError

    def clear(self) -> None:
        raise NotImplementedError


class FixedResourceManager(ResourceManager):
    """Budget bookkeeping over plain resources (no gang atomicity).

    The budget defaults to the cluster totals at first use. Acquisition
    subtracts the request's total; release adds it back. Used where trial
    actors request ordinary resources and the raylet does the real
    enforcement — the manager's job is leak-proof accounting so a failed
    actor always returns its slice of the budget.
    """

    def __init__(self, total_resources: dict | None = None):
        self._lock = threading.RLock()
        self._total = dict(total_resources) if total_resources else None
        self._used: dict[str, float] = {}
        self._pending: list[ResourceRequest] = []
        self._acquired: list[AcquiredResources] = []

    def _budget(self) -> dict:
        if self._total is None:
            import ray_tpu

            try:
                self._total = dict(ray_tpu.cluster_resources())
            except Exception:
                self._total = {}
        return self._total

    def request_resources(self, request: ResourceRequest) -> None:
        with self._lock:
            if request not in self._pending:
                self._pending.append(request)

    def cancel_resource_request(self, request: ResourceRequest) -> None:
        with self._lock:
            if request in self._pending:
                self._pending.remove(request)

    def has_resources_ready(self, request: ResourceRequest) -> bool:
        with self._lock:
            budget = self._budget()
            for k, v in request.required_resources.items():
                # Unknown resource kinds are treated as available: on a
                # growing cluster (autoscaler) the raylet is authoritative.
                if k in budget and self._used.get(k, 0) + v > budget[k]:
                    return False
            return True

    def acquire_resources(self, request: ResourceRequest) -> AcquiredResources | None:
        with self._lock:
            if not self.has_resources_ready(request):
                return None
            for k, v in request.required_resources.items():
                self._used[k] = self._used.get(k, 0) + v
            if request in self._pending:
                self._pending.remove(request)
            acq = AcquiredResources(request=request)
            self._acquired.append(acq)
            return acq

    def free_resources(self, acquired: AcquiredResources) -> None:
        with self._lock:
            if acquired._freed:
                return
            acquired._freed = True
            if acquired in self._acquired:
                self._acquired.remove(acquired)
            for k, v in acquired.request.required_resources.items():
                self._used[k] = max(0.0, self._used.get(k, 0) - v)

    def clear(self) -> None:
        with self._lock:
            for acq in list(self._acquired):
                self.free_resources(acq)
            self._pending.clear()
            self._used.clear()


class PlacementGroupResourceManager(ResourceManager):
    """Placement-group-backed acquisition: every request creates a PG with
    the request's bundles/strategy; readiness is the GCS-reported CREATED
    state (non-blocking poll); freeing removes the PG. Every PG this manager
    ever created is tracked until removed, so ``clear()`` (and consumer
    teardown paths that call it) cannot leave a bundle reserved — the leak
    audit in GlobalState.placement_groups() comes back empty.
    """

    def __init__(self):
        self._lock = threading.RLock()
        # id(request) -> (request, PlacementGroup). The request rides in the
        # value so it stays referenced while pending — an id() key alone
        # could be recycled by the allocator after the request is collected.
        self._pending: dict[int, tuple] = {}
        self._acquired: list[AcquiredResources] = []

    @staticmethod
    def _pg_state(pg) -> str:
        from ray_tpu._private import worker_context

        cw = worker_context.get_core_worker()
        resp = cw.gcs.call("get_placement_group", {"pg_id": pg.id.hex()})
        if not resp.get("found"):
            return "REMOVED"
        return resp["info"]["state"]

    def request_resources(self, request: ResourceRequest) -> None:
        from ray_tpu.util.placement_group import placement_group

        with self._lock:
            if id(request) in self._pending:
                return
            pg = placement_group(
                [dict(b) for b in request.bundles], strategy=request.strategy
            )
            self._pending[id(request)] = (request, pg)

    def cancel_resource_request(self, request: ResourceRequest) -> None:
        from ray_tpu.util.placement_group import remove_placement_group

        with self._lock:
            entry = self._pending.pop(id(request), None)
        if entry is not None:
            pg = entry[1]
            try:
                remove_placement_group(pg)
            except Exception:
                logger.warning("failed to remove cancelled PG %s", pg.id.hex()[:8])

    def has_resources_ready(self, request: ResourceRequest) -> bool:
        with self._lock:
            entry = self._pending.get(id(request))
        if entry is None:
            return False
        return self._pg_state(entry[1]) == "CREATED"

    def acquire_resources(self, request: ResourceRequest) -> AcquiredResources | None:
        with self._lock:
            entry = self._pending.get(id(request))
            if entry is None or self._pg_state(entry[1]) != "CREATED":
                return None
            self._pending.pop(id(request))
            acq = AcquiredResources(request=request, placement_group=entry[1])
            self._acquired.append(acq)
            return acq

    def free_resources(self, acquired: AcquiredResources) -> None:
        from ray_tpu.util.placement_group import remove_placement_group

        with self._lock:
            if acquired._freed:
                return
            acquired._freed = True
            if acquired in self._acquired:
                self._acquired.remove(acquired)
        if acquired.placement_group is not None:
            try:
                remove_placement_group(acquired.placement_group)
            except Exception:
                logger.warning(
                    "failed to remove PG %s on free; it may leak bundles",
                    acquired.placement_group.id.hex()[:8],
                )

    def clear(self) -> None:
        with self._lock:
            pending = [pg for _req, pg in self._pending.values()]
            self._pending.clear()
            acquired = list(self._acquired)
        from ray_tpu.util.placement_group import remove_placement_group

        for pg in pending:
            try:
                remove_placement_group(pg)
            except Exception:
                pass
        for acq in acquired:
            self.free_resources(acq)
