"""ray_tpu.air.execution — the shared fault-tolerant execution substrate
beneath the libraries (reference: python/ray/air/execution/).

One audited set of actor restart/leak semantics instead of one per library:
Tune's trial loop, Train's BackendExecutor, and Serve's controller all
route actor lifecycle and resource acquisition through
:class:`ActorManager` + :class:`ResourceManager`.
"""

from ray_tpu.air.execution.actor_manager import (  # noqa: F401
    ActorManager,
    TrackedActor,
    TrackedActorTask,
)
from ray_tpu.air.execution.resources import (  # noqa: F401
    AcquiredResources,
    FixedResourceManager,
    PlacementGroupResourceManager,
    ResourceManager,
    ResourceRequest,
)

__all__ = [
    "ActorManager",
    "TrackedActor",
    "TrackedActorTask",
    "AcquiredResources",
    "FixedResourceManager",
    "PlacementGroupResourceManager",
    "ResourceManager",
    "ResourceRequest",
]
