"""AIR configs (analog of python/ray/air/config.py: ScalingConfig:91,
RunConfig:704, FailureConfig:523, CheckpointConfig:574) — TPU-first: the
accelerator knob is ``use_tpu``/``tpu_per_worker`` and ScalingConfig can gang-
reserve an ICI slice via a STRICT_PACK placement group."""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    tpu_per_worker: int = 1
    resources_per_worker: dict | None = None
    placement_strategy: str = "PACK"  # STRICT_PACK => one ICI domain

    def worker_resources(self) -> dict:
        res = dict(self.resources_per_worker or {})
        if self.use_tpu:
            res.setdefault("TPU", self.tpu_per_worker)
        else:
            res.setdefault("CPU", 1)
        return res

    def as_placement_group_bundles(self) -> list[dict]:
        return [self.worker_resources() for _ in range(self.num_workers)]


@dataclass
class FailureConfig:
    max_failures: int = 0  # -1 = infinite retries of the whole worker group


@dataclass
class CheckpointConfig:
    num_to_keep: int | None = None
    checkpoint_score_attribute: str | None = None
    checkpoint_score_order: str = "max"


@dataclass
class RunConfig:
    name: str | None = None
    storage_path: str | None = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    stop: dict | None = None
    verbose: int = 1
    sync_config: object | None = None  # tune.syncer.SyncConfig (kept untyped: air must not import tune)

    def resolve_dir(self, default_name: str) -> str:
        """Experiment/run directory: <storage_path>/<name> (single source of
        the storage-path policy for Train and Tune)."""
        import os
        import time

        root = self.storage_path or "/tmp/ray_tpu_results"
        name = self.name or f"{default_name}_{time.strftime('%Y%m%d-%H%M%S')}"
        return os.path.join(root, name)
