"""Checkpoint — uniform dict/directory/bytes representation.

Analog of the reference's air.Checkpoint (python/ray/air/checkpoint.py:66):
convertible between an in-memory dict, a directory on disk, and opaque bytes;
framework layers (train/jax) store JAX pytrees in it. Device arrays are pulled
to host on save (orbax-compatible layout for directory form).
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
import time

import cloudpickle


class Checkpoint:
    def __init__(self, data: dict | None = None, directory: str | None = None):
        self._data = data
        self._directory = directory
        # Small side-band info (e.g. training_iteration); travels with the
        # object through the object store and as metadata.json in dir form.
        self.metadata: dict = {}
        if directory is not None:
            meta_path = os.path.join(directory, "metadata.json")
            if os.path.exists(meta_path):
                import json

                with open(meta_path) as f:
                    self.metadata = json.load(f)

    # ---- constructors ----

    @classmethod
    def from_dict(cls, data: dict) -> "Checkpoint":
        return cls(data=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(directory=path)

    @classmethod
    def from_bytes(cls, blob: bytes) -> "Checkpoint":
        return cls(data=cloudpickle.loads(blob))

    # ---- conversions ----

    def to_dict(self) -> dict:
        if self._data is not None:
            return self._data
        assert self._directory is not None
        with open(os.path.join(self._directory, "checkpoint.pkl"), "rb") as f:
            return cloudpickle.load(f)

    def to_bytes(self) -> bytes:
        return cloudpickle.dumps(self.to_dict())

    def to_directory(self, path: str | None = None) -> str:
        path = path or tempfile.mkdtemp(prefix="rtpu_ckpt_")
        os.makedirs(path, exist_ok=True)
        if self._directory is not None:
            if os.path.abspath(self._directory) != os.path.abspath(path):
                shutil.copytree(self._directory, path, dirs_exist_ok=True)
        else:
            tmp = os.path.join(path, f".tmp.{os.getpid()}.{time.monotonic_ns()}")
            with open(tmp, "wb") as f:
                cloudpickle.dump(self._data, f)
            os.replace(tmp, os.path.join(path, "checkpoint.pkl"))
        if self.metadata:
            import json

            with open(os.path.join(path, "metadata.json"), "w") as f:
                json.dump(self.metadata, f, default=str)
        return path

    def __repr__(self):
        kind = "dict" if self._data is not None else f"dir:{self._directory}"
        return f"Checkpoint({kind})"


def jax_checkpoint_from_pytree(pytree, **extra) -> Checkpoint:
    """Host-transfer a JAX pytree into a Checkpoint (device arrays -> numpy)."""
    import jax
    import numpy as np

    host = jax.tree.map(lambda x: np.asarray(x), pytree)
    return Checkpoint.from_dict({"pytree": host, **extra})
