"""Experiment-tracker integrations (analog of reference python/ray/air/
integrations/{wandb,mlflow,comet}.py).

None of the tracker SDKs ship in this image, so each setup function raises
with install guidance — the same seam the reference exposes. The in-image
alternative is the Tune logger stack (tune/logger.py: CSV/JSON/TensorBoard).
"""

from __future__ import annotations


def _gated(name: str, package: str):
    def _setup(*args, **kwargs):
        raise ImportError(
            f"{name} requires the '{package}' package, which is not installed "
            f"in this environment (pip install {package}). The built-in "
            "CSV/JSON/TensorBoard loggers (ray_tpu.tune.logger) need no "
            "external service."
        )

    _setup.__name__ = name
    return _setup


setup_wandb = _gated("setup_wandb", "wandb")
setup_mlflow = _gated("setup_mlflow", "mlflow")
setup_comet = _gated("setup_comet", "comet-ml")
