"""Training session context (analog of python/ray/air/session.py:43 report,
:359 get_dataset_shard and train/_internal/session.py's _TrainSession).

Inside ``train_loop_per_worker`` the functions here expose rank/world info,
deliver per-rank dataset shards, and queue (metrics, checkpoint) reports back
to the driver.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any

_thread_local = threading.local()


@dataclass
class TrainContext:
    world_rank: int = 0
    world_size: int = 1
    local_rank: int = 0
    config: dict = field(default_factory=dict)
    dataset_shards: dict = field(default_factory=dict)
    report_queue: Any = None  # queue.Queue of (metrics, Checkpoint|None)
    checkpoint: Any = None  # restored checkpoint, if resuming
    mesh: Any = None  # jax.sharding.Mesh for the worker gang, if built


def _set_context(ctx: TrainContext):
    _thread_local.ctx = ctx


def _get_context() -> TrainContext:
    ctx = getattr(_thread_local, "ctx", None)
    if ctx is None:
        raise RuntimeError("not inside a train session")
    return ctx


def in_session() -> bool:
    return getattr(_thread_local, "ctx", None) is not None


def report(metrics: dict, checkpoint=None) -> None:
    """Queue a result back to the driver (rank 0's checkpoint is persisted)."""
    ctx = _get_context()
    if ctx.report_queue is not None:
        ctx.report_queue.put((dict(metrics), checkpoint))


def get_world_rank() -> int:
    return _get_context().world_rank


def get_world_size() -> int:
    return _get_context().world_size


def get_local_rank() -> int:
    return _get_context().local_rank


def get_config() -> dict:
    return _get_context().config


def get_checkpoint():
    return _get_context().checkpoint


def get_dataset_shard(name: str = "train"):
    return _get_context().dataset_shards.get(name)


def get_mesh():
    """The jax Mesh materialised for this worker gang (JaxTrainer backend)."""
    return _get_context().mesh
