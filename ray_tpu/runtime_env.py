"""Public runtime-env type.

Analog of the reference's ``ray.runtime_env.RuntimeEnv``
(python/ray/runtime_env/runtime_env.py): a validated dict describing the
environment tasks/actors run in. Supported fields: ``env_vars`` (dict),
``working_dir`` (local path), ``py_modules`` (list of local paths).
``pip``/``conda``/``container`` are recognized but rejected — provisioning
them needs package installation, which this deployment model does not do;
bake dependencies into the node image instead.
"""

from __future__ import annotations

KNOWN_FIELDS = {"env_vars", "working_dir", "py_modules", "pip", "conda", "container"}
# Provisioning these needs package installation (network); rejected at
# submission (core_worker) and defensively at worker startup (worker_main).
UNSUPPORTED_FIELDS = {"pip", "conda", "container"}


class RuntimeEnv(dict):
    def __init__(
        self,
        *,
        env_vars: dict | None = None,
        working_dir: str | None = None,
        py_modules: list | None = None,
        **kwargs,
    ):
        super().__init__()
        from ray_tpu._private.runtime_env_plugins import plugin_fields

        plugin_owned = plugin_fields()
        unknown = set(kwargs) - KNOWN_FIELDS - plugin_owned
        if unknown:
            raise ValueError(
                f"unknown runtime_env fields: {sorted(unknown)} (register a "
                "runtime-env plugin to add custom fields)"
            )
        for key in plugin_owned & set(kwargs):
            self[key] = kwargs[key]
        if env_vars is not None:
            if not isinstance(env_vars, dict) or not all(
                isinstance(k, str) and isinstance(v, str) for k, v in env_vars.items()
            ):
                raise TypeError("env_vars must be a dict[str, str]")
            self["env_vars"] = dict(env_vars)
        if working_dir is not None:
            if not isinstance(working_dir, str):
                raise TypeError("working_dir must be a local path string")
            self["working_dir"] = working_dir
        if py_modules is not None:
            if not isinstance(py_modules, (list, tuple)):
                raise TypeError("py_modules must be a list of local path strings")
            self["py_modules"] = [str(p) for p in py_modules]
        for key in ("pip", "conda", "container"):
            if key in kwargs:
                self[key] = kwargs[key]
