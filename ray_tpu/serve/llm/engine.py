"""Continuous-batching LLM engine (ISSUE 11 tentpole).

``models/generate.py`` can prefill and decode a batch, but a replica built on
it serves one batch at a time: a request arriving mid-decode waits for the
whole batch to drain. This engine is the batching brain in between — the
vLLM-lineage iteration-level scheduler on top of the paged KV cache:

- **slots**: a fixed number of decode lanes (static [num_slots] shapes, so
  XLA compiles the decode step ONCE); a sequence occupies a slot from
  admission to completion, and a new prompt is admitted the moment a slot
  and enough KV blocks free up — mid-decode, not between batches.
- **paged KV cache**: ``init_paged_cache`` block pool + per-sequence block
  tables with a host-side free-list. Block 0 is the reserved null block
  (inactive slots and write-masked padding rows land there).
- **chunked prefill interleaved with decode**: at most one fixed-shape
  prefill chunk runs per scheduler iteration between decode steps, so a
  long admitted prompt cannot stall tokens for running streams.
- **prefix cache**: full blocks covering the ORIGINAL prompt are registered
  under a chain hash (hash of block tokens + predecessor hash — exactly the
  causal dependency of the KV rows); a new request whose prompt shares the
  leading blocks reuses them by refcount and skips that part of prefill.
  refs-0 blocks stay cached and are evicted LRU under allocation pressure.
- **preemption**: when the pool is exhausted mid-decode the youngest
  running sequence is preempted RECOMPUTE-style — its blocks are released
  and it re-enters the wait queue; on re-admission its already-emitted
  tokens are teacher-forced through prefill (bit-identical continuation,
  nothing is ever re-emitted, the request's RNG stream is untouched).
- **streaming**: each request carries a queue the scheduler feeds token by
  token; ``LLMRequest`` iterates it — the replica's ``StreamingResponse``
  pump drains that iterator straight onto the HTTP socket.

``serial_batch=True`` degrades the scheduler to the pre-engine behavior
(admit only into an idle engine, decode only after every admitted prompt
finished prefill, slots idle until the whole batch drains) — the honest
baseline arm for ``microbench.py --serve``.

Concurrency contract: all cache/free-list/slot state is owned by the
scheduler thread; ``submit``/``cancel`` only touch the wait queue under
``_lock`` and set the wake event (annotated ``@any_thread``); consumers
block only on per-request queues.
"""

from __future__ import annotations

import hashlib
import itertools
import queue as _queue
import threading
import time
from collections import OrderedDict, deque
from typing import Optional

import numpy as np

from ray_tpu._private import flight_recorder as _flight
from ray_tpu._private.concurrency import any_thread, blocking
from ray_tpu.serve.llm.stats import ENGINES, LLM


# Terminal-error sentinel for a DELIBERATE engine teardown (replica
# retiring). Requests that die with it surface the typed
# ReplicaDrainingError, which the serve proxy treats as migratable — a
# stream outliving its replica's drain window resumes elsewhere instead of
# dropping. Every other error string stays a plain RuntimeError.
SHUTDOWN_ERROR = "engine shutdown"


def _request_error(val: str) -> Exception:
    if val == SHUTDOWN_ERROR:
        from ray_tpu.exceptions import ReplicaDrainingError

        return ReplicaDrainingError(
            msg="llm engine shut down mid-request (replica retiring)"
        )
    return RuntimeError(val)


class LLMRequest:
    """One generation request: scheduler-fed token queue + terminal state.

    Iterate it for streaming (``for tok in req``), or ``result()`` to
    collect every token. The scheduler owns all ``_sched``-prefixed fields.
    """

    def __init__(self, rid, prompt, max_new_tokens, temperature, top_k, seed):
        self.id = rid
        self.prompt = list(prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        # The request's sampling randomness is a COUNTER-BASED stream: token
        # i is drawn from default_rng((seed, i)), never from mutable RNG
        # state. That makes the stream position-addressable, so a request
        # resumed on ANOTHER replica with resume_tokens= (mid-stream
        # migration) continues bit-identically — exactly like recompute
        # preemption, which never left the process.
        self.seed = int(seed) & 0xFFFFFFFFFFFFFFFF
        self.cancelled = threading.Event()
        self.error: Optional[str] = None
        # Prefill-role terminal state: the sealed-KV handoff descriptor
        # (dict) a decode-pool replica continues from; None on engines that
        # decode their own requests.
        self.handoff: Optional[dict] = None
        self.t_submit = time.monotonic()
        self.t_first: Optional[float] = None
        self.t_done: Optional[float] = None
        self._q: _queue.Queue = _queue.Queue()
        self._finished = False  # scheduler-side guard: one terminal event
        # --- scheduler-owned ---
        self._sched_generated: list[int] = []
        self._sched_state = "waiting"  # waiting | prefill | decode | done
        self._sched_slot: Optional[int] = None
        self._sched_table: list[int] = []
        self._sched_pos = 0
        self._sched_target = 0
        self._sched_cached_bids: set[int] = set()
        self._sched_registered_bids: set[int] = set()
        self._sched_hashes: list[bytes] = []
        self._sched_admit_seq = -1
        # Fetched KV import awaiting admission-time scatter: (host payload
        # [2, L, n_blocks, Bs, KV, Dh], kv_pos tokens it covers). Set by the
        # SUBMIT thread (the network pull must not stall the scheduler);
        # consumed and dropped by _admit.
        self._sched_kv_import: Optional[tuple] = None

    @property
    def num_generated(self) -> int:
        return len(self._sched_generated)

    @blocking
    def __iter__(self):
        while True:
            kind, val = self._q.get()
            if kind == "token":
                yield val
            elif kind == "done":
                return
            elif kind == "handoff":
                self.handoff = val
                return
            else:  # error
                raise _request_error(val)

    @blocking
    def result(self, timeout: float = 120.0) -> list[int]:
        """Collect the full completion (raises on engine-side error)."""
        out: list[int] = []
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(f"request {self.id} not finished in {timeout}s")
            try:
                kind, val = self._q.get(timeout=min(remaining, 1.0))
            except _queue.Empty:
                continue
            if kind == "token":
                out.append(val)
            elif kind == "done":
                return out
            elif kind == "handoff":
                # Prefill-role terminal: the first sampled token travels
                # inside the descriptor (resume_tokens on the decode side),
                # so the caller reads ``req.handoff``, not the token list.
                self.handoff = val
                return out
            else:
                raise _request_error(val)


def block_hashes(tokens, block_size: int) -> list[bytes]:
    """Chain hash per FULL block: h_i = sha1(h_{i-1} || tokens of block i).
    The KV rows of block i depend (causally) on every token up to its end,
    so the chain is exactly the reuse key."""
    out: list[bytes] = []
    h = b""
    for i in range(len(tokens) // block_size):
        blk = np.asarray(
            tokens[i * block_size : (i + 1) * block_size], dtype=">u4"
        ).tobytes()
        h = hashlib.sha1(h + blk).digest()
        out.append(h)
    return out


def prefix_route_hint(tokens, block_size: int = 16) -> str:
    """Router affinity hint for cache-aware routing: the hash of the FIRST
    full block (shared system prompts share it; suffixes don't disturb it).
    Empty string when the prompt doesn't fill one block — no affinity."""
    hs = block_hashes(list(tokens)[:block_size], block_size)
    return hs[0].hex() if hs else ""


# Process-level compiled-program cache: engines with the same model config
# share the jitted decode/prefill callables, so jax's own shape-keyed cache
# applies across engine instances (tests and replica reconfigures would
# otherwise recompile identical programs behind fresh lambdas).
_JIT_CACHE: dict = {}
_JIT_LOCK = threading.Lock()


def _compiled_fns(cfg):
    with _JIT_LOCK:
        fns = _JIT_CACHE.get(cfg)
        if fns is None:
            import jax
            import jax.numpy as jnp

            from ray_tpu.models.generate import (
                _paged_decode_chunk_hidden,
                paged_decode_step,
            )
            from ray_tpu.models.transformer import _head

            def prefill_chunk_row(p, t, c, bt, pos, vt, row):
                # Chunked prefill consumes logits for at most ONE row (the
                # prompt's last real token, on its final chunk) — project
                # just that row instead of paying the [1, q, V] head matmul
                # per chunk (`row` is traced: no recompile per position).
                x, c = _paged_decode_chunk_hidden(p, t, c, bt, pos, cfg, valid_to=vt)
                last = jnp.take_along_axis(
                    x, jnp.reshape(row, (1, 1, 1)).astype(jnp.int32), axis=1
                )[:, 0]
                return (last @ _head(p).astype(last.dtype)).astype(jnp.float32), c

            fns = (
                jax.jit(
                    lambda p, t, c, bt, pos: paged_decode_step(p, t, c, bt, pos, cfg)
                ),
                jax.jit(prefill_chunk_row),
            )
            _JIT_CACHE[cfg] = fns
        return fns


class _PrefixEntry:
    __slots__ = ("bid", "refs", "stamp")

    def __init__(self, bid: int, refs: int, stamp: float):
        self.bid = bid
        self.refs = refs
        self.stamp = stamp


class LLMEngine:
    def __init__(
        self,
        params,
        cfg,
        *,
        num_slots: int = 8,
        block_size: int = 16,
        max_model_len: Optional[int] = None,
        num_blocks: Optional[int] = None,
        prefill_chunk: int = 32,
        serial_batch: bool = False,
        role: str = "both",
        cluster_prefix: bool = False,
        cluster_prefix_max: int = 16,
        handoff_ttl_s: float = 120.0,
    ):
        from ray_tpu.models.generate import init_paged_cache

        if role not in ("both", "prefill", "decode"):
            raise ValueError(f"role must be both|prefill|decode, got {role!r}")
        self.params = params
        self.cfg = cfg
        # Disaggregation role (ISSUE 20). "prefill": requests terminate at
        # prefill completion with a sealed-KV handoff descriptor instead of
        # entering decode, and the prefill queue runs shortest-remaining-
        # first (a prefill-only pool has no decode fairness to protect, so
        # SJF is safe and is what keeps short prompts from queueing behind
        # long ones — the disaggregation TTFT win). "decode" behaves like
        # "both" at the engine level (it must keep full prefill capability
        # for teacher-forced resumption and migration recompute) — the role
        # tag exists for routing/config introspection.
        self.role = role
        self.cluster_prefix = bool(cluster_prefix)
        self.cluster_prefix_max = int(cluster_prefix_max)
        self.handoff_ttl_s = float(handoff_ttl_s)
        # Published prefix entries (deepest chain hash -> sealed payload +
        # registry row keys), LRU-ordered; overflow frees the sealed copy
        # and retracts its rows. _pub_oids is the same-engine import guard.
        self._published: "OrderedDict[bytes, dict]" = OrderedDict()
        self._pub_oids: set[str] = set()
        # Outstanding handoff exports (oid -> reap deadline): the decode
        # side releases the pin after importing; the TTL reaper frees
        # payloads whose handoff never completed (proxy died mid-flight).
        self._exports: dict[str, float] = {}
        self.num_slots = int(num_slots)
        self.block_size = int(block_size)
        self.max_model_len = int(max_model_len or cfg.max_seq_len)
        self.n_max = -(-self.max_model_len // self.block_size)  # blocks/seq
        # Default pool: every slot can run to max_model_len (+1 null block)
        # — preemption-free unless the caller sizes the pool down.
        self.num_blocks = int(num_blocks or self.num_slots * self.n_max + 1)
        self.prefill_chunk = int(prefill_chunk)
        self.serial_batch = bool(serial_batch)
        self._cache = init_paged_cache(cfg, self.num_blocks, self.block_size)
        # Block 0 is the reserved null block — never handed out.
        self._free: list[int] = list(range(self.num_blocks - 1, 0, -1))
        self._prefix: dict[bytes, _PrefixEntry] = {}
        self._bid_hash: dict[int, bytes] = {}
        # Evictable (refs-0) prefix entries in LRU order: insertion order IS
        # recency (pushed on the refs 1->0 transition, popped from the front
        # for eviction) — O(1) instead of scanning _prefix per allocation.
        self._lru: "OrderedDict[bytes, None]" = OrderedDict()
        self._slots: list[Optional[LLMRequest]] = [None] * self.num_slots
        self._waiting: deque[LLMRequest] = deque()
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._crashed: Optional[str] = None  # set under _lock by the crash sweep
        self._draining = False  # drain-before-retire: refuse NEW submits only
        self._rid = itertools.count()
        self._admit_seq = itertools.count()
        # Per-engine counters for stats()/tests; the process-global LLM
        # stats object (metrics) is bumped in parallel — several engines in
        # one process fold into one exported series, like rpc.WIRE.
        self._counts = {
            "admitted": 0,
            "finished": 0,
            "cancelled": 0,
            "preemptions": 0,
            "prefix_hit_blocks": 0,
            "prefix_miss_blocks": 0,
            "evicted_blocks": 0,
            "handoffs": 0,
            "handoff_exports": 0,
            "handoff_failed": 0,
            "prefix_import_hits": 0,
            "prefix_import_misses": 0,
            "prefix_import_errors": 0,
        }
        self._decode_fn, self._prefill_fn = _compiled_fns(cfg)
        try:
            from ray_tpu._private import self_metrics

            self._metrics = self_metrics.instruments()
        except Exception:
            self._metrics = None
        self._thread = threading.Thread(
            target=self._loop, name="llm-engine", daemon=True
        )
        # Live-engine registry: the flush-time metrics collector sums the
        # gauge-shaped state (running/waiting/KV utilization) across every
        # engine whose scheduler is still running; _loop's exit (stop OR
        # crash) withdraws this engine so the gauges never go stale.
        ENGINES.add(self)
        self._thread.start()

    # ------------------------------------------------------------------
    # public surface (any thread)
    # ------------------------------------------------------------------

    @any_thread
    def submit(
        self,
        tokens,
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        top_k: int = 0,
        seed: int = 0,
        resume_tokens=None,
        kv_import=None,
    ) -> LLMRequest:
        """``resume_tokens``: tokens this request ALREADY emitted on a
        replica that died mid-stream. They are teacher-forced through
        chunked prefill exactly like recompute preemption re-admission
        (they pre-seed the generated list, so admission's target covers
        them) and are NEVER re-emitted on the token queue — the stream
        continues from position len(resume_tokens), bit-identically under
        the counter-based per-request RNG stream.

        ``kv_import``: a sealed-KV handoff descriptor from a prefill-pool
        replica. The payload is pulled HERE on the caller thread (network
        I/O must not stall the scheduler) and scattered into freshly
        allocated blocks at admission, so prefill resumes at the imported
        position instead of recomputing the prompt. Any import failure
        degrades to a plain recompute — the request still completes."""
        tokens = [int(t) for t in tokens]
        if not tokens:
            raise ValueError("empty prompt")
        resume = [int(t) for t in (resume_tokens or ())]
        if len(resume) > int(max_new_tokens):
            raise ValueError(
                f"resume_tokens ({len(resume)}) exceeds max_new_tokens "
                f"({max_new_tokens})"
            )
        if len(tokens) + int(max_new_tokens) > self.max_model_len:
            raise ValueError(
                f"prompt ({len(tokens)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_model_len {self.max_model_len}"
            )
        # A request whose full extent can never be backed by the pool would
        # park at the admission FIFO head forever (and starve everything
        # behind it) — reject it here, the only place that can say why.
        max_blocks = (len(tokens) + int(max_new_tokens) - 1) // self.block_size + 1
        if max_blocks > self.num_blocks - 1:
            raise ValueError(
                f"request needs up to {max_blocks} KV blocks but the pool "
                f"only has {self.num_blocks - 1}; raise num_blocks"
            )
        req = LLMRequest(
            f"llm-{next(self._rid)}", tokens, max_new_tokens, temperature, top_k, seed
        )
        req._sched_generated = resume
        # Reuse applies to blocks fully inside tokens[:-1]: at least one
        # prompt token always runs through prefill so admission has logits
        # to sample the first output from.
        n_hashable = (len(tokens) - 1) // self.block_size
        req._sched_hashes = block_hashes(tokens, self.block_size)[:n_hashable]
        if len(resume) >= int(max_new_tokens):
            # Already complete on arrival (the dead replica emitted the last
            # token but not the terminal event): nothing to generate.
            req._finished = True
            req._sched_state = "done"
            req.t_done = time.monotonic()
            req._q.put(("done", "complete"))
            return req
        if kv_import is not None:
            self._attach_handoff_import(req, kv_import)
        elif self.cluster_prefix and not resume and req._sched_hashes:
            self._attach_cluster_prefix(req)
        with self._lock:
            # A stopped scheduler can never serve this request — fail the
            # submit instead of parking the consumer on a queue nobody
            # feeds. Both the crash handler and the shutdown drain set
            # _crashed and sweep _waiting under this same lock, so a racing
            # submit either lands in the sweep (finished with the error) or
            # raises here. White-box tests that drive the scheduler by hand
            # after shutdown() re-open submits by clearing _crashed.
            if self._crashed is not None:
                raise RuntimeError(self._crashed)
            if self._draining:
                # TYPED: a submit racing the replica-gate/engine-drain
                # window must read as went-away to the proxy/handle (one
                # bounded reassign), not as an app bug 500.
                from ray_tpu.exceptions import ReplicaDrainingError

                raise ReplicaDrainingError(
                    msg="llm engine is draining (replica retiring); "
                    "resubmit on another replica"
                )
            self._waiting.append(req)
        self._wake.set()
        return req

    @any_thread
    def cancel(self, req: LLMRequest):
        """Client disconnect: mark the request; the scheduler frees its slot
        and KV blocks on its next iteration (sub-millisecond when active)."""
        req.cancelled.set()
        self._wake.set()

    @any_thread
    def drain(self):
        """Drain-before-retire: refuse NEW submits; everything already
        accepted (running slots + the wait queue — their clients hold live
        streams) decodes to completion. The replica retires once its
        in-flight work hits zero or drain_timeout_s expires."""
        with self._lock:
            self._draining = True

    @any_thread
    def stats(self) -> dict:
        """Best-effort snapshot (plain-int reads) for tests and benches."""
        return {
            "num_blocks": self.num_blocks - 1,
            "free_blocks": len(self._free),
            "cached_blocks": len(self._prefix),
            "running": sum(r is not None for r in self._slots),
            "waiting": len(self._waiting),
            "draining": self._draining,
            "role": self.role,
            "published_prefixes": len(self._published),
            "pending_exports": len(self._exports),
            **self._counts,
        }

    @any_thread
    def shutdown(self, timeout: float = 10.0):
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=timeout)

    def check_health(self) -> bool:
        if not self._thread.is_alive() and not self._stop.is_set():
            raise RuntimeError("llm engine scheduler thread died")
        return True

    # ------------------------------------------------------------------
    # disaggregation: KV handoff import + cluster prefix tier (ISSUE 20)
    # ------------------------------------------------------------------

    @staticmethod
    def _own_addr() -> str:
        from ray_tpu._private import worker_context

        cw = worker_context.get_core_worker_if_initialized()
        return ":".join(str(x) for x in cw.address) if cw is not None else "local"

    @blocking
    def _attach_handoff_import(self, req: LLMRequest, desc: dict):
        """Pull a prefill-pool replica's sealed KV payload on the SUBMIT
        thread and stage it for admission-time scatter. Failure is not
        fatal: the request recomputes its prompt like any fresh submit."""
        from ray_tpu.serve.llm import kv_transfer

        try:
            payload = kv_transfer.fetch_kv_payload(desc, release=True)
        except Exception as e:
            self._counts["handoff_failed"] += 1
            _flight.record(
                "llm_kv_handoff",
                f"{str(desc.get('oid', '?'))[:12]}:failed:{type(e).__name__}",
            )
            return
        req._sched_kv_import = (payload, int(desc["kv_pos"]))
        LLM.handoffs += 1
        self._counts["handoffs"] += 1
        src = ":".join(str(x) for x in desc.get("addr", ()))
        _flight.record(
            "llm_kv_handoff",
            f"{desc['oid'][:12]}:{desc.get('blocks', 0)}blk:"
            f"{desc.get('nbytes', 0)}B:{src}->{self._own_addr()}",
        )

    @blocking
    def _attach_cluster_prefix(self, req: LLMRequest):
        """Bounded longest→shortest cluster-registry probe for this
        prompt's chain hashes. A hit stages the holder's sealed KV for
        admission-time scatter (exactly the handoff import path); any
        failure falls back to recompute. At most 4 registry lookups and
        ONE payload fetch per submit — the local prefix cache stays the
        fast path and short-circuits the probe entirely."""
        from ray_tpu._private import worker_context
        from ray_tpu.exceptions import DeviceObjectLostError
        from ray_tpu.serve.llm import kv_transfer

        cw = worker_context.get_core_worker_if_initialized()
        if cw is None:
            return
        n = len(req._sched_hashes)
        depths = sorted(
            {n, n - 1, n // 2, n // 4} & set(range(1, n + 1)), reverse=True
        )[:4]
        probed = False
        for d in depths:
            h = req._sched_hashes[d - 1]
            if h in self._prefix:
                # Local cache already covers depth d — admission will take
                # the refcounted hit; an import can only do worse. (Benign
                # cross-thread dict read: a stale view just costs a probe.)
                break
            row = kv_transfer.lookup_prefix_row(cw, h)
            probed = True
            if row is None:
                continue
            if row.get("oid") in self._pub_oids:
                continue  # our own publication — importing it is recompute with extra steps
            use = min(int(row.get("use_blocks", 0)), d)
            if use < 1 or int(row.get("block_size", 0)) != self.block_size:
                continue
            desc = {
                "oid": row["oid"],
                "addr": row["addr"],
                "nbytes": int(row.get("nbytes", 0)),
                "kv_pos": use * self.block_size,
                "blocks": use,
                "block_size": self.block_size,
            }
            try:
                payload = kv_transfer.fetch_kv_payload(desc, release=False)
            except Exception as e:
                LLM.prefix_import_errors += 1
                self._counts["prefix_import_errors"] += 1
                if isinstance(e, DeviceObjectLostError):
                    # The payload died under the row (holder eviction or
                    # death): retract so the next prober skips the corpse.
                    kv_transfer.retract_prefix_rows(
                        cw, [kv_transfer.PREFIX_ROW + h.hex()], desc["oid"]
                    )
                _flight.record(
                    "llm_prefix_import",
                    f"{desc['oid'][:12]}:error:{type(e).__name__}",
                )
                return
            req._sched_kv_import = (payload[:, :, :use], use * self.block_size)
            LLM.prefix_import_hits += 1
            self._counts["prefix_import_hits"] += 1
            src = ":".join(str(x) for x in desc["addr"])
            _flight.record(
                "llm_prefix_import",
                f"{desc['oid'][:12]}:{use}blk:{desc['nbytes']}B:"
                f"{src}->{self._own_addr()}",
            )
            return
        if probed:
            LLM.prefix_import_misses += 1
            self._counts["prefix_import_misses"] += 1

    def _scatter_import(self, req: LLMRequest, cached: int):
        """Admission-time KV import (scheduler thread): write the payload
        blocks the local cache did not already cover into this request's
        freshly allocated blocks, advance prefill past the imported extent,
        and register the now-valid full prompt blocks in the LOCAL prefix
        cache (the import seeds this replica for future local hits)."""
        payload, kv_pos = req._sched_kv_import
        req._sched_kv_import = None
        # Always leave ≥1 prompt token for prefill: admission needs logits
        # to sample from, exactly the n_hashable rule.
        kv_pos = min(int(kv_pos), req._sched_target - 1)
        if kv_pos <= req._sched_pos:
            return
        imp_blocks = -(-kv_pos // self.block_size)
        if imp_blocks > len(req._sched_table) or imp_blocks > payload.shape[2]:
            return  # malformed descriptor: recompute instead of corrupting
        import jax.numpy as jnp

        idx = jnp.asarray(req._sched_table[cached:imp_blocks], jnp.int32)
        chunk = jnp.asarray(payload[:, :, cached:imp_blocks])
        dt = self._cache["k"].dtype
        self._cache["k"] = self._cache["k"].at[:, idx].set(chunk[0].astype(dt))
        self._cache["v"] = self._cache["v"].at[:, idx].set(chunk[1].astype(dt))
        req._sched_pos = kv_pos
        self._register_prefix_blocks(req)

    def _try_handoff(self, req: LLMRequest, logits_row: np.ndarray) -> bool:
        """Prefill-role completion: sample the first output token, seal the
        prompt's KV blocks as a transient device object, and finish the
        request with the ~300B handoff descriptor. Returns False when
        sealing is impossible (bare engine, seal error) — the caller then
        decodes locally, bit-identically (the counter-based RNG draws the
        same token at position 0 either way)."""
        from ray_tpu.serve.llm import kv_transfer

        n_exp = -(-len(req.prompt) // self.block_size)
        try:
            desc = kv_transfer.seal_kv_payload(
                self._cache,
                req._sched_table[:n_exp],
                kv_pos=len(req.prompt),
                block_size=self.block_size,
                scope="llmkv",
            )
        except Exception:
            desc = None
        if desc is None:
            return False
        tok = self._sample(req, logits_row)
        req._sched_generated.append(tok)
        self._exports[desc["oid"]] = time.monotonic() + self.handoff_ttl_s
        LLM.handoff_exports += 1
        self._counts["handoff_exports"] += 1
        self._finish(req, handoff=dict(desc, tok0=tok))
        return True

    def _publish_prefix(self, req: LLMRequest):
        """Seal this request's hashable prompt prefix ONCE (an independent
        copy — pool eviction can never tear an in-flight import) and
        advertise one registry row per covered depth. LRU-capped at
        cluster_prefix_max sealed prefixes; overflow frees the payload and
        retracts its rows (read-check-delete, so a newer holder's
        last-write-wins row survives)."""
        hashes = req._sched_hashes
        if not hashes:
            return
        deep = hashes[-1]
        with self._lock:
            if deep in self._published:
                self._published.move_to_end(deep)
                return
        from ray_tpu._private import worker_context
        from ray_tpu.serve.llm import kv_transfer

        cw = worker_context.get_core_worker_if_initialized()
        if cw is None:
            return
        try:
            desc = kv_transfer.seal_kv_payload(
                self._cache,
                req._sched_table[: len(hashes)],
                kv_pos=len(hashes) * self.block_size,
                block_size=self.block_size,
                scope="llmprefix",
            )
        except Exception:
            desc = None
        if desc is None:
            return
        holder_id, _ = cw._holder_identity()
        keys = kv_transfer.publish_prefix_rows(cw, hashes, desc, holder_id)
        evicted: list[dict] = []
        with self._lock:
            self._published[deep] = {"oid": desc["oid"], "keys": keys}
            self._pub_oids.add(desc["oid"])
            while len(self._published) > self.cluster_prefix_max:
                _, entry = self._published.popitem(last=False)
                self._pub_oids.discard(entry["oid"])
                evicted.append(entry)
        for entry in evicted:
            self._retract_published(cw, entry)

    def _retract_published(self, cw, entry: dict):
        from ray_tpu.serve.llm import kv_transfer

        kv_transfer.retract_prefix_rows(cw, entry["keys"], entry["oid"])
        try:
            cw._device_manager().free(entry["oid"])
        except Exception:
            pass

    def _reap_exports(self):
        """Free handoff payloads whose descriptor never came back (proxy
        died between prefill and decode-assign) — the importing side's pin
        release is the fast path, this TTL is the backstop."""
        if not self._exports:
            return
        now = time.monotonic()
        stale = [oid for oid, dl in self._exports.items() if dl < now]
        if not stale:
            return
        from ray_tpu._private import worker_context

        cw = worker_context.get_core_worker_if_initialized()
        for oid in stale:
            self._exports.pop(oid, None)
            if cw is not None:
                try:
                    cw._device_manager().free(oid)
                except Exception:
                    pass

    def _teardown_cluster_tier(self):
        """Engine exit (shutdown or crash): retract every registry row this
        engine published and free the sealed payloads + stale exports, so
        the GCS KV returns to baseline and no importer chases a corpse."""
        from ray_tpu._private import worker_context

        cw = worker_context.get_core_worker_if_initialized()
        with self._lock:
            pubs = list(self._published.values())
            self._published.clear()
            self._pub_oids.clear()
        for entry in pubs:
            if cw is not None:
                self._retract_published(cw, entry)
        for oid in list(self._exports):
            self._exports.pop(oid, None)
            if cw is not None:
                try:
                    cw._device_manager().free(oid)
                except Exception:
                    pass

    # ------------------------------------------------------------------
    # scheduler (one dedicated thread owns everything below)
    # ------------------------------------------------------------------

    @blocking
    def _loop(self):
        try:
            while not self._stop.is_set():
                self._sweep_cancelled()
                self._reap_exports()
                self._admit()
                busy = self._prefill_tick()
                busy = self._decode_tick() or busy
                if not busy:
                    if any(r is not None for r in self._slots) or self._waiting:
                        self._wake.wait(0.02)
                    else:
                        # Fully idle: every state transition that could make
                        # work (submit/cancel/shutdown) sets _wake, so park
                        # until one does instead of spinning 50x/s.
                        self._wake.wait()
                    self._wake.clear()
        except BaseException as e:  # noqa: BLE001 — fail every consumer loudly
            msg = f"llm engine scheduler died: {type(e).__name__}: {e}"
            with self._lock:
                self._crashed = msg
                pending = list(self._slots) + list(self._waiting)
            for req in pending:
                if req is not None:
                    self._finish(req, error=msg)
            raise
        finally:
            ENGINES.discard(self)
            with self._lock:
                if self._crashed is None:
                    self._crashed = "llm engine is shut down"
                pending = list(self._slots) + list(self._waiting)
            for req in pending:
                if req is not None:
                    self._finish(req, error=SHUTDOWN_ERROR)
            self._teardown_cluster_tier()

    def _sweep_cancelled(self):
        for req in self._slots:
            if req is not None and req.cancelled.is_set():
                self._finish(req, cancelled=True)
        with self._lock:
            stale = [r for r in self._waiting if r.cancelled.is_set()]
            for r in stale:
                self._waiting.remove(r)
        for r in stale:
            self._finish(r, cancelled=True)

    # --- block pool ---

    def _alloc_block(self) -> Optional[int]:
        if self._free:
            return self._free.pop()
        while self._lru:
            victim_hash, _ = self._lru.popitem(last=False)  # oldest refs-0
            victim = self._prefix.get(victim_hash)
            if victim is None or victim.refs > 0:
                continue  # stale LRU entry (white-box tests may desync)
            del self._prefix[victim_hash]
            self._bid_hash.pop(victim.bid, None)
            LLM.evicted_blocks += 1
            self._counts["evicted_blocks"] += 1
            _flight.record("llm_evict", f"bid={victim.bid}")
            return victim.bid
        return None

    def _release_blocks(self, req: LLMRequest):
        now = time.monotonic()
        shared = req._sched_cached_bids | req._sched_registered_bids
        for bid in req._sched_table:
            if bid in shared:
                h = self._bid_hash.get(bid)
                e = self._prefix.get(h) if h is not None else None
                if e is not None:
                    e.refs -= 1
                    e.stamp = now
                    if e.refs <= 0:  # now evictable: most-recent LRU slot
                        self._lru.pop(h, None)
                        self._lru[h] = None
                else:  # registration raced an eviction; treat as private
                    self._free.append(bid)
            else:
                self._free.append(bid)
        req._sched_table = []
        req._sched_cached_bids = set()
        req._sched_registered_bids = set()

    # --- admission ---

    def _admit(self):
        if self.serial_batch and any(r is not None for r in self._slots):
            return
        while True:
            try:
                slot = self._slots.index(None)
            except ValueError:
                return
            with self._lock:
                if not self._waiting:
                    return
                req = self._waiting[0]
            # Teacher-forced target: original prompt plus anything already
            # emitted before a preemption.
            target = len(req.prompt) + len(req._sched_generated)
            cached = 0
            for h in req._sched_hashes:
                e = self._prefix.get(h)
                if e is None:
                    break
                cached += 1
            need = (target - 1) // self.block_size + 1 - cached
            # Evictable supply must EXCLUDE the refs-0 entries this request
            # is about to take as cached hits — counting them double lets
            # admission proceed into an alloc loop with no blocks left.
            hit_hashes = set(req._sched_hashes[:cached])
            evictable = len(self._lru) - sum(
                1 for h in hit_hashes if h in self._lru
            )
            if len(self._free) + evictable < need:
                return  # head-of-line waits for blocks (FIFO fairness)
            with self._lock:
                self._waiting.popleft()
            table: list[int] = []
            now = time.monotonic()
            for h in req._sched_hashes[:cached]:
                e = self._prefix[h]
                e.refs += 1
                e.stamp = now
                if e.refs == 1:  # left the evictable set
                    self._lru.pop(h, None)
                table.append(e.bid)
                req._sched_cached_bids.add(e.bid)
            for _ in range(need):
                bid = self._alloc_block()
                assert bid is not None  # guarded by the availability check
                table.append(bid)
            LLM.prefix_hit_blocks += cached
            self._counts["prefix_hit_blocks"] += cached
            LLM.prefix_miss_blocks += len(req._sched_hashes) - cached
            self._counts["prefix_miss_blocks"] += len(req._sched_hashes) - cached
            if cached:
                _flight.record("llm_prefix_hit", f"{req.id}:{cached}blk")
            req._sched_table = table
            req._sched_pos = cached * self.block_size
            req._sched_target = target
            if req._sched_kv_import is not None:
                self._scatter_import(req, cached)
            req._sched_state = "prefill"
            req._sched_slot = slot
            req._sched_admit_seq = next(self._admit_seq)
            self._slots[slot] = req
            LLM.admitted += 1
            self._counts["admitted"] += 1
            _flight.record(
                "llm_admit",
                f"{req.id}:T{len(req.prompt)}:hit{cached}:slot{slot}",
            )

    # --- prefill (one fixed-shape chunk per tick, interleaved with decode) ---

    def _prefill_tick(self) -> bool:
        if self.role == "prefill":
            # Prefill-only pool: shortest-remaining-first. There is no
            # decode fairness to protect here, so a short prompt jumps the
            # queue instead of waiting out a long one's chunks — the
            # disaggregation TTFT win for short streams under mixed load.
            # admit_seq tiebreaks for determinism; starvation is bounded by
            # the pool being prefill-only (every job leaves at completion).
            key = lambda r: (r._sched_target - r._sched_pos, r._sched_admit_seq)  # noqa: E731
        else:
            key = lambda r: r._sched_admit_seq  # noqa: E731
        req = min(
            (r for r in self._slots if r is not None and r._sched_state == "prefill"),
            key=key,
            default=None,
        )
        if req is None:
            return False
        import jax.numpy as jnp

        q = self.prefill_chunk
        pos0 = req._sched_pos
        seq = req.prompt + req._sched_generated
        piece = seq[pos0 : pos0 + q]
        fed = piece + [0] * (q - len(piece))
        table = np.zeros((1, self.n_max), np.int32)
        table[0, : len(req._sched_table)] = req._sched_table
        # Row of the prompt's LAST real token within this chunk — only
        # meaningful (and only consumed) on the final chunk.
        row = min(max(req._sched_target - 1 - pos0, 0), q - 1)
        logits, self._cache = self._prefill_fn(
            self.params,
            jnp.asarray([fed], jnp.int32),
            self._cache,
            jnp.asarray(table),
            jnp.asarray([pos0], jnp.int32),
            jnp.asarray([req._sched_target], jnp.int32),
            jnp.int32(row),
        )
        req._sched_pos = min(pos0 + q, req._sched_target)
        self._register_prefix_blocks(req)
        if req._sched_pos >= req._sched_target:
            # Publish BEFORE any terminal transition: sealing gathers from
            # the request's still-allocated block table.
            if self.cluster_prefix:
                self._publish_prefix(req)
            row_logits = np.asarray(logits)[0]
            if self.role == "prefill" and self._try_handoff(req, row_logits):
                return True
            self._emit_token(req, row_logits)
        return True

    def _register_prefix_blocks(self, req: LLMRequest):
        """Publish freshly-WRITTEN full prompt blocks for reuse. Done as
        prefill progresses (never at admission): a block becomes visible to
        other admissions only once its rows exist."""
        now = time.monotonic()
        done_blocks = req._sched_pos // self.block_size
        for i, h in enumerate(req._sched_hashes[:done_blocks]):
            bid = req._sched_table[i]
            if bid in req._sched_cached_bids or bid in req._sched_registered_bids:
                continue
            if h in self._prefix:
                continue  # another sequence published this hash first
            self._prefix[h] = _PrefixEntry(bid, refs=1, stamp=now)
            self._bid_hash[bid] = h
            req._sched_registered_bids.add(bid)

    # --- decode ---

    def _decode_tick(self) -> bool:
        if self.serial_batch and any(
            r is not None and r._sched_state == "prefill" for r in self._slots
        ):
            return False  # serial baseline: the batch decodes in lockstep
        active = [r for r in self._slots if r is not None and r._sched_state == "decode"]
        if not active:
            return False
        # Every active sequence needs its next write position backed by a
        # physical block before the step; exhaustion preempts the youngest.
        for req in list(active):
            if req._sched_slot is None or self._slots[req._sched_slot] is not req:
                continue  # preempted by an earlier needy sequence this tick
            while req._sched_pos // self.block_size >= len(req._sched_table):
                bid = self._alloc_block()
                if bid is not None:
                    req._sched_table.append(bid)
                    continue
                # Youngest-victim policy over ALL running sequences — the
                # needy one included: when req itself is the youngest it is
                # the one preempted (minimal recompute), not an older
                # sequence carrying more progress.
                running = [r for r in self._slots if r is not None]
                victim = max(running, key=lambda r: r._sched_admit_seq)
                if victim is req:
                    if len(running) == 1:
                        # Nobody else holds blocks: preempting req would just
                        # readmit it into the same dry pool forever.
                        self._finish(
                            req,
                            error=(
                                "KV block pool exhausted with a single "
                                "running sequence; raise num_blocks"
                            ),
                        )
                    else:
                        self._preempt(req)
                    break  # req left its slot; its alloc loop is moot
                self._preempt(victim)
        # Re-derive the step batch: preemption/failure above may have
        # removed sequences from their slots.
        active = [
            r
            for r in self._slots
            if r is not None
            and r._sched_state == "decode"
            and r._sched_pos // self.block_size < len(r._sched_table)
        ]
        if not active:
            return True
        import jax.numpy as jnp

        toks = np.zeros((self.num_slots,), np.int32)
        pos = np.zeros((self.num_slots,), np.int32)
        tables = np.zeros((self.num_slots, self.n_max), np.int32)
        for req in active:
            s = req._sched_slot
            toks[s] = req._sched_generated[-1]
            pos[s] = req._sched_pos
            tables[s, : len(req._sched_table)] = req._sched_table
        logits, self._cache = self._decode_fn(
            self.params,
            jnp.asarray(toks),
            self._cache,
            jnp.asarray(tables),
            jnp.asarray(pos),
        )
        logits = np.asarray(logits)
        for req in active:
            req._sched_pos += 1
            self._emit_token(req, logits[req._sched_slot])
        return True

    def _sample(self, req: LLMRequest, row: np.ndarray) -> int:
        if req.temperature <= 0.0:
            return int(row.argmax())
        logits = row.astype(np.float64) / req.temperature
        if req.top_k > 0:
            kth = np.sort(logits)[-req.top_k]
            logits = np.where(logits < kth, -np.inf, logits)
        logits -= logits.max()
        p = np.exp(logits)
        p /= p.sum()
        # Counter-based draw: (seed, position) fully determines the token,
        # so a resumed request samples position k identically on any
        # replica (the migration bit-exactness contract).
        rng = np.random.default_rng((req.seed, len(req._sched_generated)))
        return int(rng.choice(len(p), p=p))

    def _emit_token(self, req: LLMRequest, logits_row: np.ndarray):
        tok = self._sample(req, logits_row)
        req._sched_generated.append(tok)
        req._sched_state = "decode"
        now = time.monotonic()
        if req.t_first is None:
            req.t_first = now
            if self._metrics is not None:
                try:
                    self._metrics["serve_llm_ttft"].observe(now - req.t_submit)
                except Exception:
                    pass
        req._q.put(("token", tok))
        if len(req._sched_generated) >= req.max_new_tokens:
            self._finish(req)

    # --- terminal transitions ---

    def _preempt(self, victim: LLMRequest):
        LLM.preemptions += 1
        self._counts["preemptions"] += 1
        _flight.record(
            "llm_preempt", f"{victim.id}:n{len(victim._sched_generated)}"
        )
        self._release_blocks(victim)
        if victim._sched_slot is not None:
            self._slots[victim._sched_slot] = None
        victim._sched_slot = None
        victim._sched_state = "waiting"
        victim._sched_pos = 0
        with self._lock:
            self._waiting.appendleft(victim)  # resume first: FIFO-ish fairness

    def _finish(
        self,
        req: LLMRequest,
        error: str | None = None,
        cancelled=False,
        handoff: dict | None = None,
    ):
        if req._finished:
            return
        req._finished = True
        self._release_blocks(req)
        if req._sched_slot is not None and self._slots[req._sched_slot] is req:
            self._slots[req._sched_slot] = None
        req._sched_slot = None
        req._sched_state = "done"
        req.t_done = time.monotonic()
        if handoff is not None:
            LLM.finished += 1
            self._counts["finished"] += 1
            req._q.put(("handoff", handoff))
        elif cancelled:
            LLM.cancelled += 1
            self._counts["cancelled"] += 1
            req._q.put(("done", "cancelled"))
        elif error is not None:
            LLM.finished += 1
            self._counts["finished"] += 1
            req.error = error
            req._q.put(("error", error))
        else:
            LLM.finished += 1
            self._counts["finished"] += 1
            req._q.put(("done", "complete"))
            if self._metrics is not None and req.t_first is not None:
                n = len(req._sched_generated)
                if n > 1:
                    try:
                        self._metrics["serve_llm_tpot"].observe(
                            (req.t_done - req.t_first) / (n - 1)
                        )
                    except Exception:
                        pass
