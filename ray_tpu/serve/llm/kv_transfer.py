"""KV-block transfer plane for disaggregated LLM serving (ISSUE 20).

Two movements ride through here, both as device objects whose ~300B
descriptor travels in-band (an HTTP envelope or a GCS registry row) while
the payload moves over the direct-mailbox p2p plane — zero raylet RPCs,
zero store objects:

- **prefill→decode handoff**: a prefill-pool engine finishes a prompt,
  seals the request's KV blocks (gathered into one contiguous array) as a
  transient channel payload, and the descriptor rides the serve proxy to a
  decode-pool replica, which imports the blocks and continues generation
  through the teacher-forced-resumption admission path.
- **cluster prefix tier**: an engine publishes a hot prompt prefix's KV
  once (a sealed copy, independent of the live pool — eviction can never
  tear an in-flight import) plus one ``llmprefix/<chain-hash>`` GCS row per
  covered depth (the ``devobj/<oid>`` state-view pattern); any engine whose
  local prefix cache misses imports the payload from the holder instead of
  recomputing it.

The sealed copy costs one extra copy of the published blocks on the
holder — the price of torn-block-free imports (see PARITY.md for the
honest-gaps list). Rows are last-write-wins; retraction is read-check-
delete on the object id so a retract never deletes a newer holder's row.
"""

from __future__ import annotations

import json
import os

from ray_tpu._private.concurrency import blocking

PREFIX_ROW = "llmprefix/"


def _core_worker():
    from ray_tpu._private import worker_context

    return worker_context.get_core_worker_if_initialized()


@blocking
def seal_kv_payload(cache, bids, *, kv_pos: int, block_size: int, scope: str):
    """Gather KV blocks ``bids`` (logical order) out of the paged pool into
    one contiguous array ``[2, L, n_blocks, block_size, KV, Dh]`` and
    register it as a transient channel payload (pins=1, held by the caller).
    Returns the wire descriptor dict, or None when no core worker is
    attached (bare engine in a unit test — disaggregation is cluster-only).

    The gather is a COPY: the sealed payload is independent of the live
    pool, so pool eviction/reuse of ``bids`` after sealing cannot corrupt a
    later import.
    """
    cw = _core_worker()
    if cw is None:
        return None
    import jax.numpy as jnp

    idx = jnp.asarray(list(bids), jnp.int32)
    arr = jnp.stack(
        [jnp.take(cache["k"], idx, axis=1), jnp.take(cache["v"], idx, axis=1)]
    )
    meta = cw._device_manager().create_channel_payload(arr, pins=1, scope=scope)
    return {
        "oid": meta.object_id,
        "addr": list(meta.holder_addr),
        "nbytes": int(meta.nbytes),
        "kv_pos": int(kv_pos),
        "blocks": len(bids),
        "block_size": int(block_size),
    }


@blocking
def fetch_kv_payload(desc: dict, *, timeout: float = 20.0, release: bool = False):
    """Pull a sealed KV payload to this process as a host ``np.ndarray``
    ``[2, L, n_blocks, block_size, KV, Dh]``.

    Same-process holders resolve through the manager directly; remote
    holders get ONE ``devobj_pull`` RPC carrying a direct-mailbox reply key
    — the payload streams straight into this process's p2p inbox, no store
    seal, no host arena. Raises ``DeviceObjectLostError`` when the holder
    no longer has the object (evicted / holder died) — the caller's typed
    miss — and ``TimeoutError`` when the payload never lands.

    ``release=True`` drops the holder-side pin after a successful fetch
    (one-shot handoff payloads); prefix-tier payloads are multi-consumer
    and stay pinned by the publishing engine.
    """
    import numpy as np

    from ray_tpu._private import serialization
    from ray_tpu.exceptions import DeviceObjectLostError

    cw = _core_worker()
    if cw is None:
        raise DeviceObjectLostError(desc["oid"], msg="no core worker attached")
    oid = desc["oid"]
    addr = tuple(desc["addr"])
    if addr == tuple(cw.address):
        arr = cw._device_manager().get_local(oid)
        if arr is None:
            raise DeviceObjectLostError(oid, msg="sealed KV payload already freed")
        out = np.asarray(arr)
        if release:
            cw._device_manager().release_pin(oid)
        return out
    from ray_tpu.util.collective.p2p import direct_recv

    key = f"llmkv/{oid[:12]}/{os.urandom(6).hex()}"
    resp = cw._devobj_client(addr).call(
        "devobj_pull",
        {"object_id": oid, "direct_key": key, "direct_addr": list(cw.address)},
        timeout=timeout,
    )
    kind = resp.get("kind")
    if kind == "missing":
        raise DeviceObjectLostError(oid, msg="sealed KV payload already freed")
    if kind == "inline":
        out = np.asarray(serialization.loads(resp["data"]))
    elif kind == "direct":
        data = direct_recv(cw, key, timeout=timeout)
        if data is None:
            raise TimeoutError(
                f"KV payload {oid[:12]} never landed in the direct mailbox "
                f"({timeout}s; holder {addr})"
            )
        out = np.asarray(serialization.loads(data))
    else:
        raise DeviceObjectLostError(
            oid, msg=f"holder answered devobj_pull with kind={kind!r}"
        )
    if release:
        _release_payload(cw, addr, oid)
    return out


def _release_payload(cw, addr, oid: str) -> None:
    """Drop one holder-side pin, best-effort (the holder's TTL reaper is
    the backstop for lost releases)."""

    async def _rel():
        try:
            await cw._devobj_client(tuple(addr)).acall(
                "devobj_release", {"object_id": oid}
            )
        except Exception:
            pass

    try:
        if tuple(addr) == tuple(cw.address):
            cw._device_manager().release_pin(oid)
        else:
            cw._io.spawn(_rel())
    except Exception:
        pass


# ---- cluster prefix registry (GCS rows, devobj/<oid> state-view pattern) ----


def publish_prefix_rows(cw, hashes, desc: dict, holder_id: str) -> list[str]:
    """Write one ``llmprefix/<chain-hash>`` row per covered depth: the row
    at depth k points importers at the sealed payload's FIRST k blocks.
    Fire-and-forget (the registry is a best-effort accelerator — a lost row
    just means a recompute). Returns the row keys for later retraction."""
    keys = []
    for k, h in enumerate(hashes, start=1):
        key = PREFIX_ROW + h.hex()
        row = json.dumps(
            {
                "oid": desc["oid"],
                "addr": desc["addr"],
                "holder_id": holder_id,
                "use_blocks": k,
                "total_blocks": desc["blocks"],
                "block_size": desc["block_size"],
                "nbytes": desc["nbytes"],
            }
        ).encode()

        async def _put(key=key, row=row):
            try:
                await cw.gcs.acall("kv_put", {"key": key, "value": row})
            except Exception:
                pass

        cw._io.spawn(_put())
        keys.append(key)
    return keys


def retract_prefix_rows(cw, keys, oid: str) -> None:
    """Read-check-delete each row: only rows still pointing at ``oid`` are
    removed (last-write-wins rows may already belong to a newer holder)."""

    async def _del(key):
        try:
            got = await cw.gcs.acall("kv_get", {"key": key})
            if not got.get("found"):
                return
            if json.loads(got["value"].decode()).get("oid") != oid:
                return
            await cw.gcs.acall("kv_del", {"key": key})
        except Exception:
            pass

    for key in keys:
        try:
            cw._io.spawn(_del(key))
        except Exception:
            pass


@blocking
def lookup_prefix_row(cw, h: bytes, *, timeout: float = 2.0):
    """Resolve a chain hash to its holder row, or None."""
    try:
        got = cw.gcs.call("kv_get", {"key": PREFIX_ROW + h.hex()}, timeout=timeout)
    except Exception:
        return None
    if not got.get("found"):
        return None
    try:
        return json.loads(got["value"].decode())
    except Exception:
        return None
