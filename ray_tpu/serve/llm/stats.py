"""Plain-int hot-path counters for the continuous-batching LLM engine.

Same pattern as ``rpc.WIRE`` / ``lease_manager.LEASE_STATS``: the scheduler
loop bumps plain ints (no instrument lock per decode step); a flush-time
collector in ``_private/self_metrics.py`` folds them into the
``ray_tpu_serve_llm_*`` instruments. Gauge-shaped state (running sequences,
admission queue depth, KV-block utilization) is NOT mirrored here — the
collector computes it at flush time by summing over ``ENGINES``, the
registry of engines whose scheduler loop is still running, so several
engines in one process fold into one honest series and the gauges drop to
zero when the last engine exits instead of freezing at their final values.
"""

from __future__ import annotations

import weakref

# Engines register here at construction; the scheduler loop's exit (stop or
# crash) withdraws them. WeakSet so an abandoned engine can't pin itself.
ENGINES: "weakref.WeakSet" = weakref.WeakSet()


class _LLMStats:
    __slots__ = (
        "admitted",
        "finished",
        "cancelled",
        "preemptions",
        "prefix_hit_blocks",
        "prefix_miss_blocks",
        "evicted_blocks",
        # Disaggregated serving (ISSUE 20): completed prefill→decode KV
        # handoffs counted on the IMPORTING (decode) side, exports sealed on
        # the prefill side, and cluster-prefix-tier import attempts by
        # outcome (hit = payload landed, miss = no registry row / local
        # cache already covered it, error = row existed but the fetch
        # failed: holder dead, payload evicted, or mailbox timeout).
        "handoffs",
        "handoff_exports",
        "prefix_import_hits",
        "prefix_import_misses",
        "prefix_import_errors",
    )

    def __init__(self):
        for name in self.__slots__:
            setattr(self, name, 0)


LLM = _LLMStats()
