"""Continuous-batching LLM serving (ISSUE 11): paged KV cache + slot-level
scheduler + prefix-cache reuse, streamed through the Serve replica path.

- ``LLMEngine`` — the batching brain: admission into decode slots, chunked
  prefill interleaved with decode, paged-block free-list, prefix cache,
  preemption, per-request token streams.
- ``LLMDeployment`` — serve-ready wrapper (SSE streaming over HTTP).
- ``prefix_route_hint`` — client-side helper producing the router affinity
  hint for cache-aware routing (send as the ``serve_prefix_hash`` header or
  ``handle.options(prefix_hint=...)``).
"""

from ray_tpu.serve.llm.deployment import (
    PREFILL_SUFFIX,
    LLMDeployment,
    disaggregated_llm_app,
)
from ray_tpu.serve.llm.engine import LLMEngine, LLMRequest, block_hashes, prefix_route_hint

__all__ = [
    "LLMDeployment",
    "LLMEngine",
    "LLMRequest",
    "PREFILL_SUFFIX",
    "block_hashes",
    "disaggregated_llm_app",
    "prefix_route_hint",
]
