"""Serve-ready wrapper around ``LLMEngine``: streaming chat behind HTTP.

Deploy it like any callable — the replica holds the engine (params + the
two compiled paged-cache programs), requests stream tokens over SSE through
the existing replica ``_StreamPump`` path, and a client disconnect frees the
request's decode slot and KV blocks immediately via
``StreamingResponse.on_disconnect``:

    from ray_tpu import serve
    from ray_tpu.serve.llm import LLMDeployment

    app = serve.deployment(num_replicas=2)(LLMDeployment).bind(
        model_config={"vocab_size": 512, "d_model": 128, ...},
        engine_config={"num_slots": 8, "block_size": 16},
    )
    serve.run(app, route_prefix="/llm")

    curl -N http://host:port/llm -d '{"tokens": [1,2,3], "max_new_tokens": 16}'
    data: {"token": 42}
    ...
    data: [DONE]

Request body: ``{"tokens": [int], "max_new_tokens": int, "temperature":
float, "top_k": int, "seed": int, "stream": bool}`` — ``stream`` defaults
true (SSE); false buffers and returns ``{"tokens": [...]}``.

Disaggregated serving (ISSUE 20): give the engine ``role="prefill"`` and
requests terminate with a ``{"__llm_handoff__": ...}`` envelope — the
sealed-KV descriptor plus the first sampled token — instead of decoding.
The proxy forwards that descriptor to a decode-pool replica as
``kv_import=`` + ``resume_tokens=`` (+ ``echo_resume``, so the client
still sees the prefill-sampled token in its stream). Build the two-pool
app with :func:`disaggregated_llm_app`.
"""

from __future__ import annotations

import json

from ray_tpu.serve._private.common import PREFILL_SUFFIX  # noqa: F401
from ray_tpu.serve.llm.engine import LLMEngine, prefix_route_hint  # noqa: F401


class LLMDeployment:
    def __init__(
        self,
        model_config: dict,
        engine_config: dict | None = None,
        init_seed: int = 0,
        params=None,
    ):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.transformer import TransformerConfig, init_params

        model_config = dict(model_config)
        dtype = model_config.get("dtype")
        if isinstance(dtype, str):  # JSON-friendly configs
            model_config["dtype"] = jnp.dtype(dtype).type
        self.cfg = TransformerConfig(**model_config)
        if params is None:
            params = init_params(jax.random.PRNGKey(init_seed), self.cfg)
        self.engine = LLMEngine(params, self.cfg, **(engine_config or {}))

    def __call__(self, request):
        from ray_tpu.serve.api import StreamingResponse

        body = request.json() if hasattr(request, "json") else dict(request)
        req = self.engine.submit(
            body["tokens"],
            max_new_tokens=int(body.get("max_new_tokens", 32)),
            temperature=float(body.get("temperature", 0.0)),
            top_k=int(body.get("top_k", 0)),
            seed=int(body.get("seed", 0)),
            resume_tokens=body.get("resume_tokens"),
            kv_import=body.get("kv_import"),
        )
        # Resume tokens a migrated/handed-off request already owns but the
        # CLIENT has not seen yet (the handoff descriptor's first sampled
        # token): echo them ahead of the engine's stream so the client's
        # token sequence is complete. The engine itself never re-emits
        # resume tokens — echoing is presentation, owned here.
        echo = [int(t) for t in (body.get("resume_tokens") or ())] if body.get(
            "echo_resume"
        ) else []
        if self.engine.role == "prefill":
            return self._prefill_call(body, req)
        if not body.get("stream", True):
            try:
                toks = req.result(timeout=float(body.get("timeout", 120.0)))
                return {"tokens": echo + toks}
            except BaseException:
                # A timed-out (or otherwise failed) buffered request must not
                # keep generating into a queue nobody will read — free its
                # decode slot and KV blocks now, like the SSE path does.
                self.engine.cancel(req)
                raise
        engine = self.engine

        def sse():
            try:
                for tok in echo:
                    yield f"data: {json.dumps({'token': tok})}\n\n"
                for tok in req:
                    yield f"data: {json.dumps({'token': tok})}\n\n"
                yield "data: [DONE]\n\n"
            finally:
                # Belt: normal completion makes this a no-op; an aborted
                # generator (pump saw `cancelled` at a yield) frees the
                # request even if on_disconnect never fired.
                engine.cancel(req)

        return StreamingResponse(
            sse(),
            content_type="text/event-stream",
            # Suspenders: fires synchronously from cancel_stream / the idle
            # reaper, so the decode slot and KV blocks free immediately
            # even while the generator is parked waiting for a token.
            on_disconnect=lambda: engine.cancel(req),
            # Migration descriptor: if THIS replica dies mid-stream, the
            # proxy resubmits the original body to another replica with
            # resume_tokens= the tokens it already forwarded; "sse_tokens"
            # tells the proxy how to parse them back out of the SSE chunks
            # it relayed. The one-shot handoff fields must NOT ride along:
            # kv_import's payload is gone after the first import, and a
            # re-echo would duplicate tokens the client already has.
            # Counter-based sampling makes the continuation bit-identical,
            # so the client never notices.
            resume={
                "kind": "sse_tokens",
                "body": {
                    k: v
                    for k, v in body.items()
                    if k not in ("resume_tokens", "kv_import", "echo_resume")
                },
            },
        )

    def _prefill_call(self, body: dict, req) -> dict:
        """Prefill-role request: block until the engine finishes prefill and
        return the handoff envelope the proxy forwards to the decode pool.
        When the engine could not seal a payload (bare process) it decoded
        locally instead — return the plain buffered result so a mono-pool
        fallback still answers the client."""
        try:
            toks = req.result(timeout=float(body.get("timeout", 120.0)))
        except BaseException:
            self.engine.cancel(req)
            raise
        if req.handoff is None:
            return {"tokens": toks}
        desc = dict(req.handoff)
        tok0 = desc.pop("tok0")
        return {
            "__llm_handoff__": {
                "kv_import": desc,
                "resume_tokens": [tok0],
                "body": {
                    k: v
                    for k, v in body.items()
                    if k not in ("resume_tokens", "kv_import", "echo_resume")
                },
            }
        }

    def get_stats(self) -> dict:
        """Engine snapshot (handle-callable; used by tests and benches)."""
        return self.engine.stats()

    def check_health(self):
        self.engine.check_health()

    def drain(self):
        """Controller-initiated drain-before-retire: the engine refuses new
        admissions; in-flight decodes run to completion."""
        self.engine.drain()

    def prepare_for_shutdown(self):
        self.engine.shutdown()


def disaggregated_llm_app(
    model_config: dict,
    engine_config: dict | None = None,
    *,
    name: str = "llm",
    prefill_replicas: int = 1,
    decode_replicas: int = 1,
    cluster_prefix: bool = True,
    max_concurrent_queries: int = 100,
    init_seed: int = 0,
    route_prefix: str | None = "/llm",
):
    """Build the two-pool disaggregated serving application: a decode
    deployment that OWNS the route and a paired ``<name>--prefill``
    deployment the proxy discovers by naming convention. Pool sizes are
    static config (no cross-pool autoscaler yet — see PARITY.md). Returns
    the decode Application; ``serve.run(app)`` deploys both pools.
    """
    from ray_tpu import serve

    engine_config = dict(engine_config or {})
    engine_config.pop("role", None)
    prefill_cfg = dict(
        engine_config, role="prefill", cluster_prefix=cluster_prefix
    )
    decode_cfg = dict(engine_config, role="decode", cluster_prefix=False)
    prefill = serve.deployment(
        num_replicas=int(prefill_replicas),
        name=f"{name}{PREFILL_SUFFIX}",
        max_concurrent_queries=max_concurrent_queries,
        route_prefix=None,
    )(LLMDeployment).bind(
        model_config=model_config,
        engine_config=prefill_cfg,
        init_seed=init_seed,
    )
    decode = serve.deployment(
        num_replicas=int(decode_replicas),
        name=name,
        max_concurrent_queries=max_concurrent_queries,
        route_prefix=route_prefix,
    )(LLMDeployment).bind(
        model_config=model_config,
        engine_config=decode_cfg,
        init_seed=init_seed,
    )
    # The decode app is the root; the prefill app rides as a sibling of
    # the same application tree (deployed together, torn down together).
    decode.extras.append(prefill)
    return decode
