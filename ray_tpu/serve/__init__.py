"""ray_tpu.serve — actor-based model serving with HTTP ingress.

Analog of the reference's Ray Serve (python/ray/serve/): a singleton
controller actor reconciles deployments into replica actors; per-node HTTP
proxy actors route by prefix; Python handles route through a shared Router
with queue-limit-aware round-robin; queue-depth autoscaling. TPU idiom:
replicas pin chips and serve jit-compiled models; @serve.batch feeds the MXU
efficient batch sizes.
"""

from ray_tpu.serve._private.common import AutoscalingConfig, DeploymentConfig  # noqa: F401
from ray_tpu.serve.api import (  # noqa: F401
    Application,
    Deployment,
    delete,
    deployment,
    get_deployment_handle,
    http_address,
    http_addresses,
    ingress,
    run,
    shutdown,
    start,
    status,
)
from ray_tpu.serve.batching import batch  # noqa: F401
from ray_tpu.serve.handle import DeploymentHandle  # noqa: F401
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed  # noqa: F401
from ray_tpu.serve.api import StreamingResponse  # noqa: F401

__all__ = [
    "Application",
    "AutoscalingConfig",
    "DAGDriver",
    "Deployment",
    "DeploymentConfig",
    "DeploymentHandle",
    "batch",
    "delete",
    "deployment",
    "get_deployment_handle",
    "get_multiplexed_model_id",
    "multiplexed",
    "http_address",
    "http_addresses",
    "ingress",
    "run",
    "shutdown",
    "start",
    "status",
    "StreamingResponse",
]
from ray_tpu.serve.drivers import DAGDriver  # noqa: F401,E402
from ray_tpu.serve import http_adapters  # noqa: F401,E402
