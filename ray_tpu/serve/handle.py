"""DeploymentHandle — Python-side calls into a deployment.

Reference: python/ray/serve/handle.py (RayServeHandle / DeploymentHandle):
``handle.remote(*args)`` routes through the shared Router to a replica actor
and returns an ObjectRef; ``handle.method.remote(...)`` calls a specific
method of a class deployment.
"""

from __future__ import annotations

import threading

import ray_tpu


class _MethodCaller:
    def __init__(self, handle: "DeploymentHandle", method_name: str):
        self._handle = handle
        self._method = method_name

    def remote(self, *args, **kwargs):
        return self._handle._invoke(self._method, args, kwargs)


class DeploymentHandle:
    def __init__(
        self,
        deployment_name: str,
        router,
        multiplexed_model_id: str = "",
        prefix_hint: str = "",
    ):
        self._deployment = deployment_name
        self._router = router
        self._multiplexed_model_id = multiplexed_model_id
        self._prefix_hint = prefix_hint

    def options(
        self,
        *,
        multiplexed_model_id: str | None = None,
        prefix_hint: str | None = None,
    ) -> "DeploymentHandle":
        """Per-call options (reference: handle.options(multiplexed_model_id=…)).
        ``prefix_hint`` routes to the replica holding a shared prompt's KV
        prefix-cache blocks (serve.llm.prefix_route_hint). Unspecified
        options keep the handle's current values (pass "" to clear one)."""
        return DeploymentHandle(
            self._deployment,
            self._router,
            multiplexed_model_id=(
                self._multiplexed_model_id
                if multiplexed_model_id is None
                else multiplexed_model_id
            ),
            prefix_hint=self._prefix_hint if prefix_hint is None else prefix_hint,
        )

    def remote(self, *args, **kwargs):
        return self._invoke("__call__", args, kwargs)

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return _MethodCaller(self, item)

    def _invoke(self, method: str, args: tuple, kwargs: dict):
        import time

        model_id = self._multiplexed_model_id
        t0 = time.monotonic()
        # Assign -> dead-replica race: a replica can die after the router
        # hands it out but before it accepts (its table entry lingers until
        # the controller notices). ONE bounded reassign, driven by a cheap
        # GCS liveness probe after submission, keeps that window from
        # surfacing a raw ActorDiedError to the caller.
        exclude: list = []
        for attempt in range(2):
            replica = self._router.assign_replica(
                self._deployment,
                model_id=model_id,
                prefix_hint=self._prefix_hint,
                exclude=exclude,
            )
            try:
                actor = self._router.handle_for(replica)
                ref = actor.handle_request.remote(
                    method, args, kwargs, multiplexed_model_id=model_id
                )
            except Exception:
                self._router.release(replica, deployment=self._deployment)
                self._router.invalidate_handle(replica)
                if attempt == 0:
                    exclude.append(replica["actor_name"])
                    continue
                raise
            if attempt == 0 and not self._router.replica_alive(replica):
                # Submitted into a corpse: the ref is doomed (its error
                # resolves via refcounting; nobody waits on it). Reassign.
                self._router.release(replica, deployment=self._deployment)
                self._router.invalidate_handle(replica)
                exclude.append(replica["actor_name"])
                continue
            break
        # Release the slot once the result lands (fire-and-forget waiter);
        # the assign->result interval feeds ray_tpu_serve_replica_latency_s.
        router = self._router
        deployment = self._deployment

        def _release():
            try:
                ray_tpu.wait([ref], num_returns=1, timeout=3600, fetch_local=False)
            finally:
                router.release(
                    replica, deployment=deployment, duration_s=time.monotonic() - t0
                )

        threading.Thread(target=_release, daemon=True).start()
        return ref
