"""DAGDriver — multi-route graph ingress.

Analog of the reference's python/ray/serve/drivers.py:31: ONE driver
deployment fronts several independently-deployed (and independently
autoscaled) graph branches, dispatching HTTP requests by sub-route and
shaping inputs with an http_adapter. Bind it like any deployment:

    serve.run(DAGDriver.bind({"/a": BranchA.bind(), "/b": BranchB.bind()},
                             http_adapter="ray_tpu.serve.http_adapters.json_request"),
              route_prefix="/")

The bound branch Applications become child deployments whose handles the
replica materializes (the HandleMarker path used by all nested binds).
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import ray_tpu
from ray_tpu.serve.api import deployment
from ray_tpu.serve.http_adapters import load_http_adapter


@deployment
class DAGDriver:
    MATCH_ALL_ROUTE_PREFIX = "/"

    def __init__(self, dags, http_adapter: Optional[Union[str, Callable]] = None):
        """``dags``: one handle, or {route: handle} for multi-route apps —
        by construction the values arrive as DeploymentHandles (bound
        Applications are materialized by the replica)."""
        if not isinstance(dags, dict):
            dags = {self.MATCH_ALL_ROUTE_PREFIX: dags}
        self.dags = dict(dags)
        self.http_adapter = load_http_adapter(http_adapter)

    def _match_route(self, path: str) -> Optional[str]:
        """Exact match first, then longest matching prefix at a path
        boundary (mirrors the proxy's longest-prefix deployment routing
        one level down)."""
        if path in self.dags:
            return path
        best = None
        for route in self.dags:
            if path.startswith(route.rstrip("/") + "/") or route == "/":
                if best is None or len(route) > len(best):
                    best = route
        return best

    def __call__(self, request):
        # Dispatch on the path RELATIVE to this driver's mount point, so a
        # driver at route_prefix="/api" still serves {"/a": ...} at /api/a.
        path = getattr(request, "sub_path", None) or request.path
        route = self._match_route(path)
        if route is None:
            raise ValueError(f"no DAG route matches path {path!r}")
        inp = self.http_adapter(request)
        return ray_tpu.get(self.dags[route].remote(inp), timeout=120)

    # Python-side entry points (reference: DAGDriver.predict/_with_route).
    def predict(self, *args, **kwargs):
        if self.MATCH_ALL_ROUTE_PREFIX in self.dags:
            route = self.MATCH_ALL_ROUTE_PREFIX
        elif len(self.dags) == 1:
            route = next(iter(self.dags))
        else:
            raise ValueError(
                f"predict() is ambiguous with routes {sorted(self.dags)}; "
                "use predict_with_route()"
            )
        return ray_tpu.get(self.dags[route].remote(*args, **kwargs), timeout=120)

    def predict_with_route(self, route: str, *args, **kwargs):
        if route not in self.dags:
            raise ValueError(f"unknown DAG route {route!r} (routes: {sorted(self.dags)})")
        return ray_tpu.get(self.dags[route].remote(*args, **kwargs), timeout=120)

    def get_routes(self) -> list:
        return sorted(self.dags)
