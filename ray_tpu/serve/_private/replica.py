"""Replica actor (reference: python/ray/serve/_private/replica.py:384
RayServeReplica, handle_request at :639).

Each replica is a dedicated actor process wrapping the user callable. On a
TPU node a replica can pin the chip and hold a jit-compiled model — the
TPU-native serving idiom: one replica per chip, XLA-compiled predict, queue
depth reported to the controller for autoscaling.
"""

from __future__ import annotations

import pickle
import threading
import time


class Replica:
    def __init__(self, import_spec: bytes, user_config=None):
        from ray_tpu.serve._private.common import HandleMarker

        cls_or_fn, init_args, init_kwargs = pickle.loads(import_spec)

        def materialize(v):
            if isinstance(v, HandleMarker):
                # Composition: a bound child deployment becomes a live handle.
                from ray_tpu.serve.api import get_deployment_handle

                return get_deployment_handle(v.deployment_name)
            if isinstance(v, list):
                return [materialize(x) for x in v]
            if isinstance(v, tuple):
                return tuple(materialize(x) for x in v)
            if isinstance(v, dict):
                return {k: materialize(x) for k, x in v.items()}
            return v

        init_args = tuple(materialize(a) for a in init_args)
        init_kwargs = {k: materialize(v) for k, v in init_kwargs.items()}
        if isinstance(cls_or_fn, type):
            self._callable = cls_or_fn(*init_args, **init_kwargs)
        else:
            self._callable = cls_or_fn
        self._is_function = not isinstance(cls_or_fn, type)
        self._ongoing = 0
        self._total = 0
        self._lock = threading.Lock()
        self._streams: dict = {}
        self._stream_counter = 0
        if user_config is not None:
            self.reconfigure(user_config)

    def reconfigure(self, user_config):
        """Push a new user_config without restarting (reference:
        deployment_state version/user_config rolling update)."""
        fn = getattr(self._callable, "reconfigure", None)
        if fn is not None:
            fn(user_config)
        return True

    def handle_request(
        self, method_name: str, args: tuple, kwargs: dict, multiplexed_model_id: str = ""
    ):
        from ray_tpu.serve.multiplex import _set_multiplexed_model_id

        with self._lock:
            self._ongoing += 1
            self._total += 1
        try:
            _set_multiplexed_model_id(multiplexed_model_id)
            if self._is_function or method_name == "__call__":
                target = self._callable
            else:
                target = getattr(self._callable, method_name)
            return target(*args, **kwargs)
        finally:
            with self._lock:
                self._ongoing -= 1

    def handle_http_request(
        self,
        method: str,
        path: str,
        query: dict,
        body: bytes,
        headers: dict,
        multiplexed_model_id: str = "",
    ):
        """HTTP entry: the callable gets a lightweight Request object. The
        proxy passes the multiplexed model id it already extracted for
        routing — one extraction, no divergence."""
        request = HTTPRequest(method=method, path=path, query=query, body=body, headers=headers)
        result = self.handle_request(
            "__call__", (request,), {}, multiplexed_model_id=multiplexed_model_id
        )
        import inspect

        from ray_tpu.serve.api import StreamingResponse

        if isinstance(result, StreamingResponse) or inspect.isgenerator(result):
            # Chunked/SSE responses (reference: serve streaming responses):
            # the generator stays alive here; the proxy pumps it via
            # next_stream_chunk and writes chunks to the socket as produced.
            if isinstance(result, StreamingResponse):
                gen, ctype = iter(result.iterator), result.content_type
            else:
                gen, ctype = result, "application/octet-stream"
            with self._lock:
                self._reap_idle_streams_locked()
                self._stream_counter += 1
                sid = str(self._stream_counter)
                self._streams[sid] = {
                    "gen": gen,
                    "model_id": multiplexed_model_id,
                    "last_pump": time.time(),
                }
            return {"__serve_stream__": sid, "content_type": ctype}
        return result

    def _reap_idle_streams_locked(self):
        """A client that disconnected mid-stream stops the proxy's pump with
        no cancel RPC; close + drop generators nobody pumped for 5 minutes
        so their finalizers run and state doesn't accumulate."""
        now = time.time()
        for sid, st in list(self._streams.items()):
            if now - st["last_pump"] > 300.0:
                self._streams.pop(sid, None)
                try:
                    st["gen"].close()
                except Exception:
                    pass

    def next_stream_chunk(self, sid: str):
        """Pump ONE item from a live response stream — returning on the
        first produced item keeps time-to-first-byte at one-item latency (a
        batch pump would buffer a slow producer's output into bursts).
        Returns {"chunks": [bytes], "done": bool} or None for unknown
        streams."""
        from ray_tpu.serve.multiplex import _set_multiplexed_model_id

        with self._lock:
            st = self._streams.get(sid)
            if st is not None:
                st["last_pump"] = time.time()
        if st is None:
            return None
        # The generator body runs HERE, not in handle_request: re-scope the
        # multiplexed model id so concurrent requests on this replica can't
        # bleed their id into this stream's continuation.
        _set_multiplexed_model_id(st["model_id"])
        chunks = []
        done = False
        try:
            chunks.append(_encode_chunk(next(st["gen"])))
        except StopIteration:
            done = True
        except Exception:
            with self._lock:
                self._streams.pop(sid, None)
            raise
        if done:
            with self._lock:
                self._streams.pop(sid, None)
        return {"chunks": chunks, "done": done}

    def get_metrics(self) -> dict:
        """Queue stats for autoscaling (reference: autoscaling_metrics.py)."""
        with self._lock:
            return {"ongoing": self._ongoing, "total": self._total, "ts": time.time()}

    def check_health(self) -> bool:
        fn = getattr(self._callable, "check_health", None)
        if fn is not None:
            fn()
        return True

    def prepare_for_shutdown(self):
        """Invoke the user callable's shutdown hook, if any (reference:
        replica graceful_shutdown path)."""
        fn = getattr(self._callable, "prepare_for_shutdown", None) or getattr(
            self._callable, "shutdown", None
        )
        if fn is not None and callable(fn):
            fn()
        return True


class HTTPRequest:
    """Minimal request object handed to deployments from the proxy
    (stands in for the reference's starlette.requests.Request)."""

    def __init__(self, method: str, path: str, query: dict, body: bytes, headers: dict):
        self.method = method
        self.path = path
        self.query_params = query
        self.body = body
        self.headers = headers

    def json(self):
        import json as _json

        return _json.loads(self.body or b"null")

    def text(self) -> str:
        return (self.body or b"").decode()


def _encode_chunk(item) -> bytes:
    if isinstance(item, bytes):
        return item
    if isinstance(item, str):
        return item.encode()
    import json as _json

    return (_json.dumps(item) + "\n").encode()
