"""Replica actor (reference: python/ray/serve/_private/replica.py:384
RayServeReplica, handle_request at :639).

Each replica is a dedicated actor process wrapping the user callable. On a
TPU node a replica can pin the chip and hold a jit-compiled model — the
TPU-native serving idiom: one replica per chip, XLA-compiled predict, queue
depth reported to the controller for autoscaling.
"""

from __future__ import annotations

import pickle
import queue as _queue
import threading
import time


class _StreamPump:
    """Runs one response stream's generator on a dedicated thread,
    prefetching into a bounded queue. The replica's RPC surface only ever
    drains the queue with a short timeout, so a producer that stalls inside
    its generator cannot head-of-line-block the replica's task slots (and a
    disconnected client's pump dies on cancel, not the 5-minute reap)."""

    def __init__(self, gen, model_id: str, on_cancel=None):
        self.gen = gen
        self.model_id = model_id
        self.on_cancel = on_cancel
        self.q: _queue.Queue = _queue.Queue(maxsize=8)  # backpressure bound
        self.cancelled = threading.Event()
        self.last_pump = time.time()
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def _put(self, item) -> bool:
        while not self.cancelled.is_set():
            try:
                self.q.put(item, timeout=0.25)
                return True
            except _queue.Full:
                continue
        return False

    def _run(self):
        from ray_tpu.serve.multiplex import _set_multiplexed_model_id

        # The generator body runs HERE: scope the multiplexed model id to
        # this thread so concurrent requests can't bleed theirs in.
        _set_multiplexed_model_id(self.model_id)
        try:
            for item in self.gen:
                if not self._put(("chunk", _encode_chunk(item))):
                    break
            else:
                self._put(("done", None))
        except BaseException as e:  # delivered to the consumer, then re-raised
            self._put(("error", e))
        finally:
            try:
                self.gen.close()
            except Exception:
                pass

    def cancel(self):
        self.cancelled.set()
        # Producer-side teardown (StreamingResponse.on_disconnect) fires
        # HERE, synchronously: the generator thread may be parked inside
        # its producer (e.g. the LLM engine's token queue) and only
        # observes `cancelled` at its next yield — resources like decode
        # slots and KV blocks must not wait for that. dict.pop is
        # GIL-atomic, so concurrent cancel()s fire the callback once.
        cb = self.__dict__.pop("on_cancel", None)
        if cb is not None:
            try:
                cb()
            except Exception:
                pass


class Replica:
    def __init__(
        self,
        import_spec: bytes,
        user_config=None,
        deployment_name: str = "",
        replica_id: str = "",
        controller_name: str = "",
    ):
        from ray_tpu.serve._private.common import HandleMarker

        cls_or_fn, init_args, init_kwargs = pickle.loads(import_spec)

        def materialize(v):
            if isinstance(v, HandleMarker):
                # Composition: a bound child deployment becomes a live handle.
                from ray_tpu.serve.api import get_deployment_handle

                return get_deployment_handle(v.deployment_name)
            if isinstance(v, list):
                return [materialize(x) for x in v]
            if isinstance(v, tuple):
                return tuple(materialize(x) for x in v)
            if isinstance(v, dict):
                return {k: materialize(x) for k, x in v.items()}
            return v

        init_args = tuple(materialize(a) for a in init_args)
        init_kwargs = {k: materialize(v) for k, v in init_kwargs.items()}
        if isinstance(cls_or_fn, type):
            self._callable = cls_or_fn(*init_args, **init_kwargs)
        else:
            self._callable = cls_or_fn
        self._is_function = not isinstance(cls_or_fn, type)
        self._ongoing = 0
        self._total = 0
        self._lock = threading.Lock()
        self._streams: dict = {}
        self._stream_counter = 0
        self._draining = False
        self._deployment_name = deployment_name
        self._replica_id = replica_id
        if user_config is not None:
            self.reconfigure(user_config)
        # Autoscaling metrics PUSH (reference: autoscaling_metrics.py —
        # replicas report their own queue depth). A dedicated daemon thread,
        # NOT an actor method: actor calls share the request thread pool, so
        # a polled metric could only run when a slot freed — biased low by
        # construction.
        if deployment_name and controller_name:
            self._metrics_stop = threading.Event()

            def _push_loop():
                import ray_tpu

                controller = None
                while not self._metrics_stop.wait(1.0):
                    try:
                        if controller is None:
                            controller = ray_tpu.get_actor(controller_name)
                        controller.record_metrics.remote(
                            deployment_name, replica_id, self._ongoing
                        )
                    except Exception:
                        controller = None  # controller restarting; re-resolve

            threading.Thread(
                target=_push_loop, name="replica-metrics", daemon=True
            ).start()

    def reconfigure(self, user_config):
        """Push a new user_config without restarting (reference:
        deployment_state version/user_config rolling update)."""
        fn = getattr(self._callable, "reconfigure", None)
        if fn is not None:
            fn(user_config)
        return True

    def handle_request(
        self, method_name: str, args: tuple, kwargs: dict, multiplexed_model_id: str = ""
    ):
        from ray_tpu.serve.multiplex import _set_multiplexed_model_id

        with self._lock:
            if self._draining:
                # Drain-before-retire: NEW requests are refused with the
                # typed error (proxy/handle reassign on it); in-flight
                # requests and live stream pumps keep running to completion.
                from ray_tpu.exceptions import ReplicaDrainingError

                raise ReplicaDrainingError(
                    deployment=self._deployment_name,
                    replica_id=self._replica_id,
                )
            self._ongoing += 1
            self._total += 1
        try:
            _set_multiplexed_model_id(multiplexed_model_id)
            if self._is_function or method_name == "__call__":
                target = self._callable
            else:
                target = getattr(self._callable, method_name)
            return target(*args, **kwargs)
        finally:
            with self._lock:
                self._ongoing -= 1

    def handle_http_request(
        self,
        method: str,
        path: str,
        query: dict,
        body: bytes,
        headers: dict,
        multiplexed_model_id: str = "",
        route_prefix: str | None = None,
        raw_query_string: str | None = None,
    ):
        """HTTP entry: the callable gets a lightweight Request object. The
        proxy passes the multiplexed model id it already extracted for
        routing — one extraction, no divergence — the matched route
        prefix so sub-route dispatch (DAGDriver) works under any mount, and
        the raw query string so ASGI ingress apps see wire-exact bytes."""
        request = HTTPRequest(
            method=method, path=path, query=query, body=body, headers=headers,
            route_prefix=route_prefix, raw_query_string=raw_query_string,
        )
        result = self.handle_request(
            "__call__", (request,), {}, multiplexed_model_id=multiplexed_model_id
        )
        import inspect

        from ray_tpu.serve.api import StreamingResponse

        if isinstance(result, StreamingResponse) or inspect.isgenerator(result):
            # Chunked/SSE responses (reference: serve streaming responses):
            # the generator stays alive here; the proxy pumps it via
            # next_stream_chunk and writes chunks to the socket as produced.
            if isinstance(result, StreamingResponse):
                gen, ctype = iter(result.iterator), result.content_type
                status = getattr(result, "status", 200)
                extra = getattr(result, "headers", None) or {}
                on_cancel = getattr(result, "on_disconnect", None)
                resume = getattr(result, "resume", None)
            else:
                gen, ctype = result, "application/octet-stream"
                status, extra = 200, {}
                on_cancel = resume = None
            with self._lock:
                self._reap_idle_streams_locked()
                self._stream_counter += 1
                sid = str(self._stream_counter)
                self._streams[sid] = _StreamPump(
                    gen, multiplexed_model_id, on_cancel=on_cancel
                )
            envelope = {
                "__serve_stream__": sid,
                "content_type": ctype,
                "status": status,
                "headers": extra,
            }
            if resume is not None:
                # Migration descriptor rides the envelope: the proxy uses
                # it to resubmit this request elsewhere if THIS replica
                # dies mid-stream. The deployment supplies kind + body; the
                # ORIGINAL routing context (method/path/headers/model id/
                # mount) is stamped here so the resumed request dispatches
                # identically — a multiplexed or sub-routed deployment must
                # not resume under different semantics.
                envelope["__serve_resume__"] = dict(
                    resume,
                    ctx={
                        "method": method,
                        "path": path,
                        "query": query,
                        "headers": headers,
                        "model_id": multiplexed_model_id,
                        "route_prefix": route_prefix,
                        "raw_query": raw_query_string,
                    },
                )
            return envelope
        return result

    def _reap_idle_streams_locked(self):
        """Backstop for proxies that died mid-stream (normal disconnects
        send cancel_stream): cancel pumps nobody drained for 5 minutes so
        generator finalizers run and state doesn't accumulate."""
        now = time.time()
        for sid, pump in list(self._streams.items()):
            if now - pump.last_pump > 300.0:
                self._streams.pop(sid, None)
                pump.cancel()

    def next_stream_chunk(self, sid: str):
        """Drain the stream's prefetch queue: block briefly for the first
        chunk (one-item latency for time-to-first-byte), then sweep whatever
        else is already buffered into the same response. Returns
        {"chunks": [bytes], "done": bool} — empty chunks + done=False means
        "nothing yet, poll again" — or None for unknown streams."""
        with self._lock:
            pump = self._streams.get(sid)
            if pump is not None:
                pump.last_pump = time.time()
        if pump is None:
            return None
        chunks: list[bytes] = []
        done = False
        error = None
        block = True
        while True:
            try:
                kind, payload = pump.q.get(timeout=0.5) if block else pump.q.get_nowait()
            except _queue.Empty:
                break
            block = False
            if kind == "chunk":
                chunks.append(payload)
            elif kind == "done":
                done = True
                break
            else:  # error
                error = payload
                break
        if error is not None and chunks:
            # Deliver what the producer yielded BEFORE it raised; the error
            # surfaces on the next poll (parity with the old per-item pump).
            pump.q.put(("error", error))
            return {"chunks": chunks, "done": False}
        if done or error is not None:
            with self._lock:
                self._streams.pop(sid, None)
        if error is not None:
            raise error
        return {"chunks": chunks, "done": done}

    def cancel_stream(self, sid: str):
        """Proxy-initiated teardown on client disconnect (reference: ASGI
        disconnect -> request cancellation): stop the pump thread now
        instead of waiting out the idle reaper."""
        with self._lock:
            pump = self._streams.pop(sid, None)
        if pump is not None:
            pump.cancel()
        return True

    def get_metrics(self) -> dict:
        """Queue stats for autoscaling (reference: autoscaling_metrics.py)."""
        with self._lock:
            return {"ongoing": self._ongoing, "total": self._total, "ts": time.time()}

    def drain(self) -> bool:
        """Enter drain mode (controller-initiated, deliberate retirement):
        refuse NEW requests with the typed ReplicaDrainingError while
        in-flight requests and live stream pumps run to completion. The
        user callable's own drain() hook (e.g. the LLM engine's
        refuse-admissions flag) is forwarded to."""
        with self._lock:
            self._draining = True
        fn = getattr(self._callable, "drain", None)
        if fn is not None and callable(fn):
            try:
                fn()
            except Exception:
                pass
        return True

    # While draining, a pump nobody polled for this long stops COUNTING
    # toward drain completion: its proxy probably died without
    # cancel_stream (a live proxy polls sub-second), and the normal 300s
    # idle reaper only runs from handle_http_request, which the drain gate
    # refuses — without this, one orphan pump rides out the whole
    # drain_timeout_s on an otherwise idle replica. The pump is NOT
    # cancelled here: a slow-but-alive consumer (proxy blocked in a big
    # send) must not be silently truncated as "complete" — if it is still
    # alive at retire, its next poll gets the typed went-away error and
    # resumable streams migrate.
    _DRAIN_IDLE_EXCLUDE_S = 10.0

    def drain_status(self) -> dict:
        """What the controller's drainer polls: retire once ongoing == 0
        and no RECENTLY-PUMPED stream remains (or drain_timeout_s
        expires)."""
        with self._lock:
            now = time.time()
            streams = (
                sum(
                    1
                    for pump in self._streams.values()
                    if now - pump.last_pump <= self._DRAIN_IDLE_EXCLUDE_S
                )
                if self._draining
                else len(self._streams)
            )
            return {
                "draining": self._draining,
                "ongoing": self._ongoing,
                "streams": streams,
            }

    def check_health(self) -> bool:
        fn = getattr(self._callable, "check_health", None)
        if fn is not None:
            fn()
        return True

    def prepare_for_shutdown(self):
        """Invoke the user callable's shutdown hook, if any (reference:
        replica graceful_shutdown path)."""
        stop = getattr(self, "_metrics_stop", None)
        if stop is not None:
            stop.set()  # retired replicas must not keep pushing metrics
        fn = getattr(self._callable, "prepare_for_shutdown", None) or getattr(
            self._callable, "shutdown", None
        )
        if fn is not None and callable(fn):
            fn()
        return True


class HTTPRequest:
    """Minimal request object handed to deployments from the proxy
    (stands in for the reference's starlette.requests.Request)."""

    def __init__(self, method: str, path: str, query: dict, body: bytes, headers: dict,
                 route_prefix: str | None = None, raw_query_string: str | None = None):
        self.method = method
        self.path = path
        self.query_params = query
        self.body = body
        self.headers = headers
        self.route_prefix = route_prefix
        # Wire-exact query string (duplicate keys/order intact) for ASGI
        # ingress; query_params remains the collapsed dict convenience.
        self.raw_query_string = raw_query_string

    @property
    def sub_path(self) -> str:
        """Path RELATIVE to the deployment's matched route prefix — what
        sub-route dispatch (DAGDriver) should match on, valid under any
        mount point."""
        if not self.route_prefix or self.route_prefix == "/":
            return self.path
        rest = self.path[len(self.route_prefix.rstrip("/")):]
        return rest if rest.startswith("/") else "/" + rest if rest else "/"

    def json(self):
        import json as _json

        return _json.loads(self.body or b"null")

    def text(self) -> str:
        return (self.body or b"").decode()


def _encode_chunk(item) -> bytes:
    if isinstance(item, bytes):
        return item
    if isinstance(item, str):
        return item.encode()
    import json as _json

    return (_json.dumps(item) + "\n").encode()
