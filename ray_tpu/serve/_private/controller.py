"""ServeController — the singleton control-plane actor.

Reference: python/ray/serve/_private/controller.py:79 ServeController,
deployment reconciliation in _private/deployment_state.py (DeploymentState
:1115, _scale_deployment_replicas :1493, DeploymentStateManager :2073), config
fan-out via long-poll (_private/long_poll.py), queue-depth autoscaling
(autoscaling_policy.py:9,53).

The controller actor holds target state (deployments + configs), runs a
reconcile thread that starts/stops replica actors to match, health-checks
replicas, collects queue metrics, and serves long-poll subscriptions from
routers/proxies for the replica membership table.

Replica lifecycle rides the AIR execution layer (``air/execution``
``ActorManager`` + ``FixedResourceManager``) — the same audited
start/failure/release substrate beneath Tune and Train: replica actors are
tracked actors (named, ``max_concurrency``-tuned via ``actor_options``),
process death fires ``on_failure`` (replica leaves the routing table, the
reconcile pass starts a replacement of the TARGET version — version-aware
replacement is controller policy, so manager-level restart stays off), and
resource acquisitions release with the actor, never leaking budget. A
dedicated pump thread drives ``ActorManager.next``; every manager call
holds ``_mgr_lock`` (taken OUTSIDE ``self._lock`` — callbacks run under it
and take ``self._lock`` inside).
"""

from __future__ import annotations

import logging
import pickle
import threading
import time
import uuid

import ray_tpu
from ray_tpu._private import self_metrics
from ray_tpu.air.execution import ActorManager, FixedResourceManager, ResourceRequest
from ray_tpu.serve._private.common import (
    AutoscalingConfig,
    DeploymentConfig,
    DeploymentInfo,
    ReplicaInfo,
)

logger = logging.getLogger(__name__)


class ServeController:
    def __init__(self):
        # name -> DeploymentInfo (target state)
        self._deployments: dict[str, DeploymentInfo] = {}
        # name -> list[ReplicaInfo] (RUNNING replicas, in the routing table)
        self._replicas: dict[str, list[ReplicaInfo]] = {}
        # name -> {replica_id: created_ts} for STARTING replicas (created,
        # not yet healthy); drives both the over-start guard and the
        # rolling-update stall detector.
        self._starting_births: dict[str, dict[str, float]] = {}
        self._replica_handles: dict[str, object] = {}
        # AIR execution layer: replica actors are manager-tracked. _mgr_lock
        # serializes every manager call (pump thread, reconcile thread, RPC
        # threads) and is ALWAYS taken outside self._lock.
        self._mgr = ActorManager(FixedResourceManager())
        self._mgr_lock = threading.RLock()
        self._replica_tracked: dict[str, object] = {}  # replica_id -> TrackedActor
        # autoscaling bookkeeping
        self._metrics: dict[str, dict] = {}
        self._scale_marks: dict[str, float] = {}
        # replica_id -> last health-check timestamp (RUNNING replicas)
        self._health_marks: dict[str, float] = {}
        # name -> forced retires not yet matched by a new healthy replica.
        # Caps the stall-breaker at maxUnavailable=1: a rollout whose new
        # version never becomes healthy sacrifices at most one old replica.
        self._forced_debt: dict[str, int] = {}
        # replica_id -> drain record for replicas in drain-before-retire
        # (out of the routing table, refusing new work, finishing in-flight
        # streams). A health-check failure mid-drain pops the record and
        # retires IMMEDIATELY; the drain thread yields to it.
        self._draining: dict[str, dict] = {}
        self._lock = threading.RLock()
        self._epoch = 0
        self._epoch_cv = threading.Condition(self._lock)
        self._shutdown = False
        # Proxy fleet (reference: _private/http_state.py HTTPProxyState
        # manager): one ingress proxy actor per ALIVE node, health-checked
        # and restarted on a DEDICATED thread — proxy starts/health probes
        # block for seconds and must not stall replica reconciliation.
        self._proxies: dict[str, dict] = {}
        self._proxy_starting: set[str] = set()
        # node_id -> (consecutive start failures, monotonic next-retry time).
        # With a fixed http_port and several raylets sharing one host (the
        # simulated-cluster topology) all but one bind fails with EADDRINUSE;
        # exponential backoff keeps the reconciler from retrying every tick.
        self._proxy_backoff: dict[str, tuple[int, float]] = {}
        self._http_cfg: tuple | None = None
        self._proxy_thread: threading.Thread | None = None
        self._mgr_thread = threading.Thread(
            target=self._manager_loop, name="serve-actor-manager", daemon=True
        )
        self._mgr_thread.start()
        self._reconcile_thread = threading.Thread(
            target=self._reconcile_loop, name="serve-reconcile", daemon=True
        )
        self._reconcile_thread.start()

    def _manager_loop(self):
        """Drive the ActorManager: starts pending replicas, polls liveness,
        dispatches task callbacks (readiness checks) on this thread."""
        while not self._shutdown:
            try:
                with self._mgr_lock:
                    progressed = self._mgr.next(timeout=0.2)
            except Exception:
                logger.exception("serve actor-manager pump failed")
                progressed = False
            if not progressed:
                time.sleep(0.05)

    # ------------------------------------------------------------------
    # Target-state API (called by serve.run / serve.delete)
    # ------------------------------------------------------------------
    def deploy(self, infos: list) -> bool:
        with self._lock:
            for raw in infos:
                info: DeploymentInfo = pickle.loads(raw) if isinstance(raw, bytes) else raw
                prev = self._deployments.get(info.name)
                self._deployments[info.name] = info
                if prev is not None and prev.config.version != info.config.version:
                    pass  # rolling update handled by reconcile (version mismatch)
        self._reconcile_once()
        return True

    def delete_deployments(self, names: list) -> bool:
        with self._lock:
            for name in names:
                self._deployments.pop(name, None)
        self._reconcile_once()
        return True

    def get_deployments(self) -> dict:
        with self._lock:
            return {
                name: {
                    "num_replicas": len(self._replicas.get(name, [])),
                    "num_replicas_current_version": sum(
                        1
                        for r in self._replicas.get(name, [])
                        if r.version == info.config.version
                    ),
                    "target": self._target_replicas(info, mutate=False),
                    "route_prefix": info.route_prefix,
                    "version": info.config.version,
                }
                for name, info in self._deployments.items()
            }

    def graceful_shutdown(self):
        with self._lock:
            self._deployments.clear()
        self._reconcile_once()
        self._shutdown = True
        # Guaranteed release: whatever reconcile missed (mid-start replicas,
        # in-flight probes), the manager kills and frees.
        with self._mgr_lock:
            self._mgr.clear()
        return True

    # ------------------------------------------------------------------
    # Long-poll routing table (reference: long_poll.py LongPollHost)
    # ------------------------------------------------------------------
    def get_routing_table(self, known_epoch: int = -1, timeout_s: float = 30.0) -> dict:
        """Block until the table changes from known_epoch (long poll)."""
        deadline = time.time() + timeout_s
        with self._epoch_cv:
            while self._epoch == known_epoch and not self._shutdown:
                remaining = deadline - time.time()
                if remaining <= 0:
                    break
                self._epoch_cv.wait(remaining)
            table = {
                name: {
                    "replicas": [
                        {
                            "replica_id": r.replica_id,
                            "actor_name": r.actor_name,
                            "max_concurrent_queries": r.max_concurrent_queries,
                        }
                        for r in reps
                    ],
                    "route_prefix": self._deployments[name].route_prefix
                    if name in self._deployments
                    else None,
                }
                for name, reps in self._replicas.items()
                if name in self._deployments
            }
            return {"epoch": self._epoch, "table": table}

    def _bump_epoch_locked(self):
        self._epoch += 1
        self._epoch_cv.notify_all()

    # ------------------------------------------------------------------
    # Proxy fleet (reference: _private/http_state.py:32 HTTPProxyStateManager
    # + http_proxy.py:553 — one HTTPProxyActor per node, controller-managed)
    # ------------------------------------------------------------------
    def ensure_http(self, host: str = "127.0.0.1", port: int = 0) -> dict:
        """Enable per-node ingress; returns node_id -> [host, port] once at
        least one proxy is serving."""
        with self._lock:
            self._http_cfg = (host, port)
            if self._proxy_thread is None or not self._proxy_thread.is_alive():
                self._proxy_thread = threading.Thread(
                    target=self._proxy_loop, name="serve-proxy-fleet", daemon=True
                )
                self._proxy_thread.start()
        # First call waits for the initial proxy so serve.start() can hand
        # back a usable address.
        deadline = time.time() + 60
        while time.time() < deadline and not self.proxy_addresses():
            time.sleep(0.1)
        return self.proxy_addresses()

    def _proxy_loop(self):
        while not self._shutdown:
            try:
                self._reconcile_proxies()
            except Exception:
                logger.exception("proxy reconcile failed")
            time.sleep(1.0)

    def proxy_addresses(self) -> dict:
        with self._lock:
            return {
                nid: list(p["address"])
                for nid, p in self._proxies.items()
                if p.get("address") is not None
            }

    def _reconcile_proxies(self):
        with self._lock:
            cfg = self._http_cfg
        if cfg is None:
            return
        host, port = cfg
        try:
            nodes = ray_tpu.nodes()
        except Exception:
            return
        alive = {
            n["node_id"] for n in nodes if str(n.get("state", "ALIVE")).upper() == "ALIVE"
        }
        with self._lock:
            proxies = dict(self._proxies)
        # Ingress on a dead node is gone with the node: forget it so routing
        # (and http_address()) only ever names live proxies.
        for nid in list(proxies):
            if nid not in alive:
                with self._lock:
                    self._proxies.pop(nid, None)
                    self._proxy_backoff.pop(nid, None)
                try:
                    ray_tpu.kill(proxies[nid]["handle"])
                except Exception:
                    pass
        with self._lock:
            # Backoff entries can exist for nodes that never got a proxy up
            # (every start failed) — purge those for departed nodes too.
            for nid in list(self._proxy_backoff):
                if nid not in alive:
                    self._proxy_backoff.pop(nid, None)
        for nid in alive:
            with self._lock:
                if nid in self._proxy_starting:
                    continue  # a start for this node is already in flight
                backoff = self._proxy_backoff.get(nid)
            if (
                backoff is not None
                and nid not in proxies
                and time.monotonic() < backoff[1]
            ):
                continue  # recent start failure: wait out the backoff
            rec = proxies.get(nid)
            if rec is not None:
                if time.time() - rec.get("checked", 0) < 5.0:
                    continue
                try:
                    ray_tpu.get(rec["handle"].ready.remote(), timeout=5)
                    with self._lock:
                        if nid in self._proxies:
                            self._proxies[nid]["checked"] = time.time()
                    continue
                except Exception:
                    logger.warning("serve proxy on node %s failed health check", nid[:8])
                    with self._lock:
                        self._proxies.pop(nid, None)
                    try:
                        ray_tpu.kill(rec["handle"])
                    except Exception:
                        pass
            self._start_proxy(nid, host, port)

    def _start_proxy(self, node_id: str, host: str, port: int):
        from ray_tpu.serve._private.common import CONTROLLER_NAME, PROXY_NAME
        from ray_tpu.serve._private.http_proxy import HTTPProxy

        # Unique name per incarnation: a dead proxy's name can linger in the
        # GCS registry until death propagation completes.
        name = f"{PROXY_NAME}:{node_id[:12]}:{uuid.uuid4().hex[:6]}"
        handle = None
        with self._lock:
            if node_id in self._proxy_starting:
                return
            self._proxy_starting.add(node_id)
        try:
            cls = ray_tpu.remote(
                num_cpus=0,
                name=name,
                max_concurrency=16,
                scheduling_strategy=f"node:{node_id}",
            )(HTTPProxy)
            handle = cls.remote(CONTROLLER_NAME, host, port)
            addr = ray_tpu.get(handle.address.remote(), timeout=30)
            with self._lock:
                self._proxies[node_id] = {
                    "handle": handle,
                    "address": tuple(addr),
                    "checked": time.time(),
                }
            logger.info("serve proxy up on node %s at %s", node_id[:8], addr)
            with self._lock:
                self._proxy_backoff.pop(node_id, None)
        except Exception as e:
            with self._lock:
                fails = self._proxy_backoff.get(node_id, (0, 0.0))[0] + 1
                delay = min(2.0 * (2 ** (fails - 1)), 60.0)
                self._proxy_backoff[node_id] = (fails, time.monotonic() + delay)
            if fails == 1 or fails % 5 == 0:
                logger.exception(
                    "failed to start serve proxy on node %s "
                    "(attempt %d, next retry in %.0fs): %s",
                    node_id[:8], fails, delay, e,
                )
            if handle is not None:
                try:
                    ray_tpu.kill(handle)  # don't leak a half-started proxy
                except Exception:
                    pass
        finally:
            with self._lock:
                self._proxy_starting.discard(node_id)

    def shutdown_proxies(self):
        with self._lock:
            proxies, self._proxies = dict(self._proxies), {}
            self._http_cfg = None
        for rec in proxies.values():
            try:
                ray_tpu.kill(rec["handle"])
            except Exception:
                pass
        return True

    # ------------------------------------------------------------------
    # Metrics ingest (replicas push; reference: autoscaling_metrics.py)
    # ------------------------------------------------------------------
    def record_metrics(self, deployment: str, replica_id: str, ongoing: int) -> bool:
        with self._lock:
            self._metrics.setdefault(deployment, {})[replica_id] = (ongoing, time.time())
        return True

    def get_autoscaling_metrics(self) -> dict:
        """Current per-replica queue depths (observability + tests)."""
        with self._lock:
            return {
                name: {rid: m[0] for rid, m in reps.items()}
                for name, reps in self._metrics.items()
            }

    # ------------------------------------------------------------------
    # Reconciliation
    # ------------------------------------------------------------------
    def _reconcile_loop(self):
        while not self._shutdown:
            try:
                self._health_check_replicas()
            except Exception:
                logger.exception("replica health checks failed")
            try:
                self._sweep_stale_births()
            except Exception:
                logger.exception("stale-birth sweep failed")
            try:
                self._reconcile_once()
            except Exception:
                logger.exception("reconcile failed")
            time.sleep(0.5)

    def _health_check_replicas(self):
        """Periodically health-check RUNNING replicas and retire dead ones
        (reference: deployment_state.py check_health loop — start-up checks
        alone leave a crashed replica in the routing table forever; the
        reconcile pass then replaces the removed replica).

        Liveness signal #1 is the replica's own metrics PUSH recency: the
        push thread runs OUTSIDE the request pool, so a saturated-but-
        healthy replica (every slot busy with long requests) still proves
        it is alive without an actor call that would queue behind those
        requests and time out. The check_health actor call is the fallback
        for replicas with no recent push."""
        now = time.time()
        with self._lock:
            due = []
            for name, reps in self._replicas.items():
                info = self._deployments.get(name)
                if info is None:
                    continue
                period = info.config.health_check_period_s
                for r in reps:
                    if now - self._health_marks.get(r.replica_id, 0.0) < period:
                        continue
                    self._health_marks[r.replica_id] = now
                    push_ts = self._metrics.get(name, {}).get(r.replica_id, (0, 0.0))[1]
                    if now - push_ts < 5.0:
                        continue  # fresh push == alive
                    due.append((name, r, info.config.health_check_timeout_s))
            # DRAINING replicas left the routing table but still hold a
            # process + in-flight streams: keep health-checking them so a
            # replica that dies/wedges mid-drain is retired immediately
            # instead of riding out the whole drain_timeout_s.
            for rid, rec in list(self._draining.items()):
                info = self._deployments.get(rec["name"])
                period = info.config.health_check_period_s if info else 10.0
                if now - self._health_marks.get(rid, 0.0) < period:
                    continue
                self._health_marks[rid] = now
                push_ts = (
                    self._metrics.get(rec["name"], {}).get(rid, (0, 0.0))[1]
                )
                if now - push_ts < 5.0:
                    continue
                due.append((
                    rec["name"], rec["rinfo"],
                    info.config.health_check_timeout_s if info else 30.0,
                ))
        # Fan out ALL probes, then collect under one shared deadline: a node
        # death with N replicas must cost one timeout, not N.
        refs = []
        max_timeout = 0.0
        for name, r, timeout_s in due:
            handle = self._replica_handles.get(r.replica_id)
            if handle is None:
                with self._lock:
                    rec = self._draining.get(r.replica_id)
                handle = rec.get("handle") if rec else None
            max_timeout = max(max_timeout, timeout_s)
            if handle is None:
                refs.append((name, r, None))
                continue
            try:
                refs.append((name, r, handle.check_health.remote()))
            except Exception:
                refs.append((name, r, None))
        deadline = time.time() + max_timeout
        for name, r, ref in refs:
            ok = False
            try:
                remaining = max(0.1, deadline - time.time())
                ok = ref is not None and bool(ray_tpu.get(ref, timeout=remaining))
            except Exception:
                ok = False
            if not ok:
                self._retire_unhealthy_replica(name, r)

    def _retire_unhealthy_replica(self, name: str, r):
        with self._lock:
            reps = self._replicas.get(name, [])
            present = r in reps
            if present:
                reps.remove(r)
                self._bump_epoch_locked()
            tracked = self._replica_tracked.pop(r.replica_id, None)
            handle = self._replica_handles.pop(r.replica_id, None)
            self._health_marks.pop(r.replica_id, None)
            self._metrics.get(name, {}).pop(r.replica_id, None)
            # Health failure OUTRANKS an in-progress drain: a dead/wedged
            # replica drains nothing, so claim the drain record (its thread
            # yields once the record is gone) and kill NOW.
            draining = self._draining.pop(r.replica_id, None)
        if draining is not None:
            tracked = tracked or draining.get("tracked")
            handle = handle or draining.get("handle")
        elif not present:
            return  # raced a deliberate stop (downscale/rollout) — no-op
        logger.warning(
            "replica %s of %s failed its health check; removing and killing%s",
            r.replica_id, name,
            " (drain in progress, retired immediately)" if draining else "",
        )
        # Kill the actor too: a hung replica left alive would hold its CPU
        # reservation and starve the replacement on a full cluster.
        if tracked is not None:
            with self._mgr_lock:
                self._mgr.remove_actor(tracked)
        elif handle is not None:
            try:
                ray_tpu.kill(handle)
            except Exception:
                pass

    def _target_replicas(self, info: DeploymentInfo, mutate: bool = True) -> int:
        """Desired replica count. Only the reconcile loop may pass
        mutate=True — the delay-mark bookkeeping must not be perturbed by
        read-only callers like serve.status()."""
        auto = info.config.autoscaling
        if auto is None:
            return info.config.num_replicas
        with self._lock:
            metrics = self._metrics.get(info.name, {})
            live = {r.replica_id for r in self._replicas.get(info.name, [])}
            now = time.time()
            vals = [m[0] for rid, m in metrics.items() if rid in live and now - m[1] < 5.0]
        total_ongoing = sum(vals) if vals else 0
        # reference: autoscaling_policy.py:9 calculate_desired_num_replicas
        desired = int(-(-total_ongoing // max(auto.target_num_ongoing_requests_per_replica, 1e-9)))
        desired = max(auto.min_replicas, min(auto.max_replicas, max(desired, 0) or auto.min_replicas))
        key = info.name
        prev = len(self._replicas.get(key, []))
        if not mutate:
            return desired
        if desired > prev:
            mark = self._scale_marks.get(key + ":up")
            if mark is None:
                self._scale_marks[key + ":up"] = now
                return prev
            if now - mark < auto.upscale_delay_s:
                return prev
            self._scale_marks.pop(key + ":up", None)
            return desired
        if desired < prev:
            mark = self._scale_marks.get(key + ":down")
            if mark is None:
                self._scale_marks[key + ":down"] = now
                return prev
            if now - mark < auto.downscale_delay_s:
                return prev
            self._scale_marks.pop(key + ":down", None)
            return desired
        self._scale_marks.pop(key + ":up", None)
        self._scale_marks.pop(key + ":down", None)
        return desired

    def _reconcile_once(self):
        with self._lock:
            targets = dict(self._deployments)
        changed = False
        # Remove replicas of deleted deployments. Stale-version replicas are
        # NOT torn down here — the rolling update below retires them only as
        # new-version replicas pass health checks (reference: versioned
        # rolling updates in deployment_state.py / version.py).
        with self._lock:
            current = {k: list(v) for k, v in self._replicas.items()}
        for name, reps in current.items():
            if name not in targets:
                for r in reps:
                    self._stop_replica(name, r)
                    changed = True
        # Scale each deployment to target (STARTING replicas count toward the
        # target so reconcile doesn't over-start while actors boot).
        for name, info in targets.items():
            version = info.config.version
            with self._lock:
                reps = list(self._replicas.get(name, []))
                starting = len(self._starting_births.get(name, {}))
            new_reps = [r for r in reps if r.version == version]
            old_reps = [r for r in reps if r.version != version]
            target = self._target_replicas(info)
            if len(new_reps) + starting < target:
                for _ in range(target - len(new_reps) - starting):
                    self._start_replica(info)
            elif len(new_reps) > target:
                for r in new_reps[target:]:
                    self._stop_replica(name, r)
                changed = True
            # Retire one old replica per healthy new one; drain the rest once
            # the new version fully covers the target.
            retire = len(old_reps) if len(new_reps) >= target else min(
                len(old_reps), max(0, len(new_reps) + len(old_reps) - target)
            )
            forced = False
            if retire == 0 and old_reps and starting > 0:
                # Rolling update stalled: new-version replicas CANNOT PLACE
                # (tracked actors still PENDING = waiting for resources,
                # typically because the old version holds them all).
                # Force-retire ONE old replica to free resources — and only
                # one outstanding at a time (maxUnavailable=1), so a
                # rollout whose new version keeps crashing cannot drain the
                # whole deployment. A replica that placed and is merely
                # SLOW-STARTING (model load/compile) is NOT a stall: those
                # used to trip this branch and rob old replicas of their
                # drain (ISSUE 14).
                from ray_tpu.air.execution.actor_manager import PENDING

                with self._lock:
                    births = self._starting_births.get(name, {})
                    oldest = min(births.values()) if births else None
                    unplaceable = any(
                        self._replica_tracked.get(rid) is not None
                        and self._replica_tracked[rid].state == PENDING
                        for rid in births
                    )
                    if (
                        oldest is not None
                        and unplaceable
                        and time.time() - oldest > 3.0
                        and self._forced_debt.get(name, 0) == 0
                    ):
                        retire = 1
                        forced = True
                        self._forced_debt[name] = 1
            for r in old_reps[:retire]:
                # Forced stall-breaker retires skip the drain: they exist
                # to free resources for a wedged rollout NOW.
                self._stop_replica(name, r, drain=not forced)
                changed = True
        if changed:
            with self._epoch_cv:
                self._bump_epoch_locked()

    def _start_replica(self, info: DeploymentInfo):
        """Create the replica actor through the AIR ActorManager; it enters
        the routing table only once its first health check answers
        (reference: replica STARTING -> RUNNING transition in
        deployment_state.py). The manager owns process lifecycle + resource
        accounting; version-aware replacement stays controller policy."""
        from ray_tpu.serve._private.common import CONTROLLER_NAME
        from ray_tpu.serve._private.replica import Replica

        replica_id = uuid.uuid4().hex[:8]
        actor_name = f"SERVE_REPLICA::{info.name}#{replica_id}"
        opts = dict(info.config.ray_actor_options or {})
        bundle = {"CPU": opts.pop("num_cpus", 1)}
        ntpu = opts.pop("num_tpus", None)
        if ntpu:
            bundle["TPU"] = ntpu
        bundle.update(opts.pop("resources", None) or {})
        actor_options = dict(opts)
        actor_options["name"] = actor_name
        # Admit concurrent requests up to the routing limit so @serve.batch
        # can actually form batches (reference: replicas are async actors).
        actor_options.setdefault(
            "max_concurrency", min(info.config.max_concurrent_queries, 32)
        )
        rinfo = ReplicaInfo(
            replica_id=replica_id,
            deployment_name=info.name,
            actor_name=actor_name,
            max_concurrent_queries=info.config.max_concurrent_queries,
            version=info.config.version,
        )

        def _on_start(tracked):
            # ALIVE at the GCS: run the readiness probe as a manager task so
            # its result/error flows back through the pump thread.
            self._mgr.schedule_actor_task(
                tracked,
                "check_health",
                on_result=lambda ok: self._replica_ready(rinfo, tracked, bool(ok)),
                on_error=lambda e: self._replica_ready(rinfo, tracked, False),
            )

        def _on_failure(tracked, error, will_restart):
            self._replica_failed(rinfo, error)

        with self._mgr_lock:
            tracked = self._mgr.add_actor(
                Replica,
                {
                    "import_spec": info.import_spec,
                    "user_config": info.config.user_config,
                    "deployment_name": info.name,
                    "replica_id": replica_id,
                    "controller_name": CONTROLLER_NAME,
                },
                resource_request=ResourceRequest([bundle]),
                actor_options=actor_options,
                on_start=_on_start,
                on_failure=_on_failure,
            )
        with self._lock:
            self._starting_births.setdefault(info.name, {})[replica_id] = time.time()
            self._replica_tracked[replica_id] = tracked

    def _replica_ready(self, rinfo: ReplicaInfo, tracked, ok: bool):
        """Readiness probe answered (ActorManager pump thread, _mgr_lock
        held): healthy replicas enter the routing table, anything else is
        removed through the manager."""
        name = rinfo.deployment_name
        with self._lock:
            self._starting_births.get(name, {}).pop(rinfo.replica_id, None)
            if ok:
                self._forced_debt.pop(name, None)
            admitted = ok and name in self._deployments
            if admitted:
                self._replicas.setdefault(name, []).append(rinfo)
                self._replica_handles[rinfo.replica_id] = tracked.actor_handle
            else:
                self._replica_tracked.pop(rinfo.replica_id, None)
                self._replica_handles.pop(rinfo.replica_id, None)
        if admitted:
            with self._epoch_cv:
                self._bump_epoch_locked()
            logger.info("replica %s of %s is running", rinfo.replica_id, name)
        else:
            if not ok:
                logger.warning("replica %s of %s failed to start", rinfo.replica_id, name)
            self._mgr.remove_actor(tracked)  # reentrant under _mgr_lock

    def _replica_failed(self, rinfo: ReplicaInfo, error: BaseException):
        """Replica process died (ActorManager on_failure): drop it from the
        routing table; the reconcile pass starts a target-version
        replacement."""
        name = rinfo.deployment_name
        with self._lock:
            reps = self._replicas.get(name, [])
            present = rinfo in reps
            if present:
                reps.remove(rinfo)
            self._starting_births.get(name, {}).pop(rinfo.replica_id, None)
            self._replica_tracked.pop(rinfo.replica_id, None)
            self._replica_handles.pop(rinfo.replica_id, None)
            self._health_marks.pop(rinfo.replica_id, None)
            self._metrics.get(name, {}).pop(rinfo.replica_id, None)
            # Died while draining: the manager already reaped the process;
            # clearing the record makes the drainer thread exit quietly.
            self._draining.pop(rinfo.replica_id, None)
        if present:
            logger.warning(
                "replica %s of %s died (%s); removing from routing table",
                rinfo.replica_id, name, error,
            )
            with self._epoch_cv:
                self._bump_epoch_locked()

    def _sweep_stale_births(self):
        """Abort STARTING replicas whose readiness never answered within the
        health-check timeout (hung __init__ / lost probe): the pre-manager
        controller bounded startup with a get(timeout=) — the manager probe
        has no deadline of its own, so the sweep enforces one."""
        stale = []
        now = time.time()
        with self._lock:
            for name, births in self._starting_births.items():
                info = self._deployments.get(name)
                limit = max(
                    30.0,
                    info.config.health_check_timeout_s * 3 if info is not None else 30.0,
                )
                for rid, born in list(births.items()):
                    if now - born > limit:
                        births.pop(rid, None)
                        stale.append((name, rid, self._replica_tracked.pop(rid, None)))
        for name, rid, tracked in stale:
            logger.warning("replica %s of %s never became ready; aborting", rid, name)
            if tracked is not None:
                with self._mgr_lock:
                    self._mgr.remove_actor(tracked)

    def _stop_replica(self, name: str, rinfo: ReplicaInfo, drain: bool = True):
        """Deliberate retirement (downscale / rolling update / delete).

        With ``drain`` (and a positive ``drain_timeout_s``): the replica
        leaves the routing table NOW (routers stop assigning on the next
        epoch), is told to refuse new requests, and a drainer thread
        retires the process only once its in-flight requests and stream
        pumps hit zero — or the bound expires. The stall-breaker's forced
        retire passes ``drain=False``: it exists to free resources for a
        stuck rollout, and waiting on a drain would re-create the stall."""
        with self._lock:
            if rinfo.replica_id in self._draining:
                return  # a drainer already owns this replica
            reps = self._replicas.get(name, [])
            if rinfo in reps:
                reps.remove(rinfo)
            tracked = self._replica_tracked.pop(rinfo.replica_id, None)
            handle = self._replica_handles.pop(rinfo.replica_id, None)
            # Prune per-replica bookkeeping: under autoscaling churn these
            # maps would otherwise grow one entry per retired replica forever.
            self._health_marks.pop(rinfo.replica_id, None)
            self._metrics.get(name, {}).pop(rinfo.replica_id, None)
            info = self._deployments.get(name)
            # Deleted deployments still drain their live streams (the
            # config is gone with the deployment; use the default bound).
            timeout_s = (
                info.config.drain_timeout_s
                if info is not None
                else DeploymentConfig.drain_timeout_s
            )
            start_drain = (
                drain
                and timeout_s > 0
                and handle is not None
                and not self._shutdown
            )
            if start_drain:
                self._draining[rinfo.replica_id] = {
                    "name": name,
                    "rinfo": rinfo,
                    "tracked": tracked,
                    "handle": handle,
                }
        if start_drain:
            threading.Thread(
                target=self._drain_then_retire,
                args=(name, rinfo, tracked, handle, timeout_s),
                name=f"serve-drain-{rinfo.replica_id}",
                daemon=True,
            ).start()
            return
        self._retire_replica_process(name, rinfo, tracked, handle)

    def _drain_then_retire(self, name, rinfo, tracked, handle, timeout_s):
        """Drainer thread for ONE deliberately-stopped replica. Yields to
        the health-check path: if that retires the replica mid-drain (dead
        replicas drain nothing), the drain record vanishes and this thread
        simply exits."""
        from ray_tpu._private import flight_recorder

        rid = rinfo.replica_id
        flight_recorder.record("replica_drain", f"{rid}:begin")
        outcome = "clean"
        try:
            ray_tpu.get(handle.drain.remote(), timeout=10)
        except Exception:
            # The replica may still be fine (a loaded box can blow a 10s
            # bound); the routing-table removal already stops new assigns,
            # so keep polling — the status loop decides liveness.
            pass
        deadline = time.monotonic() + timeout_s
        fails = 0
        while not self._shutdown:
            with self._lock:
                if self._draining.get(rid) is None:
                    return  # force-retired by a health-check failure
            if time.monotonic() > deadline:
                outcome = "timeout"
                break
            try:
                st = ray_tpu.get(handle.drain_status.remote(), timeout=10)
            except Exception:
                # Transient (slow box) vs dead: three consecutive misses
                # within the drain window reads as dead — a single blown
                # bound must not retire a replica with live streams.
                fails += 1
                if fails >= 3:
                    outcome = "died_draining"
                    break
            else:
                fails = 0
                if st.get("ongoing", 0) == 0 and st.get("streams", 0) == 0:
                    break
            time.sleep(0.25)
        with self._lock:
            if self._draining.pop(rid, None) is None:
                return  # raced the force-retire path; it owns the kill
        flight_recorder.record("replica_drain", f"{rid}:{outcome}")
        try:
            self_metrics.instruments()["serve_drains"].inc(tags={"outcome": outcome})
        except Exception:
            pass
        self._retire_replica_process(name, rinfo, tracked, handle)

    def _retire_replica_process(self, name, rinfo, tracked, handle):
        if handle is not None:
            try:
                # Graceful shutdown hook: let the user callable release
                # resources before the actor process is killed.
                ray_tpu.get(
                    handle.prepare_for_shutdown.remote(),
                    timeout=min(5.0, self._deployments[name].config.graceful_shutdown_timeout_s)
                    if name in self._deployments
                    else 5.0,
                )
            except Exception:
                pass
        if tracked is not None:
            with self._mgr_lock:
                try:
                    self._mgr.remove_actor(tracked)  # kills + releases resources
                except Exception:
                    pass  # already removed (died mid-drain; on_failure ran)
        elif handle is not None:
            try:
                ray_tpu.kill(handle)
            except Exception:
                pass
        # A draining replica kept pushing queue metrics after the stop-time
        # prune (its push thread stops only in prepare_for_shutdown above);
        # prune AFTER the process is gone so retired replicas don't accrete
        # map entries.
        with self._lock:
            self._health_marks.pop(rinfo.replica_id, None)
            self._metrics.get(name, {}).pop(rinfo.replica_id, None)
        logger.info("stopped replica %s of %s", rinfo.replica_id, name)
