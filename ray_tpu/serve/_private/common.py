"""Shared Serve types (reference: python/ray/serve/_private/common.py)."""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional


@dataclasses.dataclass
class DeploymentConfig:
    """Per-deployment config (reference: serve/config.py DeploymentConfig +
    autoscaling_policy.py AutoscalingConfig)."""

    num_replicas: int = 1
    max_concurrent_queries: int = 100
    user_config: Any = None
    ray_actor_options: dict = dataclasses.field(default_factory=dict)
    health_check_period_s: float = 10.0
    health_check_timeout_s: float = 30.0
    graceful_shutdown_timeout_s: float = 20.0
    # Drain-before-retire bound for DELIBERATE stops (downscale, rolling
    # update, deployment delete): the replica leaves the routing table,
    # refuses new requests, and gets up to this long for in-flight
    # requests/streams to finish before the process is retired. 0 disables
    # draining (immediate retire, the pre-drain behavior). Health-check
    # failures always retire immediately — a dead replica drains nothing.
    drain_timeout_s: float = 30.0
    autoscaling: Optional["AutoscalingConfig"] = None
    # None = autogenerate from code + init args + user_config at deploy time
    # (reference: unversioned deployments get a new version on every deploy,
    # serve/_private/version.py DeploymentVersion).
    version: Optional[str] = None


@dataclasses.dataclass
class AutoscalingConfig:
    """Queue-depth-driven autoscaling (reference:
    serve/_private/autoscaling_policy.py:9 calculate_desired_num_replicas)."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_num_ongoing_requests_per_replica: float = 2.0
    upscale_delay_s: float = 3.0
    downscale_delay_s: float = 30.0


@dataclasses.dataclass
class ReplicaInfo:
    replica_id: str
    deployment_name: str
    actor_name: str
    max_concurrent_queries: int
    version: str


@dataclasses.dataclass
class DeploymentInfo:
    name: str
    app_name: str
    import_spec: bytes  # pickled (cls_or_fn, init_args, init_kwargs)
    config: DeploymentConfig
    route_prefix: Optional[str] = None


CONTROLLER_NAME = "SERVE_CONTROLLER"
PROXY_NAME = "SERVE_PROXY"

# HTTP header / handle option carrying the multiplexed model id
# (reference: serve/_private/constants.py SERVE_MULTIPLEXED_MODEL_ID).
MULTIPLEXED_MODEL_ID_HEADER = "serve_multiplexed_model_id"

# HTTP header / handle option carrying the prefix-cache routing hint
# (serve.llm.prefix_route_hint): requests sharing a system prompt carry the
# same value and the router pins them to the replica holding those KV
# blocks, falling back to least queue depth.
PREFIX_HINT_HEADER = "serve_prefix_hash"

# Naming convention pairing disaggregated LLM pools (ISSUE 20): the proxy
# discovers the prefill pool as f"{decode_deployment}{PREFILL_SUFFIX}" in
# its routing table. Lives here (not serve.llm.deployment, which re-exports
# it) so the proxy path never imports the model stack.
PREFILL_SUFFIX = "--prefill"


class HandleMarker:
    """Placeholder for a DeploymentHandle inside pickled init args —
    deployment composition (reference: deployment graphs / DeploymentNode
    bound as an argument). Replicas materialize it at construction."""

    def __init__(self, deployment_name: str):
        self.deployment_name = deployment_name

    def __repr__(self):
        return f"HandleMarker({self.deployment_name!r})"
