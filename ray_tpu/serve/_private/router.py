"""Router — picks a replica for each request.

Reference: python/ray/serve/_private/router.py:368 Router,
ReplicaScheduler.assign_replica :76, round-robin skipping replicas at
max_concurrent_queries :125,336; membership pushed from the controller via
long-poll (long_poll.py:68 LongPollClient).
"""

from __future__ import annotations

import logging
import threading
import time

import ray_tpu
from ray_tpu._private import self_metrics

logger = logging.getLogger(__name__)


class Router:
    """One per handle/proxy process; tracks the routing table with a
    background long-poll thread and round-robins requests."""

    _instance = None
    _instance_lock = threading.Lock()

    def __init__(self, controller_handle):
        self._controller = controller_handle
        self._table: dict = {}
        self._epoch = -1
        self._handles: dict[str, object] = {}  # actor_name -> handle
        self._rr: dict[str, int] = {}
        self._inflight: dict[str, int] = {}  # replica actor_name -> count
        self._metrics = self_metrics.instruments()
        self._lock = threading.Lock()
        self._update_event = threading.Event()
        self._poll_thread = threading.Thread(target=self._poll_loop, daemon=True)
        self._poll_thread.start()
        # Synchronous first fetch so handles work immediately after run().
        self._refresh(timeout_s=0.1)

    @classmethod
    def shared(cls, controller_handle) -> "Router":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = Router(controller_handle)
            return cls._instance

    @classmethod
    def reset(cls):
        with cls._instance_lock:
            cls._instance = None

    def _refresh(self, timeout_s: float = 30.0):
        resp = ray_tpu.get(
            self._controller.get_routing_table.remote(self._epoch, timeout_s)
        )
        with self._lock:
            self._epoch = resp["epoch"]
            self._table = resp["table"]
        self._update_event.set()

    def _poll_loop(self):
        while True:
            try:
                self._refresh()
            except Exception:
                time.sleep(1.0)

    def replicas_for(self, deployment: str) -> list:
        with self._lock:
            entry = self._table.get(deployment)
            return list(entry["replicas"]) if entry else []

    def route_for_prefix(self, path: str):
        """Longest-prefix route match for HTTP (reference: proxy route table)."""
        return self.route_and_prefix_for(path)[0]

    def route_and_prefix_for(self, path: str):
        """(deployment, matched route prefix) — the proxy forwards the
        prefix so replicas can resolve request.sub_path without knowing
        their own mount point."""
        with self._lock:
            best, best_prefix, best_len = None, None, -1
            for name, entry in self._table.items():
                prefix = entry.get("route_prefix")
                if prefix is None:
                    continue
                if (path == prefix or path.startswith(prefix.rstrip("/") + "/") or prefix == "/") and len(prefix) > best_len:
                    best, best_prefix, best_len = name, prefix, len(prefix)
            return best, best_prefix

    def wait_for_deployment(self, deployment: str, timeout_s: float = 30.0) -> bool:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if self.replicas_for(deployment):
                return True
            time.sleep(0.05)
        return False

    def assign_replica(self, deployment: str, timeout_s: float = 30.0, model_id: str = ""):
        """Round-robin over replicas, skipping ones at their queue limit
        (reference: router.py:125 RoundRobinReplicaScheduler). A multiplexed
        model id pins to a stable replica (warm model cache on TPU) with
        round-robin fallback when that replica is saturated."""
        deadline = time.time() + timeout_s
        while True:
            replicas = self.replicas_for(deployment)
            if replicas:
                with self._lock:
                    n = len(replicas)
                    if model_id:
                        # Stable affinity: same model id -> same replica.
                        import zlib

                        start = zlib.crc32(model_id.encode()) % n
                    else:
                        start = self._rr.get(deployment, 0)
                    for i in range(n):
                        r = replicas[(start + i) % n]
                        name = r["actor_name"]
                        if self._inflight.get(name, 0) < r["max_concurrent_queries"]:
                            if not model_id:
                                self._rr[deployment] = (start + i + 1) % n
                            self._inflight[name] = self._inflight.get(name, 0) + 1
                            try:
                                self._metrics["serve_requests"].inc(
                                    tags={"deployment": deployment}
                                )
                                self._set_queue_depth_locked(deployment)
                            except Exception:
                                pass
                            return r
            if time.time() >= deadline:
                raise TimeoutError(
                    f"no available replica for deployment {deployment!r} "
                    f"within {timeout_s}s"
                )
            time.sleep(0.01)

    def _set_queue_depth_locked(self, deployment: str):
        """Refresh the deployment's in-flight gauge (caller holds _lock).
        Updated on BOTH assign and release — a gauge only set on assign
        would report the peak depth forever once traffic stops."""
        entry = self._table.get(deployment)
        if entry is None:
            return
        self._metrics["serve_queue_depth"].set(
            sum(self._inflight.get(r["actor_name"], 0) for r in entry["replicas"]),
            tags={"deployment": deployment},
        )

    def release(self, replica, deployment: str | None = None, duration_s: float | None = None):
        with self._lock:
            name = replica["actor_name"]
            self._inflight[name] = max(0, self._inflight.get(name, 0) - 1)
            if deployment is not None:
                try:
                    self._set_queue_depth_locked(deployment)
                except Exception:
                    pass
        if deployment is not None and duration_s is not None:
            try:
                self._metrics["serve_latency"].observe(
                    duration_s, tags={"deployment": deployment}
                )
            except Exception:
                pass

    def handle_for(self, replica) -> object:
        name = replica["actor_name"]
        handle = self._handles.get(name)
        if handle is None:
            handle = ray_tpu.get_actor(name)
            self._handles[name] = handle
        return handle

    def invalidate_handle(self, replica):
        self._handles.pop(replica["actor_name"], None)
