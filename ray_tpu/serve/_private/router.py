"""Router — picks a replica for each request.

Reference: python/ray/serve/_private/router.py:368 Router,
ReplicaScheduler.assign_replica :76, round-robin skipping replicas at
max_concurrent_queries :125,336; membership pushed from the controller via
long-poll (long_poll.py:68 LongPollClient).
"""

from __future__ import annotations

import logging
import threading
import time

import ray_tpu
from ray_tpu._private import self_metrics

logger = logging.getLogger(__name__)


class Router:
    """One per handle/proxy process; tracks the routing table with a
    background long-poll thread and round-robins requests."""

    _instance = None
    _instance_lock = threading.Lock()

    def __init__(self, controller_handle):
        self._controller = controller_handle
        self._table: dict = {}
        self._epoch = -1
        self._handles: dict[str, object] = {}  # actor_name -> handle
        self._rr: dict[str, int] = {}
        self._inflight: dict[str, int] = {}  # replica actor_name -> count
        self._alive_cache: dict[str, float] = {}  # actor_name -> verdict stamp
        # Replicas KNOWN to be draining (refused a request with the typed
        # ReplicaDrainingError), actor_name -> expiry stamp. The controller
        # eventually removes them from the table; until that table version
        # lands, every assignment policy — round-robin, prefix-affinity pin,
        # least-queue-depth spill, handoff targeting — must skip them, or a
        # request burns one of its bounded reassign retries on a replica
        # that is guaranteed to refuse it. TTL-bounded so a replica that
        # aborts its drain (or a name reused by a new replica) recovers.
        self._draining: dict[str, float] = {}
        self._metrics = self_metrics.instruments()
        self._lock = threading.Lock()
        # Saturated assigns park on this condition (same underlying lock);
        # release() and table refreshes notify — no busy polling.
        self._avail = threading.Condition(self._lock)
        self._update_event = threading.Event()
        # controller_handle=None is the unit-test seam: a bare router with a
        # hand-fed table and no background poller.
        if controller_handle is not None:
            self._poll_thread = threading.Thread(target=self._poll_loop, daemon=True)
            self._poll_thread.start()
            # Synchronous first fetch so handles work immediately after run().
            self._refresh(timeout_s=0.1)

    @classmethod
    def shared(cls, controller_handle) -> "Router":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = Router(controller_handle)
            return cls._instance

    @classmethod
    def reset(cls):
        with cls._instance_lock:
            cls._instance = None

    def _refresh(self, timeout_s: float = 30.0):
        resp = ray_tpu.get(
            self._controller.get_routing_table.remote(self._epoch, timeout_s)
        )
        with self._lock:
            self._epoch = resp["epoch"]
            self._table = resp["table"]
            # New/scaled deployments can unblock saturated assigns.
            self._avail.notify_all()
        self._update_event.set()

    def _poll_loop(self):
        while True:
            try:
                self._refresh()
            except Exception:
                time.sleep(1.0)

    def replicas_for(self, deployment: str) -> list:
        with self._lock:
            entry = self._table.get(deployment)
            return list(entry["replicas"]) if entry else []

    def route_for_prefix(self, path: str):
        """Longest-prefix route match for HTTP (reference: proxy route table)."""
        return self.route_and_prefix_for(path)[0]

    def route_and_prefix_for(self, path: str):
        """(deployment, matched route prefix) — the proxy forwards the
        prefix so replicas can resolve request.sub_path without knowing
        their own mount point."""
        with self._lock:
            best, best_prefix, best_len = None, None, -1
            for name, entry in self._table.items():
                prefix = entry.get("route_prefix")
                if prefix is None:
                    continue
                if (path == prefix or path.startswith(prefix.rstrip("/") + "/") or prefix == "/") and len(prefix) > best_len:
                    best, best_prefix, best_len = name, prefix, len(prefix)
            return best, best_prefix

    def wait_for_deployment(self, deployment: str, timeout_s: float = 30.0) -> bool:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if self.replicas_for(deployment):
                return True
            time.sleep(0.05)
        return False

    def assign_replica(
        self,
        deployment: str,
        timeout_s: float = 30.0,
        model_id: str = "",
        prefix_hint: str = "",
        exclude=(),
    ):
        """Pick a replica and claim a queue slot on it.

        Policy (reference: router.py:125 RoundRobinReplicaScheduler, plus
        the cache-aware layer for serve.llm):

        - ``model_id`` pins to a stable replica (warm multiplexed model
          cache) with round-robin fallback when it is saturated;
        - ``prefix_hint`` (hash of a request's leading prompt block —
          ``serve.llm.prefix_route_hint``) pins to a stable replica so
          requests sharing a system prompt land where its KV prefix-cache
          blocks already live, falling back to the LEAST-QUEUE-DEPTH
          unsaturated replica (a cache miss should at least balance load);
        - otherwise round-robin, skipping replicas at max_concurrent_queries.

        When every replica is saturated the caller parks on a Condition that
        ``release()`` (and table refreshes) notify — a freed slot hands off
        in microseconds, not a 10 ms poll; ``timeout_s`` still bounds the
        total wait.

        ``exclude``: actor names to never pick — the reassign/migration
        callers pass the replica they just watched die, since it can linger
        in the table until the controller notices the death.
        """
        deadline = time.time() + timeout_s
        exclude = set(exclude)
        with self._avail:
            while True:
                entry = self._table.get(deployment)
                replicas = list(entry["replicas"]) if entry else []
                if self._draining:
                    self._prune_draining_locked()
                if self._draining:
                    replicas = [
                        r
                        for r in replicas
                        if r["actor_name"] not in self._draining
                    ]
                if exclude:
                    replicas = [r for r in replicas if r["actor_name"] not in exclude]
                if replicas:
                    r = self._pick_locked(deployment, replicas, model_id, prefix_hint)
                    if r is not None:
                        name = r["actor_name"]
                        self._inflight[name] = self._inflight.get(name, 0) + 1
                        try:
                            self._metrics["serve_requests"].inc(
                                tags={"deployment": deployment}
                            )
                            self._set_queue_depth_locked(deployment)
                        except Exception:
                            pass
                        return r
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise TimeoutError(
                        f"no available replica for deployment {deployment!r} "
                        f"within {timeout_s}s"
                    )
                # The 1 s cap is a backstop for changes nobody notifies
                # about (e.g. a replica's limit raised by a new table
                # version swallowed between checks).
                self._avail.wait(timeout=min(remaining, 1.0))

    def _pick_locked(self, deployment, replicas, model_id, prefix_hint):
        """Choose an unsaturated replica (caller holds _lock); None if all
        are at their queue limit."""
        import zlib

        n = len(replicas)

        def free(r):
            return self._inflight.get(r["actor_name"], 0) < r["max_concurrent_queries"]

        if model_id:
            # Stable affinity: same model id -> same replica; round-robin
            # scan from there when saturated (existing behavior).
            start = zlib.crc32(model_id.encode()) % n
            for i in range(n):
                r = replicas[(start + i) % n]
                if free(r):
                    return r
            return None
        if prefix_hint:
            # Cache-aware: the replica holding the shared prefix blocks,
            # else spill to the least-loaded unsaturated replica.
            r = replicas[zlib.crc32(prefix_hint.encode()) % n]
            if free(r):
                return r
            candidates = [x for x in replicas if free(x)]
            if not candidates:
                return None
            return min(
                candidates, key=lambda x: self._inflight.get(x["actor_name"], 0)
            )
        start = self._rr.get(deployment, 0)
        for i in range(n):
            r = replicas[(start + i) % n]
            if free(r):
                self._rr[deployment] = (start + i + 1) % n
                return r
        return None

    def _set_queue_depth_locked(self, deployment: str):
        """Refresh the deployment's in-flight gauge (caller holds _lock).
        Updated on BOTH assign and release — a gauge only set on assign
        would report the peak depth forever once traffic stops."""
        entry = self._table.get(deployment)
        if entry is None:
            return
        self._metrics["serve_queue_depth"].set(
            sum(self._inflight.get(r["actor_name"], 0) for r in entry["replicas"]),
            tags={"deployment": deployment},
        )

    def release(self, replica, deployment: str | None = None, duration_s: float | None = None):
        with self._lock:
            name = replica["actor_name"]
            self._inflight[name] = max(0, self._inflight.get(name, 0) - 1)
            self._avail.notify_all()  # wake assigns parked on saturation
            if deployment is not None:
                try:
                    self._set_queue_depth_locked(deployment)
                except Exception:
                    pass
        if deployment is not None and duration_s is not None:
            try:
                self._metrics["serve_latency"].observe(
                    duration_s, tags={"deployment": deployment}
                )
            except Exception:
                pass

    # How long a drain verdict sticks without confirmation. Long enough to
    # outlive the controller's table update (which removes the replica for
    # real), short enough that a reused actor name or an aborted drain
    # re-enters rotation on its own.
    _DRAINING_TTL_S = 60.0

    def mark_draining(self, replica_or_name, ttl_s: float | None = None):
        """A caller saw this replica refuse a request with the typed
        ReplicaDrainingError: take it out of every assignment policy until
        the routing table catches up (or the TTL expires)."""
        name = (
            replica_or_name["actor_name"]
            if isinstance(replica_or_name, dict)
            else replica_or_name
        )
        with self._lock:
            self._draining[name] = time.monotonic() + (
                self._DRAINING_TTL_S if ttl_s is None else ttl_s
            )

    def is_draining(self, replica_or_name) -> bool:
        name = (
            replica_or_name["actor_name"]
            if isinstance(replica_or_name, dict)
            else replica_or_name
        )
        with self._lock:
            self._prune_draining_locked()
            return name in self._draining

    def _prune_draining_locked(self):
        now = time.monotonic()
        for name, expiry in list(self._draining.items()):
            if expiry <= now:
                del self._draining[name]

    # Positive liveness verdicts are cached briefly so the per-call probe
    # costs ~one GCS RPC per replica per window, not one per request —
    # the race window the probe closes narrows from forever to the TTL.
    _ALIVE_TTL_S = 2.0

    def replica_alive(self, replica) -> bool:
        """Bounded GCS probe (TTL-cached when positive): is the replica's
        actor still registered and not DEAD? Closes the assign->dead-replica
        race for handle calls — a replica that died after assignment but
        before accepting is detectable here, and the caller reassigns
        instead of handing its caller a doomed ref. Unknown (GCS
        unreachable) reads as alive: the probe must never turn a healthy
        call into a failure."""
        from ray_tpu._private.worker_context import get_core_worker

        name = replica["actor_name"]
        now = time.monotonic()
        with self._lock:
            stamp = self._alive_cache.get(name)
            if stamp is not None and now - stamp < self._ALIVE_TTL_S:
                return True
        try:
            cw = get_core_worker()
            resp = cw.gcs.call(
                "get_actor",
                {"name": name, "namespace": cw.namespace},
                timeout=2,
            )
        except Exception:
            return True
        alive = resp.get("found", False) and resp["info"].get("state") != "DEAD"
        with self._lock:
            if alive:
                self._alive_cache[name] = now
            else:
                self._alive_cache.pop(name, None)
        return alive

    def handle_for(self, replica) -> object:
        name = replica["actor_name"]
        handle = self._handles.get(name)
        if handle is None:
            handle = ray_tpu.get_actor(name)
            self._handles[name] = handle
        return handle

    def invalidate_handle(self, replica):
        self._handles.pop(replica["actor_name"], None)
        with self._lock:
            self._alive_cache.pop(replica["actor_name"], None)
