"""ASGI boundary for Serve ingress.

The reference's proxy IS an ASGI application served by uvicorn
(python/ray/serve/_private/http_proxy.py:320 `HTTPProxy.__call__(scope,
receive, send)`), and replicas mount user ASGI apps (FastAPI) via
`serve.ingress` (python/ray/serve/api.py:100). This module gives ray_tpu the
same seam with the servers available in this image:

- `ProxyASGIApp` — the ingress routing logic as a pure ASGI-3 callable. No
  aiohttp types anywhere in it; it speaks only scope/receive/send.
- `AiohttpASGIServer` — adapter that serves ANY ASGI-3 app on aiohttp (the
  only HTTP server in the image). Swapping servers (e.g. to uvicorn) means
  replacing this one class; the app and everything behind it are untouched.
- `run_asgi_request` — replica-side bridge: drives a user ASGI app from the
  `HTTPRequest` a replica receives, so `@serve.ingress(asgi_app)` mounts raw
  ASGI apps (what the reference does with FastAPI) on deployments.

Responses flow back as either a buffered envelope dict
(`{"__serve_http_response__": True, status, headers, body}`) or a
`StreamingResponse` whose chunks ride the replica's stream pump — both of
which `ProxyASGIApp` translates back into ASGI send events.
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
import time
from urllib.parse import parse_qsl, urlencode

from ray_tpu._private.concurrency import any_thread, blocking

logger = logging.getLogger(__name__)

_DISCONNECT = {"type": "http.disconnect"}


class ClientDisconnected(Exception):
    """Raised from ``send`` inside a user ASGI app once the client is gone —
    the ASGI-standard way a server stops a producer (uvicorn raises on send
    after disconnect); the app unwinds through its own finally blocks."""


def _build_scope(method, path, root_path, query_string: bytes, headers, client=None, server=None):
    """One scope-dict construction for both bridges (adapter + replica)."""
    return {
        "type": "http",
        "asgi": {"version": "3.0", "spec_version": "2.3"},
        "http_version": "1.1",
        "method": method,
        "scheme": "http",
        "path": path,
        # utf-8, not latin-1: `path` arrives percent-DECODED (aiohttp's
        # request.path / the replica sub_path) and may contain any unicode;
        # headers stay latin-1 per the HTTP wire format.
        "raw_path": path.encode("utf-8"),
        "root_path": root_path,
        "query_string": query_string,
        "headers": headers,
        "client": client,
        "server": server,
    }


async def _read_body(receive) -> bytes:
    """Drain `http.request` events into one body (ASGI allows chunking)."""
    parts = []
    while True:
        msg = await receive()
        if msg["type"] == "http.request":
            parts.append(msg.get("body", b""))
            if not msg.get("more_body", False):
                break
        else:  # http.disconnect
            break
    return b"".join(parts)


async def _respond_start(send, status: int, content_type: str, extra_headers: dict):
    headers = [(b"content-type", content_type.encode("latin-1"))]
    for k, v in extra_headers.items():
        if k.lower() != "content-type":
            headers.append((k.lower().encode("latin-1"), str(v).encode("latin-1")))
    await send({"type": "http.response.start", "status": status, "headers": headers})


async def _respond(send, status: int, body: bytes, content_type: str, extra_headers: dict | None = None):
    extra = dict(extra_headers or {})
    ctype = next((v for k, v in extra.items() if k.lower() == "content-type"), content_type)
    await _respond_start(send, status, ctype, extra)
    await send({"type": "http.response.body", "body": body, "more_body": False})


def _np_default(o):
    import numpy as np

    if isinstance(o, np.ndarray):
        return o.tolist()
    if isinstance(o, np.generic):
        return o.item()
    raise TypeError(f"not JSON serializable: {type(o)}")


def _replica_went_away(e: BaseException) -> bool:
    """The typed this-replica-is-gone errors that justify a bounded
    reassign/migration: process death (ActorDiedError and its unavailable
    sibling) or deliberate drain (ReplicaDrainingError — possibly wrapped
    in the TaskError envelope a raising remote method rides home in).
    Anything else (app bugs, timeouts) surfaces unchanged."""
    from ray_tpu.exceptions import (
        ActorDiedError,
        ActorUnavailableError,
        ReplicaDrainingError,
        TaskError,
    )

    if isinstance(e, (ActorDiedError, ActorUnavailableError, ReplicaDrainingError)):
        return True
    if isinstance(e, TaskError):
        return isinstance(e.cause, ReplicaDrainingError)
    return False


def _drain_refused(e: BaseException) -> bool:
    """The drain subset of :func:`_replica_went_away`: the replica is alive
    and healthy but REFUSED the request because it is retiring. Unlike a
    death this is a pure routing-table race — the caller marks the replica
    draining on its router (so no policy picks it again) and retries
    WITHOUT burning one of the bounded reassign/migration attempts, which
    exist to cap work wasted on crashes, not on polite refusals."""
    from ray_tpu.exceptions import ReplicaDrainingError, TaskError

    if isinstance(e, ReplicaDrainingError):
        return True
    if isinstance(e, TaskError):
        return isinstance(e.cause, ReplicaDrainingError)
    return False


class _SSETokenParser:
    """Incremental parser over the SSE chunk bytes the proxy forwards:
    collects the ``data: {"token": n}`` payloads the CLIENT has already
    received — exactly the tokens a migrated request must teacher-force
    and never re-emit. Chunk boundaries are arbitrary (the replica pump
    batches), so events are split on the wire-level ``\\n\\n`` frame."""

    def __init__(self):
        self.tokens: list = []
        self._buf = b""

    def feed(self, chunk: bytes):
        self._buf += bytes(chunk)
        while b"\n\n" in self._buf:
            event, self._buf = self._buf.split(b"\n\n", 1)
            if not event.startswith(b"data: "):
                continue
            payload = event[6:]
            if payload == b"[DONE]":
                continue
            try:
                tok = json.loads(payload).get("token")
            except Exception:
                continue
            if tok is not None:
                self.tokens.append(int(tok))


class ProxyASGIApp:
    """Serve's HTTP ingress as an ASGI-3 application.

    Routes by longest prefix through the shared Router, forwards the request
    to a replica (in an executor — replica calls block on the object store),
    and pumps streaming responses chunk-by-chunk. Mirrors the reference's
    `HTTPProxy` ASGI app (http_proxy.py:320) over ray_tpu's replica
    protocol.
    """

    def __init__(self, router, pool):
        self._router = router
        self._pool = pool

    async def __call__(self, scope, receive, send):
        if scope["type"] == "lifespan":
            while True:
                msg = await receive()
                if msg["type"] == "lifespan.startup":
                    await send({"type": "lifespan.startup.complete"})
                elif msg["type"] == "lifespan.shutdown":
                    await send({"type": "lifespan.shutdown.complete"})
                    return
        if scope["type"] != "http":
            return  # websockets not supported
        path = scope.get("path", "/")
        if path == "/-/healthz":
            await _respond(send, 200, b"ok", "text/plain")
            return
        if path == "/-/routes":
            with self._router._lock:
                routes = {
                    name: e.get("route_prefix") for name, e in self._router._table.items()
                }
            await _respond(send, 200, json.dumps(routes).encode(), "application/json")
            return
        deployment, matched_prefix = self._router.route_and_prefix_for(path)
        if deployment is None:
            await _respond(send, 404, f"no deployment for path {path}".encode(), "text/plain")
            return
        body = await _read_body(receive)
        method = scope.get("method", "GET")
        # surrogateescape so arbitrary wire bytes survive the str hop to the
        # replica and re-encode back to the identical bytes for its scope.
        raw_query = scope.get("query_string", b"").decode("utf-8", "surrogateescape")
        query = dict(parse_qsl(raw_query, keep_blank_values=True))
        headers = {
            k.decode("latin-1"): v.decode("latin-1") for k, v in scope.get("headers", [])
        }
        loop = asyncio.get_running_loop()
        import ray_tpu

        def call():
            import time as _time

            from ray_tpu.serve._private.common import (
                MULTIPLEXED_MODEL_ID_HEADER,
                PREFIX_HINT_HEADER,
            )

            model_id = next(
                (v for k, v in headers.items() if k.lower() == MULTIPLEXED_MODEL_ID_HEADER),
                "",
            )
            prefix_hint = next(
                (v for k, v in headers.items() if k.lower() == PREFIX_HINT_HEADER),
                "",
            )
            # Disaggregated LLM (ISSUE 20): a paired "<name>--prefill"
            # deployment in the table means LLM generate requests run their
            # prefill leg on that pool first; the sealed-KV handoff envelope
            # rewrites the body the decode pool (this deployment) receives.
            # Any prefill-leg failure returns None and the decode pool
            # simply recomputes the prefill — never a client-visible error.
            req_body = body
            from ray_tpu.serve._private.common import PREFILL_SUFFIX

            prefill_dep = deployment + PREFILL_SUFFIX
            if method == "POST" and self._router.replicas_for(prefill_dep):
                req_body = (
                    self._prefill_handoff(
                        prefill_dep, body, headers, model_id, prefix_hint,
                        path, query, matched_prefix, raw_query,
                    )
                    or body
                )
            # ONE bounded reassign on the typed went-away errors: a replica
            # that died after assignment (assign->dead race) must not 500
            # the client while healthy replicas exist. Drain refusals
            # (deliberate retirement; the routing-table removal races this
            # request by design) retry WITHOUT consuming that bound — they
            # mark the replica draining instead, capped by a deadline.
            exclude: list = []
            casualties = 0
            drain_deadline = _time.monotonic() + 30.0
            while True:
                t0 = _time.monotonic()
                replica = self._router.assign_replica(
                    deployment, model_id=model_id, prefix_hint=prefix_hint,
                    exclude=exclude,
                )
                try:
                    actor = self._router.handle_for(replica)
                    ref = actor.handle_http_request.remote(
                        method, path, query, req_body, headers, model_id,
                        matched_prefix, raw_query,
                    )
                    result = ray_tpu.get(ref, timeout=120)
                except BaseException as e:
                    self._router.release(replica, deployment=deployment)
                    if _drain_refused(e) and _time.monotonic() < drain_deadline:
                        self._router.mark_draining(replica)
                        exclude.append(replica["actor_name"])
                        continue
                    casualties += 1
                    if casualties <= 1 and _replica_went_away(e):
                        self._router.invalidate_handle(replica)
                        exclude.append(replica["actor_name"])
                        continue
                    raise
                break
            if isinstance(result, dict) and "__serve_stream__" in result:
                # Streaming: the replica stays assigned (queue metrics + its
                # generator live there) until the pump finishes.
                return replica, result
            self._router.release(
                replica, deployment=deployment, duration_s=_time.monotonic() - t0
            )
            return None, result

        try:
            replica, result = await loop.run_in_executor(self._pool, call)
        except Exception as e:
            logger.exception("request to %s failed", deployment)
            await _respond(send, 500, f"{type(e).__name__}: {e}".encode(), "text/plain")
            return

        if replica is not None:
            await self._pump_stream(send, loop, deployment, replica, result)
            return

        status, payload, ctype, extra = _encode_result(result)
        await _respond(send, status, payload, ctype, extra)

    def _prefill_handoff(
        self, prefill_dep, body, headers, model_id, prefix_hint,
        path, query, matched_prefix, raw_query,
    ):
        """Prefill leg of a disaggregated LLM request (runs in the executor
        pool: blocking calls). Sends the ORIGINAL body to a prefill-pool
        replica — prefix_hint affinity steers shared prompts to the replica
        whose cache (local or imported via the cluster prefix tier) already
        holds their KV — and translates the ``__llm_handoff__`` envelope it
        returns into the decode-pool body: the original request plus the
        sealed-KV descriptor, the first sampled token as resume_tokens, and
        echo_resume so the client still sees that token.

        Returns the rewritten body bytes, or None for ANY miss — body not
        an LLM generate, already a resume/handoff, prefill pool saturated,
        dead, draining, or unable to seal — in which case the caller sends
        the original body to the decode pool and it recomputes the prefill.
        The handoff is an optimization, never a point of failure."""
        import ray_tpu

        try:
            parsed = json.loads(body or b"{}")
        except Exception:
            return None
        if not isinstance(parsed, dict) or "tokens" not in parsed:
            return None
        if parsed.get("resume_tokens") or parsed.get("kv_import"):
            return None  # mid-migration/handoff already — decode directly
        exclude: list = []
        casualties = 0
        drain_deadline = time.monotonic() + 30.0
        while True:
            try:
                replica = self._router.assign_replica(
                    prefill_dep, timeout_s=10.0, model_id=model_id,
                    prefix_hint=prefix_hint, exclude=exclude,
                )
            except TimeoutError:
                return None
            try:
                actor = self._router.handle_for(replica)
                result = ray_tpu.get(
                    actor.handle_http_request.remote(
                        "POST", path, query, body, headers, model_id,
                        matched_prefix, raw_query,
                    ),
                    timeout=120,
                )
            except BaseException as e:
                self._router.release(replica, deployment=prefill_dep)
                if _drain_refused(e) and time.monotonic() < drain_deadline:
                    self._router.mark_draining(replica)
                    exclude.append(replica["actor_name"])
                    continue
                casualties += 1
                if casualties <= 1 and _replica_went_away(e):
                    self._router.invalidate_handle(replica)
                    exclude.append(replica["actor_name"])
                    continue
                logger.warning(
                    "prefill leg of %s failed (%s); decode pool recomputes",
                    prefill_dep, type(e).__name__,
                )
                return None
            self._router.release(replica, deployment=prefill_dep)
            break
        env = result.get("__llm_handoff__") if isinstance(result, dict) else None
        if env is None:
            return None  # engine decoded locally (could not seal)
        body2 = dict(env.get("body") or {})
        body2["resume_tokens"] = list(env.get("resume_tokens") or ())
        body2["kv_import"] = env["kv_import"]
        body2["echo_resume"] = True
        return json.dumps(body2).encode()

    # Mid-stream migrations per request: one covers the common single
    # replica death; the second covers dying onto a second casualty during
    # a rolling restart. Beyond that the stream aborts honestly.
    _MAX_MIGRATIONS = 2

    async def _pump_stream(self, send, loop, deployment, replica, envelope):
        import ray_tpu

        sid = envelope["__serve_stream__"]
        resume = envelope.get("__serve_resume__")
        parser = (
            _SSETokenParser() if resume and resume.get("kind") == "sse_tokens" else None
        )
        await _respond_start(
            send,
            int(envelope.get("status", 200)),
            envelope.get("content_type", "application/octet-stream"),
            envelope.get("headers") or {},
        )
        actor = self._router.handle_for(replica)
        finished = False
        migrations = 0
        dead: list = []
        # Slot-accounting ownership: the dead replica is released at the
        # START of a migration, so a failed migration must not let the
        # finally below release it a second time (release() clamps at 0,
        # but a double decrement would steal a count from another stream
        # still assigned to the same replica).
        held = True
        try:
            while True:
                try:
                    batch = await loop.run_in_executor(
                        self._pool,
                        lambda: ray_tpu.get(
                            actor.next_stream_chunk.remote(sid), timeout=120
                        ),
                    )
                except Exception as e:
                    if (
                        parser is None
                        or migrations >= self._MAX_MIGRATIONS
                        or not _replica_went_away(e)
                    ):
                        raise
                    # Typed replica death mid-stream: MIGRATE. Resubmit the
                    # original request to another replica with the tokens
                    # the client already received teacher-forced back in —
                    # the engine continues bit-identically from there and
                    # re-emits nothing.
                    migrations += 1
                    dead.append(replica["actor_name"])
                    self._router.release(replica, deployment=deployment)
                    self._router.invalidate_handle(replica)
                    held = False
                    replica, actor, sid = await loop.run_in_executor(
                        self._pool,
                        lambda: self._migrate_stream(deployment, resume, parser, dead),
                    )
                    held = True
                    continue
                if batch is None:
                    finished = True
                    break
                for chunk in batch["chunks"]:
                    if parser is not None:
                        parser.feed(chunk)
                    await send({"type": "http.response.body", "body": chunk, "more_body": True})
                if batch["done"]:
                    finished = True
                    break
        except Exception:
            logger.exception("stream from %s aborted", deployment)
        finally:
            if not finished:
                # Client disconnect / pump error: tear the stream down now
                # rather than leaving its generator to the replica's
                # 5-minute idle reaper.
                try:
                    actor.cancel_stream.remote(sid)
                except Exception:
                    pass
            if held:
                self._router.release(replica, deployment=deployment)
        await send({"type": "http.response.body", "body": b"", "more_body": False})

    def _migrate_stream(self, deployment, resume, parser, dead):
        """Resubmit a broken stream's request to a live replica with
        ``resume_tokens=`` (runs in the executor pool: blocking calls).
        Returns (replica, actor, sid) of the resumed stream. The migration
        TARGET can itself be mid-death/drain (stale table during a rolling
        restart) — that is the same went-away race as everywhere else, so
        it is excluded and the resubmit retried within a bound rather than
        aborting a stream healthy replicas could still serve."""
        import ray_tpu
        from ray_tpu._private import flight_recorder, self_metrics

        body2 = dict(resume.get("body") or {})
        body2["resume_tokens"] = parser.tokens
        body2["stream"] = True
        payload = json.dumps(body2).encode()
        # Replay the ORIGINAL request's routing context (stamped by the
        # replica into the resume descriptor) — only the body changes. The
        # dead replica is excluded, so prefix affinity is moot, but model
        # affinity still steers multiplexed deployments to a warm replica.
        ctx = resume.get("ctx") or {}
        casualties = 0
        drain_deadline = time.monotonic() + 30.0
        while True:
            replica = self._router.assign_replica(
                deployment, model_id=ctx.get("model_id", ""), exclude=dead
            )
            try:
                actor = self._router.handle_for(replica)
                env2 = ray_tpu.get(
                    actor.handle_http_request.remote(
                        ctx.get("method", "POST"),
                        ctx.get("path", "/"),
                        ctx.get("query", {}),
                        payload,
                        ctx.get("headers", {}),
                        ctx.get("model_id", ""),
                        ctx.get("route_prefix"),
                        ctx.get("raw_query"),
                    ),
                    timeout=120,
                )
                if not (isinstance(env2, dict) and "__serve_stream__" in env2):
                    raise RuntimeError(
                        f"migration resubmit did not return a stream: {type(env2)}"
                    )
            except BaseException as e:
                self._router.release(replica, deployment=deployment)
                # A draining target refused: not a casualty (the bound is
                # for crashes) — mark it, exclude it, keep looking.
                if _drain_refused(e) and time.monotonic() < drain_deadline:
                    self._router.mark_draining(replica)
                    dead.append(replica["actor_name"])
                    continue
                casualties += 1
                if casualties <= self._MAX_MIGRATIONS and _replica_went_away(e):
                    self._router.invalidate_handle(replica)
                    dead.append(replica["actor_name"])
                    continue
                raise
            break
        flight_recorder.record(
            "llm_migrate", f"{deployment[:20]}:n{len(parser.tokens)}"
        )
        try:
            self_metrics.instruments()["serve_migrations"].inc(
                tags={"deployment": deployment}
            )
        except Exception:
            pass
        logger.warning(
            "migrated stream of %s to %s after replica death "
            "(%d tokens teacher-forced)",
            deployment, replica["actor_name"], len(parser.tokens),
        )
        return replica, actor, env2["__serve_stream__"]


def _encode_result(result):
    """Replica return value -> (status, payload bytes, content_type, extra_headers)."""
    if isinstance(result, dict) and result.get("__serve_http_response__"):
        body = result.get("body", b"")
        if isinstance(body, str):
            body = body.encode()
        headers = dict(result.get("headers") or {})
        ctype = next(
            (v for k, v in headers.items() if k.lower() == "content-type"),
            "application/octet-stream",
        )
        headers = {k: v for k, v in headers.items() if k.lower() != "content-type"}
        return int(result.get("status", 200)), body, ctype, headers
    if isinstance(result, bytes):
        return 200, result, "application/octet-stream", None
    if isinstance(result, str):
        return 200, result.encode(), "text/plain; charset=utf-8", None
    return 200, json.dumps(result, default=_np_default).encode(), "application/json", None


class AiohttpASGIServer:
    """Serve any ASGI-3 application on aiohttp.

    The seam the reference gets from uvicorn: this class is the ONLY place
    that knows the HTTP server's types. `await start()` on the serving loop
    binds the socket; `.port` is the actual bound port.
    """

    def __init__(self, app, host: str = "127.0.0.1", port: int = 0):
        self._app = app
        self._host = host
        self._want_port = port
        self.port: int | None = None
        self._runner = None

    async def start(self):
        from aiohttp import web

        async def handle(request: "web.Request"):
            scope = _build_scope(
                request.method,
                request.path,
                "",
                request.query_string.encode("utf-8"),
                [
                    (k.lower().encode("latin-1"), v.encode("latin-1"))
                    for k, v in request.headers.items()
                ],
                client=request.transport.get_extra_info("peername")
                if request.transport
                else None,
                server=(self._host, self.port),
            )
            body = await request.read()
            delivered = [False]
            # Set when the final http.response.body lands; a second receive()
            # blocks until then (a live client is NOT "disconnected" — apps
            # that race response-writing against a disconnect listener must
            # not see an instant disconnect). A real mid-stream disconnect
            # cancels this handler task, which cancels the app coroutine at
            # whatever await it is parked on — the uvicorn behavior.
            response_done = asyncio.Event()

            async def receive():
                if not delivered[0]:
                    delivered[0] = True
                    return {"type": "http.request", "body": body, "more_body": False}
                await response_done.wait()
                return dict(_DISCONNECT)

            state: dict = {"status": 200, "headers": [], "resp": None}

            async def send(event):
                if event["type"] == "http.response.start":
                    state["status"] = event["status"]
                    state["headers"] = event.get("headers", [])
                    return
                if event["type"] != "http.response.body":
                    return
                chunk = event.get("body", b"")
                more = event.get("more_body", False)
                hdrs = {
                    k.decode("latin-1"): v.decode("latin-1") for k, v in state["headers"]
                }
                if state["resp"] is None:
                    if not more:
                        state["resp"] = web.Response(
                            status=state["status"], body=chunk, headers=hdrs
                        )
                        response_done.set()
                        return
                    resp = web.StreamResponse(status=state["status"], headers=hdrs)
                    await resp.prepare(request)
                    if chunk:
                        await resp.write(chunk)
                    state["resp"] = resp
                    return
                resp = state["resp"]
                if isinstance(resp, web.StreamResponse) and not isinstance(resp, web.Response):
                    if chunk:
                        await resp.write(chunk)
                    if not more:
                        await resp.write_eof()
                        response_done.set()

            await self._app(scope, receive, send)
            resp = state["resp"]
            if resp is None:
                resp = web.Response(status=500, text="ASGI app sent no response")
            return resp

        app = web.Application(client_max_size=1 << 30)
        app.router.add_route("*", "/{tail:.*}", handle)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self._host, self._want_port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return self


_ingress_loop_lock = threading.Lock()
_ingress_loop = None


def _get_ingress_loop():
    """One persistent event loop thread per process for all serve.ingress
    apps — loop-bound app state (connection pools, caches) survives across
    requests and no thread/loop is created per request."""
    global _ingress_loop
    with _ingress_loop_lock:
        if _ingress_loop is None or not _ingress_loop[1].is_alive():
            loop = asyncio.new_event_loop()
            thread = threading.Thread(
                target=lambda: (asyncio.set_event_loop(loop), loop.run_forever()),
                name="asgi-ingress",
                daemon=True,
            )
            thread.start()
            _ingress_loop = (loop, thread)
        return _ingress_loop[0]


class _AppBridge:
    """send/receive pair driving a user ASGI app from sync replica code.

    - ``send`` events land in a BOUNDED queue drained by the caller (fast
      producers park in ``send`` — uvicorn-style backpressure); once
      ``closed`` is set (client gone or response fully consumed) further
      sends raise ClientDisconnected so the app stops producing — the leak
      guard for infinite SSE producers whose client went away.
    - app completion is signalled via the ``done`` flag + ``error`` holder
      (never a queue put, which could block the shared ingress loop on a
      full queue); a sentinel wake is best-effort with put_nowait.
    - a second ``receive`` blocks until ``closed``, then reports
      http.disconnect — never an instant disconnect while the response is
      still being consumed (spec: disconnect means the client is GONE).
    """

    # Bounded: a fast producer with a slow client parks in ``send`` instead
    # of buffering the whole response in replica memory (uvicorn's
    # backpressure, expressed as a poll so the shared ingress loop is never
    # blocked by one stream).
    _MAX_BUFFERED_EVENTS = 256

    def __init__(self, body: bytes):
        import queue as _queue

        self.out: _queue.Queue = _queue.Queue(maxsize=self._MAX_BUFFERED_EVENTS)
        self.closed = threading.Event()
        self.done = threading.Event()
        self.error: BaseException | None = None
        self._body = body
        self._delivered = False

    @any_thread
    def finish(self, error: BaseException | None):
        """Mark the app coroutine finished. Usually runs on the shared
        ingress loop (future done-callback), so it must never block: flag
        first, then a best-effort wake. @any_thread, not @loop_only: when
        the app coroutine finishes before ``add_done_callback`` registers,
        the callback fires synchronously on the REPLICA thread instead
        (audited for graftlint: the run_coroutine_threadsafe result is
        never ``.result()``-ed anywhere the ingress loop could reach)."""
        import queue as _queue

        self.error = error
        self.done.set()
        try:
            self.out.put_nowait({"type": "__app_done__"})
        except _queue.Full:
            pass  # consumer will drain the queue and then see the flag

    async def receive(self):
        if not self._delivered:
            self._delivered = True
            return {"type": "http.request", "body": self._body, "more_body": False}
        await asyncio.get_running_loop().run_in_executor(None, self.closed.wait)
        return dict(_DISCONNECT)

    async def send(self, event):
        import queue as _queue

        while True:
            if self.closed.is_set():
                raise ClientDisconnected()
            try:
                self.out.put_nowait(event)
                return
            except _queue.Full:
                await asyncio.sleep(0.02)


def _next_event(bridge: _AppBridge, deadline_s: float):
    """Next send event from the bridge, or None once the app has finished
    and the queue is drained. Raises the app's error (after in-order
    delivery of everything it sent first) or TimeoutError on a stalled app."""
    import queue as _queue

    end = time.monotonic() + deadline_s
    while True:
        try:
            ev = bridge.out.get(timeout=0.1)
        except _queue.Empty:
            if bridge.done.is_set():
                if bridge.error is not None:
                    raise bridge.error
                return None
            if time.monotonic() > end:
                raise TimeoutError("ASGI app produced no event within deadline")
            continue
        if ev["type"] == "__app_done__":
            if bridge.error is not None:
                raise bridge.error
            return None
        return ev


@blocking
def run_asgi_request(asgi_app, request):
    """Drive a user ASGI app with a replica `HTTPRequest`, sync->async bridge.

    Replica side of `serve.ingress` (reference mounts FastAPI apps this way,
    python/ray/serve/api.py:100; here any ASGI-3 callable). The app runs on
    the shared per-process ingress loop; its send events are collected from
    a queue. Buffered responses return the envelope dict `_encode_result`
    understands; streaming responses (more_body=True) return a
    `StreamingResponse` whose generator drains the queue as the app
    produces chunks — riding the replica's existing stream pump.

    Scope mapping: the deployment's matched route prefix becomes ASGI
    `root_path` and the app sees the sub-path, so apps behave identically
    under any mount point (starlette mount semantics). The query string is
    the raw wire bytes the proxy saw (duplicate keys and ordering intact).
    """
    from ray_tpu.serve.api import StreamingResponse

    raw_query = getattr(request, "raw_query_string", None)
    if raw_query is None:
        raw_query = urlencode(request.query_params or {})
    scope = _build_scope(
        request.method,
        request.sub_path,
        (request.route_prefix or "").rstrip("/"),
        raw_query.encode("utf-8", "surrogateescape"),
        [
            (k.lower().encode("latin-1"), str(v).encode("latin-1"))
            for k, v in (request.headers or {}).items()
        ],
    )
    bridge = _AppBridge(request.body or b"")
    fut = asyncio.run_coroutine_threadsafe(
        asgi_app(scope, bridge.receive, bridge.send), _get_ingress_loop()
    )

    def _on_done(f):
        try:
            exc = f.exception()
        except asyncio.CancelledError:
            exc = None
        if isinstance(exc, ClientDisconnected):
            exc = None
        bridge.finish(exc)

    fut.add_done_callback(_on_done)

    status, headers = 200, {}
    chunks: list[bytes] = []
    streaming = False
    try:
        while True:
            ev = _next_event(bridge, 120.0)
            if ev is None:
                break
            if ev["type"] == "http.response.start":
                status = ev["status"]
                headers = {
                    k.decode("latin-1"): v.decode("latin-1")
                    for k, v in ev.get("headers", [])
                }
            elif ev["type"] == "http.response.body":
                chunk = ev.get("body", b"")
                if ev.get("more_body", False):
                    streaming = True  # the generator owns bridge closure

                    def gen(first=chunk):
                        try:
                            if first:
                                yield first
                            while True:
                                e2 = _next_event(bridge, 300.0)
                                if e2 is None:
                                    return
                                if e2["type"] == "http.response.body":
                                    b2 = e2.get("body", b"")
                                    if b2:
                                        yield b2
                                    if not e2.get("more_body", False):
                                        return
                        finally:
                            # Normal end, client disconnect (GeneratorExit
                            # via the stream pump's close), or error: stop
                            # the producer and unblock its receive().
                            bridge.closed.set()

                    ctype = next(
                        (v for k, v in headers.items() if k.lower() == "content-type"),
                        "application/octet-stream",
                    )
                    return StreamingResponse(
                        gen(),
                        content_type=ctype,
                        status=status,
                        headers={
                            k: v for k, v in headers.items() if k.lower() != "content-type"
                        },
                    )
                chunks.append(chunk)
                break  # complete buffered response
    finally:
        # Buffered response consumed, app finished, or collection failed:
        # post-response sends raise and a parked disconnect-listener
        # receive() resolves. The streaming path closes from its generator.
        if not streaming:
            bridge.closed.set()
    return {
        "__serve_http_response__": True,
        "status": status,
        "headers": headers,
        "body": b"".join(chunks),
    }
