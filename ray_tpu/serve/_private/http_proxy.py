"""HTTP ingress proxy actor.

Reference: python/ray/serve/_private/http_proxy.py:320 HTTPProxy (ASGI app),
:553 HTTPProxyActor — one proxy actor per node, routing by longest prefix to
deployment replicas. Here the ASGI stack is aiohttp running on a dedicated
thread inside the proxy actor process; replica calls run in an executor so
the HTTP loop never blocks on the object store.
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
from concurrent.futures import ThreadPoolExecutor

import ray_tpu

logger = logging.getLogger(__name__)


class HTTPProxy:
    def __init__(self, controller_name: str, host: str = "127.0.0.1", port: int = 8000):
        from ray_tpu.serve._private.router import Router

        controller = ray_tpu.get_actor(controller_name)
        self._router = Router(controller)
        self._host = host
        self._port = port
        self._pool = ThreadPoolExecutor(max_workers=32, thread_name_prefix="proxy-call")
        self._ready = threading.Event()
        self._actual_port = None
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        self._ready.wait(timeout=30)

    def address(self) -> tuple:
        return (self._host, self._actual_port)

    def ready(self) -> bool:
        return self._ready.is_set()

    def _serve(self):
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)

        async def handler(request: "web.Request"):
            path = request.path
            if path == "/-/healthz":
                return web.Response(text="ok")
            if path == "/-/routes":
                with self._router._lock:
                    routes = {
                        name: e.get("route_prefix")
                        for name, e in self._router._table.items()
                    }
                return web.json_response(routes)
            deployment, matched_prefix = self._router.route_and_prefix_for(path)
            if deployment is None:
                return web.Response(status=404, text=f"no deployment for path {path}")
            body = await request.read()
            method = request.method
            query = dict(request.query)
            headers = dict(request.headers)

            def call():
                from ray_tpu.serve._private.common import MULTIPLEXED_MODEL_ID_HEADER

                # Case-insensitive header lookup without mutating the header
                # dict user deployments receive.
                model_id = next(
                    (v for k, v in headers.items() if k.lower() == MULTIPLEXED_MODEL_ID_HEADER),
                    "",
                )
                replica = self._router.assign_replica(deployment, model_id=model_id)
                try:
                    actor = self._router.handle_for(replica)
                    ref = actor.handle_http_request.remote(
                        method, path, query, body, headers, model_id,
                        matched_prefix,
                    )
                    result = ray_tpu.get(ref, timeout=120)
                except BaseException:
                    self._router.release(replica)
                    raise
                if isinstance(result, dict) and "__serve_stream__" in result:
                    # Streaming: the replica stays assigned (queue metrics +
                    # its generator lives there) until the pump finishes.
                    return replica, result
                self._router.release(replica)
                return None, result

            try:
                replica, result = await loop.run_in_executor(self._pool, call)
            except Exception as e:
                logger.exception("request to %s failed", deployment)
                return web.Response(status=500, text=f"{type(e).__name__}: {e}")
            if replica is not None:
                sid = result["__serve_stream__"]
                resp = web.StreamResponse(
                    headers={"Content-Type": result.get("content_type", "application/octet-stream")}
                )
                await resp.prepare(request)
                actor = self._router.handle_for(replica)
                finished = False
                try:
                    while True:
                        batch = await loop.run_in_executor(
                            self._pool,
                            lambda: ray_tpu.get(
                                actor.next_stream_chunk.remote(sid), timeout=120
                            ),
                        )
                        if batch is None:
                            finished = True
                            break
                        for chunk in batch["chunks"]:
                            await resp.write(chunk)
                        if batch["done"]:
                            finished = True
                            break
                except Exception:
                    logger.exception("stream from %s aborted", deployment)
                finally:
                    if not finished:
                        # Client disconnect / pump error: tear the stream
                        # down now rather than leaving its generator to the
                        # replica's 5-minute idle reaper.
                        try:
                            actor.cancel_stream.remote(sid)
                        except Exception:
                            pass
                    self._router.release(replica)
                await resp.write_eof()
                return resp
            if isinstance(result, bytes):
                return web.Response(body=result)
            if isinstance(result, str):
                return web.Response(text=result)
            return web.json_response(result, dumps=lambda o: json.dumps(o, default=_np_default))

        app = web.Application(client_max_size=1 << 30)
        app.router.add_route("*", "/{tail:.*}", handler)
        runner = web.AppRunner(app, access_log=None)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, self._host, self._port)
        loop.run_until_complete(site.start())
        self._actual_port = site._server.sockets[0].getsockname()[1]
        self._ready.set()
        loop.run_forever()


def _np_default(o):
    import numpy as np

    if isinstance(o, np.ndarray):
        return o.tolist()
    if isinstance(o, np.generic):
        return o.item()
    raise TypeError(f"not JSON serializable: {type(o)}")
