"""HTTP ingress proxy actor.

Reference: python/ray/serve/_private/http_proxy.py:320 HTTPProxy (ASGI app),
:553 HTTPProxyActor — one proxy actor per node, routing by longest prefix to
deployment replicas. The routing logic lives in `ProxyASGIApp`
(_private/asgi.py), a pure ASGI-3 application — exactly the reference's
shape — and this actor just binds it to a server. The server is the
`AiohttpASGIServer` adapter (uvicorn is absent from the image); swapping
servers touches only that adapter, never the app. Replica calls run in an
executor so the HTTP loop never blocks on the object store.
"""

from __future__ import annotations

import asyncio
import logging
import threading
from concurrent.futures import ThreadPoolExecutor

import ray_tpu

logger = logging.getLogger(__name__)


class HTTPProxy:
    def __init__(self, controller_name: str, host: str = "127.0.0.1", port: int = 8000):
        from ray_tpu.serve._private.router import Router

        controller = ray_tpu.get_actor(controller_name)
        self._router = Router(controller)
        self._host = host
        self._port = port
        self._pool = ThreadPoolExecutor(max_workers=32, thread_name_prefix="proxy-call")
        self._ready = threading.Event()
        self._actual_port = None
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        self._ready.wait(timeout=30)

    def address(self) -> tuple:
        return (self._host, self._actual_port)

    def ready(self) -> bool:
        return self._ready.is_set()

    def _serve(self):
        from ray_tpu.serve._private.asgi import AiohttpASGIServer, ProxyASGIApp

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        app = ProxyASGIApp(self._router, self._pool)
        server = AiohttpASGIServer(app, self._host, self._port)
        loop.run_until_complete(server.start())
        self._actual_port = server.port
        self._ready.set()
        loop.run_forever()
