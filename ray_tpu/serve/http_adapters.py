"""HTTP adapters — convert ingress requests into deployment inputs.

Analog of the reference's ray.serve.http_adapters (python/ray/serve/
http_adapters.py): small callables the DAGDriver applies to the incoming
request before invoking a graph branch. Accepts either the callable itself
or its import string (e.g. ``"ray_tpu.serve.http_adapters.json_request"``).
"""

from __future__ import annotations

import importlib
from typing import Callable, Optional, Union


def json_request(request):
    """Parse the body as JSON (the reference's default adapter)."""
    return request.json()


def text_request(request):
    return request.text()  # None-body-safe (HTTPRequest.text guards)


def bytes_request(request):
    return request.body


def query_params(request):
    """Pass the query-string parameters through as a dict."""
    return dict(request.query_params)


def json_to_ndarray(request):
    """JSON body -> numpy array (reference: json_to_ndarray)."""
    import numpy as np

    return np.asarray(request.json())


def load_http_adapter(adapter: Optional[Union[str, Callable]]) -> Callable:
    """Resolve an adapter: None -> json_request, import string -> callable."""
    if adapter is None:
        return json_request
    if callable(adapter):
        return adapter
    module, _, attr = str(adapter).rpartition(".")
    if not module:
        raise ValueError(f"invalid http_adapter import string {adapter!r}")
    fn = getattr(importlib.import_module(module), attr)
    if not callable(fn):
        raise TypeError(f"http_adapter {adapter!r} is not callable")
    return fn
