"""Serve config schema (analog of reference python/ray/serve/schema.py).

Pydantic models for the declarative multi-application deploy config that
`serve deploy <file>` consumes (the reference posts the same shape to the
dashboard's REST API; here the CLI — already a driver — applies it
directly, and the dashboard exposes read-only serve state).

Example config (YAML or JSON):

    applications:
      - name: default
        import_path: my_module:app
        route_prefix: /
        deployments:
          - name: Model
            num_replicas: 2
            max_concurrent_queries: 16
            autoscaling_config:
              min_replicas: 1
              max_replicas: 4

Disaggregated LLM pools (serve.llm.disaggregated_llm_app) are two sibling
deployments of one application — size them independently with two entries:

    deployments:
      - name: llm            # decode pool (owns the route)
        num_replicas: 2
      - name: llm--prefill   # prefill pool (handle-only)
        num_replicas: 2
"""

from __future__ import annotations

from typing import Any, Optional

from pydantic import BaseModel, Field, field_validator


class AutoscalingConfigSchema(BaseModel):
    min_replicas: int = 1
    max_replicas: int = 1
    target_num_ongoing_requests_per_replica: float = 1.0
    upscale_delay_s: float = 0.0
    downscale_delay_s: float = 0.0


class DeploymentSchema(BaseModel):
    name: str
    num_replicas: Optional[int] = None
    max_concurrent_queries: Optional[int] = None
    user_config: Optional[Any] = None
    ray_actor_options: Optional[dict] = None
    autoscaling_config: Optional[AutoscalingConfigSchema] = None


class ServeApplicationSchema(BaseModel):
    name: str = "default"
    import_path: str
    route_prefix: Optional[str] = None
    args: dict = Field(default_factory=dict)
    deployments: list[DeploymentSchema] = Field(default_factory=list)

    @field_validator("import_path")
    @classmethod
    def _check_import_path(cls, v: str) -> str:
        if ":" not in v and "." not in v:
            raise ValueError(
                f"import_path {v!r} must look like 'module:attribute'"
            )
        return v


class ServeDeploySchema(BaseModel):
    applications: list[ServeApplicationSchema]

    @field_validator("applications")
    @classmethod
    def _unique_names(cls, v):
        names = [a.name for a in v]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate application names in config: {names}")
        return v


def load_config(path: str) -> ServeDeploySchema:
    """Parse + validate a YAML/JSON deploy config file."""
    import json

    with open(path) as f:
        text = f.read()
    if path.endswith((".yaml", ".yml")):
        import yaml

        data = yaml.safe_load(text)
    else:
        data = json.loads(text)
    return ServeDeploySchema(**data)


def _apply_overrides(app, overrides: dict, used: set):
    """Rebuild the Application tree with config overrides applied — bound
    deployments can nest inside init args, including containers
    (Ingress.bind([A.bind(), B.bind()], cfg={"m": C.bind()})). Shared
    bindings (the same Application object bound twice) stay shared: the
    rebuild is memoized by node identity so serve.run's diamond detection
    keeps working."""
    from ray_tpu import serve

    if not overrides:
        return app  # nothing to change — keep the exact object graph
    memo: dict[int, object] = {}

    def rebuild(node):
        if isinstance(node, serve.Application):
            if id(node) in memo:
                return memo[id(node)]
            dep = node.deployment
            override = overrides.get(dep.name)
            if override is not None:
                used.add(dep.name)
                dep = dep.options(
                    num_replicas=override.num_replicas,
                    max_concurrent_queries=override.max_concurrent_queries,
                    user_config=override.user_config,
                    ray_actor_options=override.ray_actor_options,
                    autoscaling_config=(
                        override.autoscaling_config.model_dump()
                        if override.autoscaling_config is not None
                        else None
                    ),
                )
            out = serve.Application(
                dep,
                tuple(rebuild(a) for a in node.init_args),
                {k: rebuild(v) for k, v in node.init_kwargs.items()},
            )
            memo[id(node)] = out
            # Sibling applications (disaggregated-LLM prefill pools) are
            # part of the tree: rebuild them so a config file can size the
            # two pools independently (e.g. override "llm" and
            # "llm--prefill" num_replicas as two deployment entries).
            out.extras = [rebuild(e) for e in getattr(node, "extras", ())]
            return out
        # Exact list/tuple/dict only — a namedtuple or tuple subclass has a
        # different constructor signature and passes through untouched.
        if type(node) in (list, tuple):
            return type(node)(rebuild(v) for v in node)
        if type(node) is dict:
            return {k: rebuild(v) for k, v in node.items()}
        return node

    return rebuild(app)


def apply_config(config: ServeDeploySchema) -> dict:
    """Deploy every application in the config (CLI-side analog of the
    reference controller's deploy_apps). Returns {app_name: route_prefix};
    a None route means the app is handle-only (no HTTP route registered)."""
    import importlib
    import os
    import sys

    from ray_tpu import serve

    routes = {}
    if os.getcwd() not in sys.path:
        sys.path.insert(0, os.getcwd())
    for app_schema in config.applications:
        mod_name, _, attr = app_schema.import_path.partition(":")
        app = getattr(importlib.import_module(mod_name), attr or "app")
        overrides = {d.name: d for d in app_schema.deployments}
        used: set = set()
        app = _apply_overrides(app, overrides, used)
        unknown = set(overrides) - used
        if unknown:
            raise ValueError(
                f"config for app {app_schema.name!r} overrides deployments "
                f"{sorted(unknown)} that do not exist in the application"
            )
        serve.run(
            app,
            name=app_schema.name,
            route_prefix=app_schema.route_prefix or "__from_deployment__",
            _blocking=True,
        )
        # Report only routes that were actually registered.
        routes[app_schema.name] = app_schema.route_prefix or app.deployment.route_prefix
    return routes
