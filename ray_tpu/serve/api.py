"""Public Serve API.

Reference: python/ray/serve/api.py — serve.start :61, @serve.deployment :241,
serve.run :413; Deployment in serve/deployment.py.

Usage:
    @serve.deployment(num_replicas=2)
    class Model:
        def __call__(self, request): ...

    handle = serve.run(Model.bind(arg), route_prefix="/model")
    ray_tpu.get(handle.remote(x))
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pickle
import time

import cloudpickle
from typing import Any, Callable, Optional

import ray_tpu
from ray_tpu.serve._private.common import (
    CONTROLLER_NAME,
    AutoscalingConfig,
    DeploymentConfig,
    DeploymentInfo,
    HandleMarker,
)
from ray_tpu.serve.handle import DeploymentHandle

_started = False
_http_port: Optional[int] = None


class Application:
    """A bound deployment (reference: serve's built Application via .bind())."""

    def __init__(self, deployment: "Deployment", init_args: tuple, init_kwargs: dict):
        self.deployment = deployment
        self.init_args = init_args
        self.init_kwargs = init_kwargs
        # Sibling applications deployed (and torn down) WITH this one but
        # not referenced from its init args — e.g. the prefill pool paired
        # with a disaggregated LLM decode deployment, which the proxy finds
        # by naming convention rather than by handle. Each keeps its own
        # name and route prefix.
        self.extras: list = []


class Deployment:
    def __init__(self, cls_or_fn: Callable, name: str, config: DeploymentConfig, route_prefix: Optional[str]):
        self._cls_or_fn = cls_or_fn
        self.name = name
        self.config = config
        self.route_prefix = route_prefix

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def options(self, *, num_replicas: Optional[int] = None, name: Optional[str] = None,
                max_concurrent_queries: Optional[int] = None, user_config: Any = None,
                ray_actor_options: Optional[dict] = None, autoscaling_config=None,
                route_prefix: Optional[str] = "__unset__", version: Optional[str] = None,
                drain_timeout_s: Optional[float] = None) -> "Deployment":
        import dataclasses

        cfg = dataclasses.replace(self.config)
        if num_replicas is not None:
            cfg.num_replicas = num_replicas
        if drain_timeout_s is not None:
            cfg.drain_timeout_s = drain_timeout_s
        if max_concurrent_queries is not None:
            cfg.max_concurrent_queries = max_concurrent_queries
        if user_config is not None:
            cfg.user_config = user_config
        if ray_actor_options is not None:
            cfg.ray_actor_options = ray_actor_options
        if autoscaling_config is not None:
            cfg.autoscaling = _coerce_autoscaling(autoscaling_config)
        if version is not None:
            cfg.version = version
        return Deployment(
            self._cls_or_fn,
            name or self.name,
            cfg,
            self.route_prefix if route_prefix == "__unset__" else route_prefix,
        )


def deployment(
    _cls=None,
    *,
    name: Optional[str] = None,
    num_replicas: int = 1,
    max_concurrent_queries: int = 100,
    user_config: Any = None,
    ray_actor_options: Optional[dict] = None,
    autoscaling_config=None,
    route_prefix: Optional[str] = None,
    version: Optional[str] = None,
    drain_timeout_s: float = 30.0,
):
    """``@serve.deployment`` decorator (reference: api.py:241)."""

    def wrap(cls_or_fn):
        cfg = DeploymentConfig(
            num_replicas=num_replicas,
            max_concurrent_queries=max_concurrent_queries,
            user_config=user_config,
            ray_actor_options=ray_actor_options or {},
            autoscaling=_coerce_autoscaling(autoscaling_config),
            version=version,
            drain_timeout_s=drain_timeout_s,
        )
        return Deployment(cls_or_fn, name or cls_or_fn.__name__, cfg, route_prefix)

    if _cls is not None:
        return wrap(_cls)
    return wrap


def _coerce_autoscaling(cfg) -> Optional[AutoscalingConfig]:
    if cfg is None:
        return None
    if isinstance(cfg, AutoscalingConfig):
        return cfg
    return AutoscalingConfig(**cfg)


def start(http_host: str = "127.0.0.1", http_port: int = 0, detached: bool = True):
    """Start the Serve control plane: controller actor + one HTTP proxy per
    node (reference: http_state.py proxy fleet). The controller's reconcile
    loop keeps a proxy on every ALIVE node and replaces unhealthy ones, so
    ingress survives losing the node a proxy lives on."""
    global _started, _http_port
    if _started:
        return
    from ray_tpu.serve._private.controller import ServeController

    controller_cls = ray_tpu.remote(num_cpus=0, name=CONTROLLER_NAME, max_concurrency=16)(ServeController)
    controller_cls.remote()
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    addrs = ray_tpu.get(controller.ensure_http.remote(http_host, http_port), timeout=120)
    deadline = time.time() + 60
    while not addrs and time.time() < deadline:
        time.sleep(0.5)
        addrs = ray_tpu.get(controller.proxy_addresses.remote())
    if not addrs:
        raise RuntimeError("no serve proxy came up on any node")
    _http_port = next(iter(addrs.values()))[1]
    _started = True


def http_address() -> tuple:
    """Address of one live ingress proxy (prefer this node's)."""
    from ray_tpu._private.worker_context import get_core_worker

    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    addrs = ray_tpu.get(controller.proxy_addresses.remote())
    if not addrs:
        raise RuntimeError("no live serve proxies")
    local = addrs.get(get_core_worker().node_id)
    return tuple(local if local is not None else next(iter(addrs.values())))


def http_addresses() -> dict:
    """All live ingress proxies, node_id -> (host, port)."""
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    return {k: tuple(v) for k, v in ray_tpu.get(controller.proxy_addresses.remote()).items()}


def run(app: Application, *, name: str = "default", route_prefix: Optional[str] = "__from_deployment__", _blocking: bool = True) -> DeploymentHandle:
    """Deploy an application and return a handle (reference: api.py:413)."""
    from ray_tpu.serve._private.router import Router

    if not _started:
        start()
    # Deployment composition: Applications bound as init args become child
    # deployments, replaced by HandleMarkers the replicas materialize into
    # DeploymentHandles (reference: deployment graphs / DeploymentNode args).
    infos: dict[str, DeploymentInfo] = {}
    root_name = _build_app_tree(app, name, infos, root_route_prefix=route_prefix)
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    ray_tpu.get(controller.deploy.remote([pickle.dumps(i) for i in infos.values()]))
    router = Router.shared(controller)
    if _blocking:
        # Worker spawn is ~seconds per replica on an idle box but degrades
        # under CPU contention; scale the readiness budget with the app's
        # STARTUP replica count — autoscaled deployments start at
        # min_replicas, not num_replicas — and apply it to BOTH waits
        # below (overridable: RAY_TPU_SERVE_READY_TIMEOUT_S).
        def _startup_replicas(info) -> int:
            auto = getattr(info.config, "autoscaling", None)
            if auto is not None:
                return max(int(getattr(auto, "min_replicas", 1) or 1), 1)
            return max(int(getattr(info.config, "num_replicas", 1) or 1), 1)

        total_replicas = sum(_startup_replicas(i) for i in infos.values())
        try:
            timeout_s = float(os.environ["RAY_TPU_SERVE_READY_TIMEOUT_S"])
        except (KeyError, ValueError):  # unset, "" or malformed -> computed default
            timeout_s = 60.0 + 30.0 * total_replicas
        for dep_name, info in infos.items():
            if not router.wait_for_deployment(dep_name, timeout_s=timeout_s):
                raise TimeoutError(f"deployment {dep_name} did not become ready")
            # Block until the full target replica count for this version is
            # RUNNING and stale-version replicas are retired (reference:
            # serve.run waits for the application to reach RUNNING state).
            deadline = time.time() + timeout_s
            while time.time() < deadline:
                st = ray_tpu.get(controller.get_deployments.remote()).get(dep_name)
                if (
                    st is not None
                    and st["version"] == info.config.version
                    and st["num_replicas_current_version"] >= st["target"]
                    and st["num_replicas"] == st["num_replicas_current_version"]
                ):
                    break
                time.sleep(0.05)
            else:
                raise TimeoutError(
                    f"deployment {dep_name} did not reach target replica count"
                )
    return DeploymentHandle(root_name, router)


def _build_app_tree(
    app: Application,
    app_name: str,
    infos: dict,
    root_route_prefix="__from_deployment__",
) -> str:
    """Depth-first build of DeploymentInfos for an application graph.
    Children keep their own deployment names; only the root gets the
    requested route prefix."""
    dep = app.deployment
    existing = infos.get(dep.name)
    if existing is not None:
        # The same Application object bound in two places is a legitimate
        # diamond; two different bindings under one deployment name would
        # silently drop the second one's init args — refuse.
        if existing._source_app_id != id(app):
            raise ValueError(
                f"deployment name {dep.name!r} is bound more than once with "
                "different arguments; give each binding a distinct name via "
                ".options(name=...)"
            )
        return dep.name

    def subst(value):
        if isinstance(value, Application):
            return HandleMarker(_build_app_tree(value, app_name, infos))
        # Recurse into containers so e.g. Ingress.bind([A.bind(), B.bind()])
        # or {"a": A.bind()} also deploy their children.
        if isinstance(value, list):
            return [subst(v) for v in value]
        if isinstance(value, tuple):
            return tuple(subst(v) for v in value)
        if isinstance(value, dict):
            return {k: subst(v) for k, v in value.items()}
        return value

    init_args = tuple(subst(a) for a in app.init_args)
    init_kwargs = {k: subst(v) for k, v in app.init_kwargs.items()}
    prefix = (
        dep.route_prefix
        if root_route_prefix == "__from_deployment__"
        else root_route_prefix
    )
    import_spec = cloudpickle.dumps((dep._cls_or_fn, init_args, init_kwargs))
    cfg = dataclasses.replace(dep.config)
    if cfg.version is None:
        # Unversioned deployment: every change to code, init args, or
        # user_config is a new version → rolling update (reference:
        # serve/_private/version.py DeploymentVersion). JSON with sorted
        # keys gives an order-insensitive digest; cloudpickle covers
        # non-JSON user_configs (lambdas etc.).
        try:
            uc_bytes = json.dumps(cfg.user_config, sort_keys=True).encode()
        except (TypeError, ValueError):
            uc_bytes = cloudpickle.dumps(cfg.user_config)
        cfg.version = hashlib.md5(import_spec + uc_bytes).hexdigest()[:10]
    info = DeploymentInfo(
        name=dep.name,
        app_name=app_name,
        import_spec=import_spec,
        config=cfg,
        route_prefix=prefix,
    )
    info._source_app_id = id(app)
    infos[dep.name] = info
    for extra in getattr(app, "extras", ()):
        _build_app_tree(extra, app_name, infos)
    return dep.name


def get_deployment_handle(deployment_name: str) -> DeploymentHandle:
    from ray_tpu.serve._private.router import Router

    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    return DeploymentHandle(deployment_name, Router.shared(controller))


def status() -> dict:
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    return ray_tpu.get(controller.get_deployments.remote())


def delete(deployment_name: str):
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    ray_tpu.get(controller.delete_deployments.remote([deployment_name]))


def shutdown(timeout_s: float = 30.0):
    """Tear down the Serve control plane. Every controller call is BOUNDED:
    a wedged controller (hung reconcile, dead event loop) used to park this
    call forever on an unbounded ``get``; now it is force-killed after
    ``timeout_s`` and the typed ``ActorUnavailableError`` names it."""
    global _started
    from ray_tpu.exceptions import ActorUnavailableError
    from ray_tpu.serve._private.router import Router

    # Another driver (e.g. the CLI) may shut down a running Serve instance:
    # resolve the controller once; absent controller + not started = no-op.
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        if not _started:
            return
        controller = None
    wedged = None
    try:
        if controller is None:
            raise RuntimeError("no controller")
        ray_tpu.get(controller.shutdown_proxies.remote(), timeout=timeout_s)
        ray_tpu.get(controller.graceful_shutdown.remote(), timeout=timeout_s)
        time.sleep(0.2)
        ray_tpu.kill(controller)
    except TimeoutError as e:
        # The controller exists but cannot answer: force-kill it so its
        # replicas/proxies get reaped, then SURFACE the wedge (the old
        # swallow-everything path hid a stuck control plane entirely).
        wedged = ActorUnavailableError(
            f"serve controller {CONTROLLER_NAME!r} did not answer "
            f"graceful shutdown within {timeout_s}s ({type(e).__name__}); "
            "force-killed"
        )
        try:
            ray_tpu.kill(controller)
        except Exception:
            pass
    except Exception:
        pass
    Router.reset()
    _started = False
    if wedged is not None:
        raise wedged


class StreamingResponse:
    """Wrap a generator/iterable to stream the HTTP response body chunk by
    chunk (reference: serve streaming responses). Yielded bytes/str pass
    through; other values are JSON-encoded one per line (SSE-style payloads
    are just str chunks like "data: ...\n\n").

        @serve.deployment
        class Tokens:
            def __call__(self, request):
                return StreamingResponse(self.generate(), content_type="text/plain")
    """

    def __init__(
        self,
        iterator,
        content_type: str = "application/octet-stream",
        status: int = 200,
        headers: Optional[dict] = None,
        on_disconnect: Optional[Callable[[], None]] = None,
        resume: Optional[dict] = None,
    ):
        self.iterator = iterator
        self.content_type = content_type
        self.status = status
        self.headers = headers or {}
        # Called EXACTLY ONCE if the stream is torn down before completion
        # (client disconnect via cancel_stream, or the idle reaper). Lets
        # producers holding real resources — e.g. the LLM engine's decode
        # slot + KV blocks — release them immediately instead of waiting
        # for their generator to observe GeneratorExit on its next yield.
        self.on_disconnect = on_disconnect
        # Mid-stream migration descriptor ({"kind": "sse_tokens", "body":
        # {...}}): if the replica dies mid-stream, the proxy resubmits
        # body (+ resume_tokens it parsed from the chunks it already
        # forwarded) to another replica instead of dropping the stream.
        # None (the default) = the stream is not migratable.
        self.resume = resume


def ingress(asgi_app):
    """Mount an ASGI-3 application as a deployment's HTTP entry.

    Reference: python/ray/serve/api.py:100 `serve.ingress(fastapi_app)` —
    there it mounts FastAPI; here any raw ASGI-3 callable (fastapi/starlette
    are not in the image, and the seam is the ASGI protocol itself, not a
    particular framework). Apply UNDER @serve.deployment:

        @serve.deployment(route_prefix="/svc")
        @serve.ingress(my_asgi_app)
        class Svc:
            pass

    HTTP requests routed to the deployment drive ``my_asgi_app`` with the
    matched route prefix as ASGI root_path (starlette mount semantics);
    handle calls still reach methods defined on the class.
    """

    def decorator(cls):
        from ray_tpu.serve._private.asgi import run_asgi_request

        class ASGIWrapped(cls):
            def __call__(self, request):
                return run_asgi_request(asgi_app, request)

        ASGIWrapped.__name__ = cls.__name__
        ASGIWrapped.__qualname__ = getattr(cls, "__qualname__", cls.__name__)
        ASGIWrapped.__module__ = cls.__module__
        ASGIWrapped.__doc__ = cls.__doc__
        return ASGIWrapped

    return decorator
