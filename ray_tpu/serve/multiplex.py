"""Model multiplexing.

Analog of the reference's serve.multiplexed / get_multiplexed_model_id
(python/ray/serve/multiplex.py, api.py): one deployment serves many models;
each replica LRU-caches up to ``max_num_models_per_replica`` loaded models,
and the router pins a given model id to a stable replica so repeat traffic
hits a warm cache.

TPU idiom: model switching on a chip costs a weight upload (and possibly a
recompile), so affinity matters more than on GPU — the router uses a stable
hash of the model id over the replica list.
"""

from __future__ import annotations

import collections
import contextvars
import threading

_current_model_id: contextvars.ContextVar = contextvars.ContextVar(
    "serve_multiplexed_model_id", default=""
)

# Guards lazy wrapper creation. Module-level so deployment classes carrying
# the descriptor stay picklable (a closure-captured lock would be serialized
# by value with the class and locks cannot be pickled).
_CREATION_LOCK = threading.Lock()


def get_multiplexed_model_id() -> str:
    """Inside a request: the model id this request was routed with
    (reference: serve.get_multiplexed_model_id)."""
    return _current_model_id.get()


def _set_multiplexed_model_id(model_id: str):
    _current_model_id.set(model_id or "")


class _MultiplexWrapper:
    """Bound-method wrapper: LRU of loaded models keyed by model id."""

    def __init__(self, fn, instance, max_num_models_per_replica: int):
        self._fn = fn
        self._instance = instance
        self._max = max_num_models_per_replica
        self._models: "collections.OrderedDict[str, object]" = collections.OrderedDict()
        self._lock = threading.Lock()
        # Per-model-id load locks so concurrent misses for the same id load
        # once; different ids still load in parallel.
        self._load_locks: dict[str, threading.Lock] = {}

    def load_model(self, model_id: str):
        with self._lock:
            if model_id in self._models:
                self._models.move_to_end(model_id)
                return self._models[model_id]
            load_lock = self._load_locks.setdefault(model_id, threading.Lock())
        with load_lock:
            with self._lock:
                if model_id in self._models:  # loaded while we waited
                    self._models.move_to_end(model_id)
                    return self._models[model_id]
            args = (self._instance, model_id) if self._instance is not None else (model_id,)
            model = self._fn(*args)
            with self._lock:
                self._models[model_id] = model
                self._models.move_to_end(model_id)
                # Evicted models are dropped from the cache; their device
                # memory is released when the last in-flight reference dies
                # (never call __del__ on a model a request may still hold).
                while len(self._models) > self._max:
                    evicted_id, _ = self._models.popitem(last=False)
                    self._load_locks.pop(evicted_id, None)
        return model

    __call__ = load_model

    @property
    def loaded_model_ids(self) -> list:
        with self._lock:
            return list(self._models)


def multiplexed(fn=None, *, max_num_models_per_replica: int = 3):
    """Decorator for a model-loader method: ``model = await/call
    self.get_model(model_id)`` with per-replica LRU caching."""

    def wrap(loader):
        class _Descriptor:
            def __set_name__(self, owner, name):
                self._name = name

            def __get__(self, instance, owner=None):
                if instance is None:
                    return loader
                cache_attr = f"__multiplex_{loader.__name__}"
                wrapper = getattr(instance, cache_attr, None)
                if wrapper is None:
                    # Serialized creation: concurrent first requests must
                    # share ONE wrapper/cache, or models load twice.
                    from ray_tpu.serve import multiplex as _mx

                    with _mx._CREATION_LOCK:
                        wrapper = getattr(instance, cache_attr, None)
                        if wrapper is None:
                            wrapper = _MultiplexWrapper(
                                loader, instance, max_num_models_per_replica
                            )
                            setattr(instance, cache_attr, wrapper)
                return wrapper

        return _Descriptor()

    if fn is not None:
        return wrap(fn)
    return wrap
