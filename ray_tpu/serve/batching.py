"""@serve.batch — transparent request batching.

Reference: python/ray/serve/batching.py — queued requests are flushed to the
wrapped method as a list when the batch fills or the wait timeout expires.
The TPU angle: batching is how single-request traffic reaches MXU-efficient
batch sizes; pair with a jit-compiled predictor padded to fixed batch shapes.
"""

from __future__ import annotations

import functools
import threading
import time
from concurrent.futures import Future
from typing import Callable, List, Optional


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int, batch_wait_timeout_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._wait = batch_wait_timeout_s
        self._items: List[tuple] = []
        self._lock = threading.Lock()
        self._flusher: Optional[threading.Timer] = None

    def submit(self, instance, item) -> Future:
        fut: Future = Future()
        flush_now = False
        with self._lock:
            self._items.append((instance, item, fut))
            if len(self._items) >= self._max:
                flush_now = True
            elif self._flusher is None:
                self._flusher = threading.Timer(self._wait, self._flush)
                self._flusher.daemon = True
                self._flusher.start()
        if flush_now:
            self._flush()
        return fut

    def _flush(self):
        with self._lock:
            if self._flusher is not None:
                self._flusher.cancel()
                self._flusher = None
            items, self._items = self._items, []
        if not items:
            return
        instance = items[0][0]
        batch = [item for _, item, _ in items]
        futures = [fut for _, _, fut in items]
        try:
            if instance is not None:
                results = self._fn(instance, batch)
            else:
                results = self._fn(batch)
            if len(results) != len(batch):
                raise ValueError(
                    f"@serve.batch function returned {len(results)} results "
                    f"for a batch of {len(batch)}"
                )
            for fut, res in zip(futures, results):
                fut.set_result(res)
        except Exception as e:
            for fut in futures:
                if not fut.done():
                    fut.set_exception(e)


def batch(_fn=None, *, max_batch_size: int = 8, batch_wait_timeout_s: float = 0.01):
    """Decorator: calls with single items are batched into list calls."""

    def wrap(fn):
        # One queue per bound instance (keyed by id) — a single shared queue
        # would flush instance B's items through instance A's method.
        queues: dict = {}
        queues_lock = threading.Lock()

        def queue_for(instance) -> _BatchQueue:
            key = id(instance)
            with queues_lock:
                q = queues.get(key)
                if q is None:
                    q = _BatchQueue(fn, max_batch_size, batch_wait_timeout_s)
                    queues[key] = q
                return q

        @functools.wraps(fn)
        def wrapper(*args):
            if len(args) == 2:  # bound method: (self, item)
                instance, item = args
            else:
                instance, item = None, args[0]
            return queue_for(instance).submit(instance, item).result(timeout=60)

        wrapper._batch_queues = queues
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
