"""Experiment-directory syncing (analog of reference python/ray/tune/syncer.py).

The reference syncs trial/experiment state to cloud storage or shared NFS so
a new head node can `Tuner.restore` an interrupted sweep. Here:
- local / NFS / file:// targets sync with a real directory copy;
- cloud URI schemes (s3:// gs:// ...) are gated — no cloud SDKs in this
  image — with the same Syncer plugin seam the reference exposes, so a
  deployment with boto/gcsfs installs a custom Syncer and keeps the API.
"""

from __future__ import annotations

import os
import shutil
import time
from dataclasses import dataclass


class Syncer:
    """Plugin seam (reference: tune/syncer.py Syncer)."""

    def sync_up(self, local_dir: str, remote_dir: str) -> bool:
        raise NotImplementedError

    def sync_down(self, remote_dir: str, local_dir: str) -> bool:
        raise NotImplementedError


class _LocalDirSyncer(Syncer):
    """rsync-style copy for filesystem targets (NFS mounts, file:// URIs)."""

    def _copy(self, src: str, dst: str) -> bool:
        if not os.path.isdir(src):
            return False
        os.makedirs(dst, exist_ok=True)
        for root, _dirs, files in os.walk(src):
            rel = os.path.relpath(root, src)
            out = os.path.join(dst, rel) if rel != "." else dst
            os.makedirs(out, exist_ok=True)
            for fname in files:
                s = os.path.join(root, fname)
                d = os.path.join(out, fname)
                # Skip files whose size+mtime are unchanged (rsync heuristic).
                try:
                    if os.path.exists(d):
                        ss, ds = os.stat(s), os.stat(d)
                        if ss.st_size == ds.st_size and ss.st_mtime <= ds.st_mtime:
                            continue
                    shutil.copy2(s, d)
                except OSError:
                    pass
        return True

    def sync_up(self, local_dir: str, remote_dir: str) -> bool:
        return self._copy(local_dir, _strip_file_scheme(remote_dir))

    def sync_down(self, remote_dir: str, local_dir: str) -> bool:
        return self._copy(_strip_file_scheme(remote_dir), local_dir)


def _strip_file_scheme(uri: str) -> str:
    return uri[len("file://"):] if uri.startswith("file://") else uri


_CLOUD_SCHEMES = ("s3://", "gs://", "gcs://", "az://", "abfs://", "hdfs://")


def get_syncer(upload_dir: str | None, custom: Syncer | None = None) -> Syncer | None:
    if custom is not None:
        return custom
    if not upload_dir:
        return None
    if upload_dir.startswith(_CLOUD_SCHEMES):
        raise ValueError(
            f"cloud sync target {upload_dir!r} needs a cloud SDK that is not "
            "in this image; pass SyncConfig(syncer=YourSyncer()) backed by "
            "your storage client (reference: custom Syncer plugin)"
        )
    return _LocalDirSyncer()


@dataclass
class SyncConfig:
    """Analog of reference tune/syncer.py SyncConfig."""

    upload_dir: str | None = None
    syncer: Syncer | None = None
    sync_period_s: float = 300.0


class SyncManager:
    """Throttled sync_up driver used by the Tune controller."""

    def __init__(self, config: SyncConfig, experiment_dir: str, experiment_name: str):
        self.config = config
        self.experiment_dir = experiment_dir
        self.remote_dir = (
            os.path.join(config.upload_dir, experiment_name) if config.upload_dir else None
        )
        self._syncer = get_syncer(config.upload_dir, config.syncer)
        self._last = 0.0

    @property
    def enabled(self) -> bool:
        return self._syncer is not None and self.remote_dir is not None

    def maybe_sync_up(self, force: bool = False) -> bool:
        if not self.enabled:
            return False
        now = time.monotonic()
        if not force and now - self._last < self.config.sync_period_s:
            return False
        self._last = now
        return self._syncer.sync_up(self.experiment_dir, self.remote_dir)
