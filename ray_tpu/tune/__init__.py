"""ray_tpu.tune — the experiment runner (analog of python/ray/tune).

Tuner/tune.run drive trials-as-actors through a TuneController with pluggable
searchers (grid/random/model-based) and schedulers (FIFO/ASHA/median/PBT);
every other library's .fit() can route through it like the reference
(base_trainer.py:559)."""

from ray_tpu.tune.sample import (  # noqa: F401
    choice,
    grid_search,
    lograndint,
    loguniform,
    qloguniform,
    qrandint,
    quniform,
    randint,
    randn,
    sample_from,
    uniform,
)
from ray_tpu.tune.trainable import (  # noqa: F401
    FunctionTrainable,
    Trainable,
    get_checkpoint,
    report,
)
from ray_tpu.air.config import CheckpointConfig, FailureConfig, RunConfig  # noqa: F401
from ray_tpu.tune.tune_config import TuneConfig  # noqa: F401
from ray_tpu.tune.analysis import ExperimentAnalysis  # noqa: F401
from ray_tpu.tune.result_grid import ResultGrid  # noqa: F401
from ray_tpu.tune.tuner import Tuner, run  # noqa: F401
