"""Synchronous HyperBand (analog of reference python/ray/tune/schedulers/
hyperband.py HyperBandScheduler).

Trials fill brackets; each bracket runs successive-halving rounds: all member
trials run to the current milestone (PAUSE as they arrive), then the top
1/eta continue into the next rung and the rest STOP. Unlike ASHA (async,
never pauses), a rung only halves when every live member has reported — the
synchronous algorithm of Li et al. 2016.
"""

from __future__ import annotations

import math

from ray_tpu.tune.experiment.trial import PAUSED, PENDING, RUNNING
from ray_tpu.tune.schedulers.trial_scheduler import (
    CONTINUE,
    PAUSE,
    STOP,
    TrialScheduler,
)


class _SyncBracket:
    def __init__(self, n0: int, r0: int, eta: float, max_t: int):
        self.eta = eta
        self.max_t = max_t
        self.capacity = n0
        self.trials: list = []
        self.milestone = min(r0, max_t)
        self.cum_iter = self.milestone
        self.results: dict[str, float] = {}  # trial_id -> metric at milestone
        self.dropped: set[str] = set()

    @property
    def full(self) -> bool:
        return len(self.trials) >= self.capacity

    def add(self, trial):
        self.trials.append(trial)

    def live(self) -> list:
        return [t for t in self.trials if t.trial_id not in self.dropped]

    def on_result(self, trial, cur_iter: int, metric: float) -> str:
        if cur_iter < self.milestone or trial.trial_id in self.results:
            return CONTINUE
        self.results[trial.trial_id] = metric
        if self.milestone >= self.max_t:
            return STOP  # ran the full budget
        return PAUSE

    def try_halve(self) -> tuple[list, list]:
        """If every live member has reported at the milestone, keep the top
        1/eta; returns (promoted_trials, stopped_trials), or ([], []) if the
        rung isn't complete yet."""
        live = self.live()
        if not live or any(t.trial_id not in self.results for t in live):
            return [], []
        ranked = sorted(live, key=lambda t: self.results[t.trial_id], reverse=True)
        keep = max(1, int(len(ranked) / self.eta))
        promoted, stopped = ranked[:keep], ranked[keep:]
        for t in stopped:
            self.dropped.add(t.trial_id)
        self.milestone = min(int(self.milestone * self.eta), self.max_t)
        self.results = {}
        return promoted, stopped


class HyperBandScheduler(TrialScheduler):
    def __init__(
        self,
        metric: str | None = None,
        mode: str = "max",
        max_t: int = 81,
        reduction_factor: float = 3,
        time_attr: str = "training_iteration",
    ):
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.eta = reduction_factor
        self.time_attr = time_attr
        self._brackets: list[_SyncBracket] = []
        self._trial_bracket: dict[str, _SyncBracket] = {}
        # Bracket shapes cycle s = s_max..0 (reference: HyperBandScheduler
        # uses the same (n, r) schedule from the paper).
        self._s_max = int(math.log(max_t, self.eta))
        self._next_s = self._s_max

    def _new_bracket(self) -> _SyncBracket:
        s = self._next_s
        self._next_s = self._next_s - 1 if self._next_s > 0 else self._s_max
        n0 = int(math.ceil((self._s_max + 1) * self.eta**s / (s + 1)))
        r0 = max(1, int(self.max_t * self.eta**-s))
        b = _SyncBracket(n0, r0, self.eta, self.max_t)
        self._brackets.append(b)
        return b

    def on_trial_add(self, controller, trial):
        b = next((x for x in self._brackets if not x.full), None) or self._new_bracket()
        b.add(trial)
        self._trial_bracket[trial.trial_id] = b

    def _signed(self, result: dict) -> float | None:
        v = result.get(self.metric) if self.metric else None
        if v is None:
            return None
        return float(v) if self.mode == "max" else -float(v)

    def on_trial_result(self, controller, trial, result):
        b = self._trial_bracket.get(trial.trial_id)
        metric = self._signed(result)
        if b is None or metric is None:
            return CONTINUE
        decision = b.on_result(trial, int(result.get(self.time_attr, 0)), metric)
        if decision == PAUSE:
            # A pause may complete the rung: losers stop, winners resume via
            # choose_trial_to_run picking PAUSED trials.
            _, stopped = b.try_halve()
            for t in stopped:
                if t.trial_id == trial.trial_id:
                    decision = STOP
                elif t.status in (RUNNING, PAUSED, PENDING):
                    controller.stop_trial(t)
        return decision

    def on_trial_complete(self, controller, trial, result):
        b = self._trial_bracket.get(trial.trial_id)
        if b is not None:
            b.dropped.add(trial.trial_id)
            _, stopped = b.try_halve()
            for t in stopped:
                controller.stop_trial(t)

    def on_trial_error(self, controller, trial):
        self.on_trial_complete(controller, trial, {})

    def on_no_available_trials(self, controller):
        """Deadlock release: members that can no longer report (terminated
        outside the bracket's bookkeeping) must not hold a rung open — drop
        them and finalize the halving so PAUSED winners become resumable."""
        for b in self._brackets:
            for t in b.live():
                if t.status not in (RUNNING, PAUSED, PENDING):
                    b.dropped.add(t.trial_id)
            _, stopped = b.try_halve()
            for t in stopped:
                controller.stop_trial(t)

    def choose_trial_to_run(self, controller):
        """PENDING trials fill brackets; a PAUSED trial is resumable ONLY
        after its rung halved (its id left bracket.results) — resuming
        earlier would run it past the milestone while rung-mates are still
        below it, breaking the synchronous halving invariant."""
        from ray_tpu.tune.experiment.trial import PAUSED, PENDING

        for t in controller.trials:
            if t.status == PENDING:
                return t
        for t in controller.trials:
            if t.status != PAUSED:
                continue
            b = self._trial_bracket.get(t.trial_id)
            if b is None:
                return t
            if t.trial_id not in b.dropped and t.trial_id not in b.results:
                return t
        return None
