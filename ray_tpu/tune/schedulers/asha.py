"""Asynchronous Successive Halving (analog of reference
python/ray/tune/schedulers/async_hyperband.py ASHAScheduler).

Rungs at reduction_factor^k * grace_period; a trial reaching a rung is stopped
unless its metric is in the top 1/reduction_factor of recorded values at that
rung. Fully asynchronous: decisions use whatever has been recorded so far.
"""

from __future__ import annotations

import math

from ray_tpu.tune.schedulers.trial_scheduler import CONTINUE, STOP, TrialScheduler


class _Bracket:
    def __init__(self, min_t: int, max_t: int, rf: float, stop_last: bool):
        self.rf = rf
        self.rungs: list[tuple[int, dict]] = []  # (milestone, {trial_id: metric})
        t = max_t
        while t > min_t:
            self.rungs.append((t, {}))
            t = int(t / rf)
        self.rungs.append((min_t, {}))
        self.rungs = sorted(self.rungs)  # ascending milestones
        self.stop_last = stop_last

    def on_result(self, trial_id: str, cur_iter: int, metric: float) -> str:
        decision = CONTINUE
        for milestone, recorded in self.rungs:
            if cur_iter < milestone or trial_id in recorded:
                continue
            recorded[trial_id] = metric
            values = sorted(recorded.values())
            if len(values) >= self.rf:
                cutoff_idx = int(math.ceil(len(values) * (1 - 1 / self.rf))) - 1
                cutoff = values[max(cutoff_idx, 0)]
                if metric < cutoff:
                    decision = STOP
            break
        return decision


class ASHAScheduler(TrialScheduler):
    def __init__(
        self,
        metric: str | None = None,
        mode: str = "max",
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: float = 4,
        time_attr: str = "training_iteration",
    ):
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.time_attr = time_attr
        self._bracket = _Bracket(grace_period, max_t, reduction_factor, True)

    def on_trial_result(self, controller, trial, result):
        if self.metric is None or self.metric not in result:
            return CONTINUE
        cur = result.get(self.time_attr, 0)
        if cur >= self.max_t:
            return STOP
        v = float(result[self.metric])
        if self.mode == "min":
            v = -v
        return self._bracket.on_result(trial.trial_id, int(cur), v)
