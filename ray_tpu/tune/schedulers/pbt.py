"""Population Based Training (analog of reference python/ray/tune/schedulers/
pbt.py PopulationBasedTraining).

Every ``perturbation_interval`` iterations a trial in the bottom quantile
exploits a top-quantile trial: it clones that trial's latest checkpoint and
config, then explores by perturbing hyperparameters (×1.2 / ×0.8 for numeric,
resample for domains). The controller applies the exploit by restarting the
trial actor with the new config + donor checkpoint.
"""

from __future__ import annotations

import random

from ray_tpu.tune import sample as s
from ray_tpu.tune.schedulers.trial_scheduler import CONTINUE, TrialScheduler

EXPLOIT = "EXPLOIT"  # extra decision understood by the controller


class PopulationBasedTraining(TrialScheduler):
    def __init__(
        self,
        metric: str | None = None,
        mode: str = "max",
        perturbation_interval: int = 5,
        hyperparam_mutations: dict | None = None,
        quantile_fraction: float = 0.25,
        resample_probability: float = 0.25,
        seed: int | None = None,
        time_attr: str = "training_iteration",
    ):
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self.rng = random.Random(seed)
        self.time_attr = time_attr
        self._last_perturb: dict[str, int] = {}
        # set by on_trial_result when EXPLOIT is returned; consumed by controller
        self.pending_exploit: dict[str, tuple] = {}  # trial_id -> (donor_trial, new_config)

    def _signed(self, trial) -> float | None:
        v = trial.last_result.get(self.metric) if self.metric else None
        if v is None:
            return None
        return float(v) if self.mode == "max" else -float(v)

    def explore(self, config: dict) -> dict:
        new = dict(config)
        for key, spec in self.mutations.items():
            cur = new.get(key)
            if self.rng.random() < self.resample_p or cur is None:
                if isinstance(spec, s.Domain):
                    new[key] = spec.sample(self.rng)
                elif isinstance(spec, list):
                    new[key] = self.rng.choice(spec)
                elif callable(spec):
                    new[key] = spec()
                continue
            if isinstance(cur, (int, float)) and not isinstance(cur, bool):
                factor = 1.2 if self.rng.random() > 0.5 else 0.8
                new[key] = type(cur)(cur * factor) if isinstance(cur, float) else max(1, int(cur * factor))
            elif isinstance(spec, list) and cur in spec:
                i = spec.index(cur)
                new[key] = spec[max(0, min(len(spec) - 1, i + self.rng.choice([-1, 1])))]
        return new

    def on_trial_result(self, controller, trial, result):
        t = int(result.get(self.time_attr, 0))
        if self.metric is None or self.metric not in result:
            return CONTINUE
        if t - self._last_perturb.get(trial.trial_id, 0) < self.interval:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t

        scored = [(tr, sv) for tr in controller.trials if (sv := self._signed(tr)) is not None]
        if len(scored) < 2:
            return CONTINUE
        scored.sort(key=lambda x: x[1])
        n_q = max(1, int(len(scored) * self.quantile))
        bottom = [tr for tr, _ in scored[:n_q]]
        top = [tr for tr, _ in scored[-n_q:]]
        if trial not in bottom or trial in top:
            return CONTINUE
        donor = self.rng.choice(top)
        if donor.trial_id == trial.trial_id or donor.checkpoint is None:
            return CONTINUE
        new_config = self.explore(donor.config)
        self.pending_exploit[trial.trial_id] = (donor, new_config)
        return EXPLOIT
