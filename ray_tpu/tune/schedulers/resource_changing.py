"""ResourceChangingScheduler — resize trials mid-run.

Analog of the reference's resource_changing_scheduler.py:590: wraps a base
scheduler; after each result a ``resources_allocation_function`` may
propose a new resource dict for the trial. A change pauses the trial
(checkpoint via the controller's normal PAUSE path), stores the new
resources on the Trial, and the restart re-places the actor with them —
the Trainable sees the update through ``self.trial_resources``.
"""

from __future__ import annotations

from typing import Callable, Optional

from ray_tpu.tune.schedulers.trial_scheduler import CONTINUE, PAUSE, FIFOScheduler, TrialScheduler


class DistributeResources:
    """Default allocation policy (reference: DistributeResources): split
    the cluster's CPUs evenly among unfinished trials, each trial at least
    its original request."""

    def __call__(self, controller, trial, result, scheduler) -> Optional[dict]:
        import ray_tpu
        from ray_tpu.tune.experiment.trial import PAUSED, PENDING, RUNNING

        try:
            total = int(ray_tpu.cluster_resources().get("CPU", 1))
        except Exception:
            return None
        live = [t for t in controller.trials if t.status in (RUNNING, PENDING, PAUSED)]
        if not live:
            return None
        base = int(controller.resources_per_trial.get("CPU", 1))
        share = max(base, total // len(live))
        current = dict(trial.resources or controller.resources_per_trial)
        if int(current.get("CPU", 1)) == share:
            return None
        current["CPU"] = share
        return current


class ResourceChangingScheduler(TrialScheduler):
    def __init__(self, base_scheduler: Optional[TrialScheduler] = None,
                 resources_allocation_function: Optional[Callable] = None):
        self.base = base_scheduler or FIFOScheduler()
        self.fn = resources_allocation_function or DistributeResources()
        self.reallocated: dict[str, int] = {}  # trial_id -> resize count

    def set_search_properties(self, metric, mode) -> bool:
        super().set_search_properties(metric, mode)
        return self.base.set_search_properties(metric, mode)

    def on_trial_add(self, controller, trial) -> None:
        self.base.on_trial_add(controller, trial)

    def on_trial_result(self, controller, trial, result: dict) -> str:
        decision = self.base.on_trial_result(controller, trial, result)
        if decision != CONTINUE:
            return decision
        new = self.fn(controller, trial, result, self)
        old = dict(trial.resources or controller.resources_per_trial)
        if new and dict(new) != old:
            trial.resources = dict(new)
            self.reallocated[trial.trial_id] = self.reallocated.get(trial.trial_id, 0) + 1
            if trial.iteration > 0 and trial.checkpoint is None:
                # The PAUSE below checkpoints via Trainable.save(); a
                # trainable without save_checkpoint yields None and the
                # restart begins from iteration 0 — resize still happens,
                # but pre-resize progress is redone. Say so loudly.
                import logging

                logging.getLogger(__name__).warning(
                    "ResourceChangingScheduler: trial %s has no checkpoint; "
                    "resizing restarts it from iteration 0 (implement "
                    "save_checkpoint to carry progress across resizes)",
                    trial.trial_id,
                )
            # PAUSE drives the controller's checkpoint-then-stop path; the
            # restart re-places the actor under the new resources.
            return PAUSE
        return decision

    def on_trial_complete(self, controller, trial, result: dict) -> None:
        self.base.on_trial_complete(controller, trial, result)

    def on_trial_error(self, controller, trial) -> None:
        self.base.on_trial_error(controller, trial)

    def choose_trial_to_run(self, controller):
        return self.base.choose_trial_to_run(controller)

    def on_no_available_trials(self, controller) -> None:
        self.base.on_no_available_trials(controller)
