"""PB2 — Population Based Bandits (GP-guided PBT explore step).

Reference: python/ray/tune/schedulers/pb2.py (+pb2_utils.py): PBT's exploit
keeps copying top-quantile checkpoints, but explore replaces the random
×1.2/×0.8 perturbation with a GP-UCB bandit over the hyperparameter box:
fit a GP on (normalized hyperparams → reward improvement per interval)
observations, pick the candidate maximizing mu + kappa*sigma. The reference
wraps GPy; this build fits sklearn's GaussianProcessRegressor (in-image).
Only numeric bounded hyperparameters participate (same constraint as the
reference — PB2 requires a continuous box).
"""

from __future__ import annotations

import math

import numpy as np

from ray_tpu.tune import sample as s
from ray_tpu.tune.schedulers.pbt import PopulationBasedTraining


def _bounds_of(spec) -> tuple[float, float, bool] | None:
    """(lower, upper, log) for a numeric domain / [lo, hi] list, else None."""
    if isinstance(spec, (s.Float, s.Integer)):
        return float(spec.lower), float(spec.upper), bool(getattr(spec, "log", False))
    if isinstance(spec, list) and len(spec) == 2 and all(
        isinstance(v, (int, float)) and not isinstance(v, bool) for v in spec
    ):
        return float(min(spec)), float(max(spec)), False
    return None


class PB2(PopulationBasedTraining):
    def __init__(self, *args, ucb_kappa: float = 2.0, candidates: int = 256, **kwargs):
        super().__init__(*args, **kwargs)
        self.ucb_kappa = ucb_kappa
        self.n_candidates = candidates
        self._box: dict[str, tuple[float, float, bool]] = {
            k: b for k, v in self.mutations.items() if (b := _bounds_of(v)) is not None
        }
        # Observations: normalized hyperparam vector -> reward delta over the
        # last perturbation interval.
        self._obs_X: list[list[float]] = []
        self._obs_y: list[float] = []
        self._last_metric: dict[str, float] = {}

    def _to_unit(self, config: dict) -> list[float]:
        x = []
        for k, (lo, hi, log) in self._box.items():
            v = float(config.get(k, lo))
            if log:
                u = (math.log(max(v, 1e-12)) - math.log(lo)) / (math.log(hi) - math.log(lo))
            else:
                u = (v - lo) / (hi - lo or 1.0)
            x.append(min(max(u, 0.0), 1.0))
        return x

    def _from_unit(self, x: np.ndarray, template: dict) -> dict:
        new = dict(template)
        for (k, (lo, hi, log)), u in zip(self._box.items(), x):
            if log:
                v = math.exp(math.log(lo) + float(u) * (math.log(hi) - math.log(lo)))
            else:
                v = lo + float(u) * (hi - lo)
            v = min(max(v, lo), hi)  # clamp to the declared box, nothing else
            spec = self.mutations[k]
            if isinstance(spec, s.Integer) or (
                isinstance(new.get(k), int) and not isinstance(new.get(k), bool)
            ):
                v = min(max(int(round(v)), int(math.ceil(lo))), int(math.floor(hi)))
            new[k] = v
        return new

    def on_trial_result(self, controller, trial, result):
        # Record reward deltas for the GP before PBT's exploit logic runs.
        if self.metric and self.metric in result:
            cur = float(result[self.metric]) * (1.0 if self.mode == "max" else -1.0)
            prev = self._last_metric.get(trial.trial_id)
            if prev is not None:
                self._obs_X.append(self._to_unit(trial.config))
                self._obs_y.append(cur - prev)
            self._last_metric[trial.trial_id] = cur
        return super().on_trial_result(controller, trial, result)

    def explore(self, config: dict) -> dict:
        if not self._box:
            return super().explore(config)
        new = super().explore(config)  # handles categorical/list mutations
        if len(self._obs_X) < 4:
            # Not enough observations for a GP: random point in the box.
            u = np.random.default_rng(self.rng.randint(0, 1 << 31)).random(len(self._box))
            return self._from_unit(u, new)
        from sklearn.gaussian_process import GaussianProcessRegressor
        from sklearn.gaussian_process.kernels import ConstantKernel, Matern

        X = np.asarray(self._obs_X[-256:])  # bounded window, recent behaviour
        y = np.asarray(self._obs_y[-256:])
        y = (y - y.mean()) / (y.std() + 1e-9)
        gp = GaussianProcessRegressor(
            kernel=ConstantKernel(1.0) * Matern(nu=2.5),
            alpha=1e-4,
            random_state=self.rng.randint(0, 1 << 31),
        )
        gp.fit(X, y)
        rng = np.random.default_rng(self.rng.randint(0, 1 << 31))
        cand = rng.random((self.n_candidates, len(self._box)))
        mu, sigma = gp.predict(cand, return_std=True)
        best = cand[int(np.argmax(mu + self.ucb_kappa * sigma))]
        return self._from_unit(best, new)
