from ray_tpu.tune.schedulers.trial_scheduler import (  # noqa: F401
    CONTINUE,
    PAUSE,
    STOP,
    FIFOScheduler,
    TrialScheduler,
)
from ray_tpu.tune.schedulers.asha import ASHAScheduler  # noqa: F401
from ray_tpu.tune.schedulers.hyperband import HyperBandScheduler  # noqa: F401
from ray_tpu.tune.schedulers.median_stopping import MedianStoppingRule  # noqa: F401
from ray_tpu.tune.schedulers.pb2 import PB2  # noqa: F401
from ray_tpu.tune.schedulers.pbt import PopulationBasedTraining  # noqa: F401
from ray_tpu.tune.schedulers.resource_changing import (  # noqa: F401
    DistributeResources,
    ResourceChangingScheduler,
)

AsyncHyperBandScheduler = ASHAScheduler
# BOHB pairs the TuneBOHB searcher with synchronous HyperBand rungs
# (reference: hb_bohb.py) — our sync HyperBand already pauses at
# milestones, which is the behavior HyperBandForBOHB adds there.
HyperBandForBOHB = HyperBandScheduler
