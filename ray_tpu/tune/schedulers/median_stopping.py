"""Median stopping rule (analog of reference python/ray/tune/schedulers/
median_stopping_rule.py): stop a trial whose best result so far is worse than
the median of other trials' running averages at the same point in time."""

from __future__ import annotations

import statistics

from ray_tpu.tune.schedulers.trial_scheduler import CONTINUE, STOP, TrialScheduler


class MedianStoppingRule(TrialScheduler):
    def __init__(
        self,
        metric: str | None = None,
        mode: str = "max",
        grace_period: int = 1,
        min_samples_required: int = 3,
        time_attr: str = "training_iteration",
    ):
        self.metric = metric
        self.mode = mode
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self.time_attr = time_attr
        self._histories: dict[str, list[float]] = {}

    def _signed(self, v: float) -> float:
        return float(v) if self.mode == "max" else -float(v)

    def on_trial_result(self, controller, trial, result):
        if self.metric is None or self.metric not in result:
            return CONTINUE
        hist = self._histories.setdefault(trial.trial_id, [])
        hist.append(self._signed(result[self.metric]))
        t = int(result.get(self.time_attr, 0))
        if t < self.grace_period:
            return CONTINUE
        other_avgs = [
            statistics.fmean(h[:t] or h)
            for tid, h in self._histories.items()
            if tid != trial.trial_id and h
        ]
        if len(other_avgs) < self.min_samples:
            return CONTINUE
        if max(hist) < statistics.median(other_avgs):
            return STOP
        return CONTINUE
