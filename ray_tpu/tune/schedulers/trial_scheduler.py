"""TrialScheduler protocol (analog of reference python/ray/tune/schedulers/
trial_scheduler.py — decisions on each result: CONTINUE / PAUSE / STOP)."""

from __future__ import annotations

CONTINUE = "CONTINUE"
PAUSE = "PAUSE"
STOP = "STOP"


class TrialScheduler:
    def set_search_properties(self, metric: str | None, mode: str | None) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True

    metric: str | None = None
    mode: str = "max"

    def on_trial_add(self, controller, trial) -> None:
        pass

    def on_trial_result(self, controller, trial, result: dict) -> str:
        return CONTINUE

    def on_trial_complete(self, controller, trial, result: dict) -> None:
        pass

    def on_trial_error(self, controller, trial) -> None:
        pass

    def choose_trial_to_run(self, controller):
        """Pick the next PENDING/PAUSED trial to (re)start, or None."""
        from ray_tpu.tune.experiment.trial import PAUSED, PENDING

        for t in controller.trials:
            if t.status == PENDING:
                return t
        for t in controller.trials:
            if t.status == PAUSED:
                return t
        return None

    def on_no_available_trials(self, controller) -> None:
        """Called when the experiment would otherwise deadlock: nothing is
        running and choose_trial_to_run returned None while gated trials
        remain. Schedulers holding synchronization state (e.g. sync
        HyperBand rungs) release their gates consistently here — the
        controller re-asks choose_trial_to_run afterwards instead of
        force-starting a gated trial past its milestone."""


class FIFOScheduler(TrialScheduler):
    pass
