"""Per-trial result loggers (analog of reference python/ray/tune/logger/:
CSVLoggerCallback, JsonLoggerCallback, TBXLoggerCallback).

The controller drives a LoggerManager: every trial gets
``<experiment_dir>/<trial_id>/{progress.csv, result.json, events.out...}``
so sweeps are inspectable with pandas/jq/tensorboard exactly like the
reference's trial dirs.
"""

from __future__ import annotations

import csv
import json
import logging
import os
from typing import Optional

logger = logging.getLogger(__name__)


class Logger:
    def on_result(self, trial, result: dict) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


def _scalars(result: dict) -> dict:
    return {
        k: v
        for k, v in result.items()
        if isinstance(v, (int, float)) and not isinstance(v, bool)
    }


class CSVLogger(Logger):
    """progress.csv — one row per reported result; the header is the union
    of keys seen on the FIRST result (later novel keys are dropped, same as
    the reference's CSV logger)."""

    def __init__(self, trial_dir: str):
        self.path = os.path.join(trial_dir, "progress.csv")
        self._file = None
        self._writer: Optional[csv.DictWriter] = None

    def on_result(self, trial, result):
        row = _scalars(result)
        if self._writer is None:
            self._file = open(self.path, "w", newline="")
            self._writer = csv.DictWriter(self._file, fieldnames=list(row))
            self._writer.writeheader()
        self._writer.writerow({k: row.get(k, "") for k in self._writer.fieldnames})
        self._file.flush()

    def close(self):
        if self._file is not None:
            self._file.close()


class JsonLogger(Logger):
    """result.json — one JSON object per line, full (serializable) result."""

    def __init__(self, trial_dir: str):
        self.path = os.path.join(trial_dir, "result.json")
        self._file = open(self.path, "a")

    def on_result(self, trial, result):
        safe = {}
        for k, v in result.items():
            try:
                json.dumps(v)
                safe[k] = v
            except (TypeError, ValueError):
                safe[k] = repr(v)
        self._file.write(json.dumps(safe) + "\n")
        self._file.flush()

    def close(self):
        self._file.close()


class TBXLogger(Logger):
    """TensorBoard event files via tensorboardX (in this image)."""

    def __init__(self, trial_dir: str):
        from tensorboardX import SummaryWriter

        self._writer = SummaryWriter(logdir=trial_dir)

    def on_result(self, trial, result):
        step = int(result.get("training_iteration", 0))
        for k, v in _scalars(result).items():
            if k == "training_iteration":
                continue
            try:
                self._writer.add_scalar(k, float(v), global_step=step)
            except Exception:
                pass

    def close(self):
        try:
            self._writer.close()
        except Exception:
            pass


DEFAULT_LOGGERS = (CSVLogger, JsonLogger, TBXLogger)


class LoggerManager:
    def __init__(self, experiment_dir: str, logger_classes=DEFAULT_LOGGERS):
        self.experiment_dir = experiment_dir
        self.logger_classes = logger_classes
        self._per_trial: dict[str, list[Logger]] = {}

    def _loggers_for(self, trial) -> list[Logger]:
        existing = self._per_trial.get(trial.trial_id)
        if existing is not None:
            return existing
        trial_dir = os.path.join(self.experiment_dir, trial.trial_id)
        os.makedirs(trial_dir, exist_ok=True)
        with open(os.path.join(trial_dir, "params.json"), "w") as f:
            try:
                json.dump(trial.config, f, default=repr)
            except Exception:
                pass
        loggers = []
        for cls in self.logger_classes:
            try:
                loggers.append(cls(trial_dir))
            except Exception as e:
                logger.debug("logger %s unavailable: %s", cls.__name__, e)
        self._per_trial[trial.trial_id] = loggers
        return loggers

    def on_result(self, trial, result: dict):
        for lg in self._loggers_for(trial):
            try:
                lg.on_result(trial, result)
            except Exception:
                logger.debug("logger failed", exc_info=True)

    def close(self):
        for loggers in self._per_trial.values():
            for lg in loggers:
                lg.close()
        self._per_trial.clear()
