"""Trainable protocol (analog of reference python/ray/tune/trainable/
trainable.py:69 — class API with setup/step/save_checkpoint/load_checkpoint —
and trainable/function_trainable.py — function API reporting via
``tune.report``).

A trial actor hosts exactly one Trainable. The class API is stepwise and
synchronous; the function API runs the user function on a thread and converts
each ``tune.report`` call into one step result.
"""

from __future__ import annotations

import inspect
import queue
import threading
import time
import traceback

from ray_tpu.air.checkpoint import Checkpoint

RESULT_DONE = "done"
TRAINING_ITERATION = "training_iteration"


class _TuneSession:
    def __init__(self, checkpoint: Checkpoint | None):
        self.result_queue: queue.Queue = queue.Queue()
        self.continue_event = threading.Event()
        self.checkpoint = checkpoint
        self.stop_requested = False


_thread_local = threading.local()


def _set_session(s: _TuneSession | None):
    _thread_local.session = s


def get_session() -> _TuneSession | None:
    return getattr(_thread_local, "session", None)


def report(metrics: dict, checkpoint: Checkpoint | None = None) -> None:
    """Report one step's metrics (and optionally a checkpoint) from inside a
    function trainable. Blocks until the controller consumes the result, which
    gives schedulers a synchronous decision point (reference
    function_trainable semantics)."""
    s = get_session()
    if s is None:
        # Inside a JaxTrainer worker the air session owns reporting.
        from ray_tpu.air import session as air_session

        if air_session.in_session():
            air_session.report(metrics, checkpoint=checkpoint)
            return
        raise RuntimeError("tune.report() called outside a tune session")
    s.continue_event.clear()
    s.result_queue.put((dict(metrics), checkpoint))
    s.continue_event.wait()
    if s.stop_requested:
        raise StopIteration("trial stopped by scheduler")


def get_checkpoint() -> Checkpoint | None:
    s = get_session()
    if s is not None:
        return s.checkpoint
    from ray_tpu.air import session as air_session

    if air_session.in_session():
        return air_session.get_checkpoint()
    return None


class Trainable:
    """Stepwise trainable (class API)."""

    def __init__(self, config: dict | None = None):
        self.config = config or {}
        self.iteration = 0
        self._start = time.time()
        self._trial_resources: dict = {}
        self.setup(self.config)

    @property
    def trial_resources(self) -> dict:
        """Resources currently allocated to this trial (reference:
        Trainable.trial_resources). Updated by the controller on every
        actor (re)start, so a ResourceChangingScheduler resize is visible
        from step() after the restart — read it there, not in setup()."""
        return self._trial_resources

    # -- subclass surface ---------------------------------------------------
    def setup(self, config: dict) -> None:
        pass

    def step(self) -> dict:
        raise NotImplementedError

    def save_checkpoint(self) -> Checkpoint | None:
        return None

    def load_checkpoint(self, checkpoint: Checkpoint) -> None:
        pass

    def reset_config(self, new_config: dict) -> bool:
        """Reuse this instance for a new config (PBT exploit). Return True if
        supported; False forces actor recreation."""
        return False

    def cleanup(self) -> None:
        pass

    # -- controller surface -------------------------------------------------
    def train(self) -> dict:
        result = self.step() or {}
        self.iteration += 1
        result.setdefault(TRAINING_ITERATION, self.iteration)
        result.setdefault("time_total_s", time.time() - self._start)
        result.setdefault(RESULT_DONE, False)
        return result

    def save(self) -> Checkpoint | None:
        ckpt = self.save_checkpoint()
        if ckpt is not None:
            ckpt.metadata.setdefault(TRAINING_ITERATION, self.iteration)
        return ckpt

    def restore(self, checkpoint: Checkpoint) -> None:
        self.load_checkpoint(checkpoint)
        it = checkpoint.metadata.get(TRAINING_ITERATION) if checkpoint else None
        if it is not None:
            self.iteration = int(it)

    def stop(self) -> None:
        self.cleanup()


class FunctionTrainable(Trainable):
    """Adapts ``fn(config)`` (optionally ``fn(config, checkpoint)``) to the
    stepwise protocol: each ``tune.report`` inside fn is one step."""

    _fn = None  # subclass or instance attribute

    def __init__(self, config: dict | None = None, fn=None, checkpoint: Checkpoint | None = None):
        if fn is not None:
            self._fn = fn
        self._session = _TuneSession(checkpoint)
        self._thread: threading.Thread | None = None
        self._error: str | None = None
        self._last_checkpoint: Checkpoint | None = checkpoint
        super().__init__(config)

    def _runner(self):
        _set_session(self._session)
        try:
            fn = self._fn
            params = inspect.signature(fn).parameters
            if len(params) >= 2 and "checkpoint" in params:
                fn(self.config, checkpoint=self._session.checkpoint)
            else:
                fn(self.config)
        except StopIteration:
            pass
        except BaseException:
            self._error = traceback.format_exc()
        finally:
            self._session.result_queue.put(None)  # sentinel: thread finished

    def step(self) -> dict:
        if self._thread is None:
            self._thread = threading.Thread(target=self._runner, daemon=True)
            self._thread.start()
        item = self._session.result_queue.get()
        if item is None:
            if self._error:
                raise RuntimeError(f"trial function failed:\n{self._error}")
            return {RESULT_DONE: True}
        metrics, ckpt = item
        if ckpt is not None:
            self._last_checkpoint = ckpt
        self._session.continue_event.set()
        metrics.setdefault(RESULT_DONE, False)
        return metrics

    def save_checkpoint(self) -> Checkpoint | None:
        return self._last_checkpoint

    def load_checkpoint(self, checkpoint: Checkpoint) -> None:
        self._session.checkpoint = checkpoint
        self._last_checkpoint = checkpoint

    def cleanup(self) -> None:
        self._session.stop_requested = True
        self._session.continue_event.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


def wrap_function(fn) -> type:
    """Build a FunctionTrainable subclass bound to ``fn`` (so it pickles as a
    class for the trial actor)."""

    class _Wrapped(FunctionTrainable):
        pass

    _Wrapped._fn = staticmethod(fn)
    _Wrapped.__name__ = getattr(fn, "__name__", "fn")
    return _Wrapped
