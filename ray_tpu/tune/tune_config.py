"""TuneConfig (analog of reference python/ray/tune/tune_config.py)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any


@dataclass
class TuneConfig:
    metric: str | None = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int | None = None
    search_alg: Any = None  # Searcher
    scheduler: Any = None  # TrialScheduler
    time_budget_s: float | None = None
    reuse_actors: bool = False
    trial_name_creator: Any = None
