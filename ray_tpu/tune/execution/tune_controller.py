"""TuneController (analog of reference python/ray/tune/execution/
tune_controller.py:49 + ray_trial_executor.py:188): the experiment step loop.

Each trial runs in a dedicated **trial actor** (`_TrialActor`) holding one
Trainable; the controller drives train/save/stop via actor calls and reacts to
results with the searcher + scheduler. Failed trials are retried up to
``max_failures`` by recreating the actor from the latest checkpoint — same
gang-restart shape the JaxTrainer BackendExecutor uses.
"""

from __future__ import annotations

import json
import os
import time
import traceback

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.tune.experiment.trial import (
    ERROR,
    PAUSED,
    PENDING,
    RUNNING,
    TERMINATED,
    Trial,
)
from ray_tpu.tune.schedulers.pbt import EXPLOIT, PopulationBasedTraining
from ray_tpu.tune.schedulers.trial_scheduler import (
    CONTINUE,
    PAUSE,
    STOP,
    FIFOScheduler,
    TrialScheduler,
)
from ray_tpu.tune.search.searcher import Searcher
from ray_tpu.tune.trainable import RESULT_DONE, Trainable, wrap_function


class _TrialActor:
    """Actor hosting one Trainable instance (reference: the trainable-as-actor
    pattern, ray_trial_executor.py:382 _setup_remote_runner)."""

    def __init__(self, trainable_cls, config: dict, checkpoint=None, trial_resources: dict | None = None):
        self._trainable: Trainable = trainable_cls(config)
        # Current trial resources (reference: Trainable.trial_resources) —
        # updated on every (re)start so ResourceChangingScheduler resizes
        # are visible to the training code.
        self._trainable._trial_resources = dict(trial_resources or {})
        if checkpoint is not None:
            self._trainable.restore(checkpoint)

    def train(self) -> dict:
        return self._trainable.train()

    def save(self):
        return self._trainable.save()

    def restore(self, checkpoint) -> None:
        self._trainable.restore(checkpoint)

    def reset(self, new_config: dict, checkpoint=None) -> bool:
        ok = self._trainable.reset_config(new_config)
        if ok and checkpoint is not None:
            self._trainable.restore(checkpoint)
        return ok

    def stop(self) -> None:
        self._trainable.stop()


class TuneController:
    def __init__(
        self,
        trainable,
        *,
        param_space: dict | None = None,
        searcher: Searcher,
        scheduler: TrialScheduler | None = None,
        metric: str | None = None,
        mode: str = "max",
        num_samples: int = 1,
        max_concurrent: int | None = None,
        stop: dict | None = None,
        time_budget_s: float | None = None,
        max_failures: int = 0,
        resources_per_trial: dict | None = None,
        experiment_dir: str | None = None,
        experiment_name: str = "exp",
        checkpoint_frequency: int = 1,
        sync_config=None,
    ):
        if isinstance(trainable, type) and issubclass(trainable, Trainable):
            self.trainable_cls = trainable
        elif callable(trainable):
            self.trainable_cls = wrap_function(trainable)
        else:
            raise TypeError(f"trainable must be a Trainable subclass or function, got {trainable!r}")
        self.searcher = searcher
        self.scheduler = scheduler or FIFOScheduler()
        self.metric = metric
        self.mode = mode
        self.num_samples = num_samples
        self.max_concurrent = max_concurrent
        self.stop_criteria = stop or {}
        self.time_budget_s = time_budget_s
        self.max_failures = max_failures
        self.resources_per_trial = resources_per_trial or {"CPU": 1}
        self.experiment_dir = experiment_dir
        self.experiment_name = experiment_name
        self.checkpoint_frequency = checkpoint_frequency
        self._sync_manager = None
        if sync_config is not None and experiment_dir:
            from ray_tpu.tune.syncer import SyncManager

            self._sync_manager = SyncManager(sync_config, experiment_dir, experiment_name)
        self._logger_manager = None
        if experiment_dir:
            from ray_tpu.tune.logger import LoggerManager

            self._logger_manager = LoggerManager(experiment_dir)

        self.trials: list[Trial] = []
        self._searcher_done = False
        self._start_time = time.time()
        self._saved_ckpt_ids: dict[str, int] = {}

        self.searcher.set_search_properties(metric, mode, param_space or {})
        self.scheduler.set_search_properties(metric, mode)

    # -- trial lifecycle ----------------------------------------------------

    def _actor_options(self, trial: Trial | None = None) -> dict:
        # Per-trial override (ResourceChangingScheduler) wins over the
        # experiment-wide default.
        res = dict(
            trial.resources
            if trial is not None and trial.resources
            else self.resources_per_trial
        )
        opts: dict = {}
        ncpu = res.pop("CPU", None)
        ntpu = res.pop("TPU", None)
        if ncpu:
            opts["num_cpus"] = ncpu
        if ntpu:
            opts["num_tpus"] = ntpu
        if res:
            opts["resources"] = res
        return opts

    def _start_trial(self, trial: Trial, checkpoint=None, config: dict | None = None):
        if config is not None:
            trial.config = config
        cls = ray_tpu.remote(_TrialActor)
        trial.runner = cls.options(
            max_restarts=0, **self._actor_options(trial)
        ).remote(
            self.trainable_cls, trial.config,
            checkpoint if checkpoint is not None else trial.checkpoint,
            trial.resources or self.resources_per_trial,
        )
        trial.status = RUNNING
        trial.start_time = time.time()
        trial.pending_future = trial.runner.train.remote()
        trial.pending_action = "train"

    def _stop_trial(self, trial: Trial, status: str = TERMINATED):
        if trial.runner is not None:
            try:
                trial.runner.stop.remote()
                ray_tpu.kill(trial.runner)
            except Exception:
                pass
        trial.runner = None
        trial.pending_future = None
        trial.status = status

    def _maybe_add_trial(self) -> bool:
        """Ask the searcher for a new config; returns True if a trial was added."""
        if self._searcher_done:
            return False
        total = self.searcher.total_samples
        if total is not None and len(self.trials) >= total:
            self._searcher_done = True
            return False
        if total is None and len(self.trials) >= self.num_samples:
            self._searcher_done = True
            return False
        trial = Trial(config={})
        cfg = self.searcher.suggest(trial.trial_id)
        if cfg is None:
            return False  # limiter saturated or exhausted; retry later
        trial.config = cfg
        self.trials.append(trial)
        self.scheduler.on_trial_add(self, trial)
        return True

    def _live_trials(self) -> list[Trial]:
        return [t for t in self.trials if t.status == RUNNING]

    def _should_stop_trial(self, result: dict) -> bool:
        if result.get(RESULT_DONE):
            return True
        # Stop criteria are always "stop once value reaches bound", regardless
        # of optimisation mode (reference Ray semantics).
        for key, bound in self.stop_criteria.items():
            v = result.get(key)
            if v is not None and v >= bound:
                return True
        return False

    # -- result handling ----------------------------------------------------

    def _on_result(self, trial: Trial, result: dict):
        # A bare done sentinel (function trainable ending) carries no new
        # metrics — logging it would duplicate the last row. Trainable.train
        # decorates every result with iteration/timing bookkeeping, so only
        # non-bookkeeping keys count; a final step reporting real metrics
        # together with done is still logged.
        raw_has_metrics = any(
            k not in (RESULT_DONE, "training_iteration", "time_total_s", "time_this_iter_s")
            for k in result
        )
        # merge so the final done-sentinel step doesn't erase reported metrics
        trial.last_result = {**trial.last_result, **result}
        result = trial.last_result
        if self.metric and self.metric in result:
            trial.metric_history.append(result[self.metric])
        if self._logger_manager is not None and raw_has_metrics:
            self._logger_manager.on_result(trial, result)
        self.searcher.on_trial_result(trial.trial_id, result)

        if self._should_stop_trial(result):
            self._complete_trial(trial, result)
            return

        decision = self.scheduler.on_trial_result(self, trial, result)
        if decision == STOP:
            self._complete_trial(trial, result)
        elif decision == PAUSE:
            self._save_then(trial, next_action="pause")
        elif decision == EXPLOIT:
            donor, new_config = self.scheduler.pending_exploit.pop(trial.trial_id)
            self._exploit(trial, donor, new_config)
        else:  # CONTINUE
            if self.checkpoint_frequency and trial.iteration % self.checkpoint_frequency == 0:
                self._save_then(trial, next_action="train")
            else:
                trial.pending_future = trial.runner.train.remote()
                trial.pending_action = "train"

    def _save_then(self, trial: Trial, next_action: str):
        trial.pending_future = trial.runner.save.remote()
        trial.pending_action = f"save:{next_action}"

    def _complete_trial(self, trial: Trial, result: dict):
        self.searcher.on_trial_complete(trial.trial_id, result)
        self.scheduler.on_trial_complete(self, trial, result)
        # capture a final checkpoint before teardown
        try:
            ckpt = ray_tpu.get(trial.runner.save.remote(), timeout=30)
            if ckpt is not None:
                trial.checkpoint = ckpt
        except Exception:
            pass
        self._stop_trial(trial, TERMINATED)

    def stop_trial(self, trial: Trial):
        """Scheduler-initiated termination of a trial other than the one
        being processed (e.g. HyperBand halving losers). The scheduler has
        already accounted for it — only the searcher needs the completion."""
        if trial.status in (RUNNING, PENDING, PAUSED):
            self.searcher.on_trial_complete(trial.trial_id, trial.last_result)
            self._stop_trial(trial, TERMINATED)

    def _exploit(self, trial: Trial, donor: Trial, new_config: dict):
        """PBT: restart `trial` from donor's checkpoint with a mutated config."""
        self._stop_trial(trial, PENDING)
        trial.checkpoint = donor.checkpoint
        self._start_trial(trial, checkpoint=donor.checkpoint, config=new_config)

    def _on_error(self, trial: Trial, err: Exception):
        trial.num_failures += 1
        trial.error_msg = f"{type(err).__name__}: {err}"
        if trial.num_failures <= self.max_failures or self.max_failures < 0:
            self._stop_trial(trial, PENDING)  # retried from latest checkpoint
        else:
            # Only tell the searcher once the trial is truly finished — a
            # retried trial will complete (or exhaust retries) later.
            self.searcher.on_trial_complete(trial.trial_id, error=True)
            self.scheduler.on_trial_error(self, trial)
            self._stop_trial(trial, ERROR)

    # -- main loop ----------------------------------------------------------

    def step(self):
        """One controller iteration: top up trials, wait on one future, react."""
        cap = self.max_concurrent or max(1, int(ray_tpu.cluster_resources().get("CPU", 1)))
        while len(self._live_trials()) < cap:
            pending = [t for t in self.trials if t.status in (PENDING, PAUSED)]
            if pending:
                t = self.scheduler.choose_trial_to_run(self)
                if t is None:
                    # Scheduler is gating the paused trials (e.g. sync
                    # HyperBand mid-rung). Try topping up with a fresh trial;
                    # otherwise respect the gate while work is running. With
                    # nothing running, ask the scheduler to release its gates
                    # consistently (finalize/halve incomplete rungs) and
                    # re-ask. As an absolute last resort prefer forcing a
                    # PENDING trial (safe); if only gated PAUSED trials
                    # remain, one IS forced past its milestone — a scheduler
                    # that must never allow that has to release the gate in
                    # its on_no_available_trials hook (livelock is worse
                    # than an invariant break we can't see from here).
                    if self._maybe_add_trial():
                        continue
                    if self._live_trials():
                        break
                    self.scheduler.on_no_available_trials(self)
                    t = self.scheduler.choose_trial_to_run(self)
                    if t is None:
                        pending = [x for x in self.trials if x.status in (PENDING, PAUSED)]
                        if not pending:
                            break
                        t = next((x for x in pending if x.status == PENDING), pending[0])
                self._start_trial(t)
                continue
            if not self._maybe_add_trial():
                break

        live = self._live_trials()
        if not live:
            return
        futures = {t.pending_future: t for t in live if t.pending_future is not None}
        if not futures:
            return
        ready, _ = ray_tpu.wait(list(futures), num_returns=1, timeout=10.0)
        for ref in ready:
            trial = futures[ref]
            try:
                value = ray_tpu.get(ref)
            except Exception as e:
                self._on_error(trial, e)
                continue
            action = trial.pending_action
            if action == "train":
                self._on_result(trial, value)
            elif action.startswith("save"):
                if value is not None:
                    trial.checkpoint = value
                nxt = action.split(":", 1)[1]
                if nxt == "train":
                    trial.pending_future = trial.runner.train.remote()
                    trial.pending_action = "train"
                else:  # pause
                    self._stop_trial(trial, PAUSED)

    def is_finished(self) -> bool:
        if self.time_budget_s and time.time() - self._start_time > self.time_budget_s:
            return True
        active = [t for t in self.trials if t.status in (RUNNING, PENDING, PAUSED)]
        return self._searcher_done and not active

    def run(self):
        try:
            while not self.is_finished():
                self.step()
                self.save_experiment_state()
                if self._sync_manager is not None:
                    self._sync_manager.maybe_sync_up()
        finally:
            for t in self._live_trials():
                self._stop_trial(t, TERMINATED)
            self.save_experiment_state()
            if self._logger_manager is not None:
                self._logger_manager.close()
            if self._sync_manager is not None:
                self._sync_manager.maybe_sync_up(force=True)
        return self.trials

    # -- persistence (reference: execution/experiment_state.py) -------------

    def save_experiment_state(self):
        if not self.experiment_dir:
            return
        os.makedirs(self.experiment_dir, exist_ok=True)
        state = {
            "experiment_name": self.experiment_name,
            "metric": self.metric,
            "mode": self.mode,
            "trials": [t.summary() for t in self.trials],
            "timestamp": time.time(),
        }
        path = os.path.join(self.experiment_dir, "experiment_state.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f, indent=1, default=str)
        os.replace(tmp, path)
        for t in self.trials:
            # only re-serialise checkpoints that changed since the last save
            if t.checkpoint is not None and self._saved_ckpt_ids.get(t.trial_id) != id(t.checkpoint):
                try:
                    t.checkpoint.to_directory(
                        os.path.join(self.experiment_dir, f"checkpoint_{t.trial_id}")
                    )
                    self._saved_ckpt_ids[t.trial_id] = id(t.checkpoint)
                except Exception:
                    pass

    @staticmethod
    def load_experiment_state(experiment_dir: str) -> dict:
        # Shared loader: Tuner.restore and offline ExperimentAnalysis read
        # the experiment directory through the same schema/parser.
        from ray_tpu.tune.analysis import ExperimentAnalysis

        ea = ExperimentAnalysis(experiment_dir)
        state = ea._state
        for ts, rec in zip(state["trials"], ea.trials):
            ckpt = rec.checkpoint
            if ckpt is not None:
                ts["checkpoint"] = ckpt
        return state
