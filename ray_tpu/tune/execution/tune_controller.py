"""TuneController (analog of reference python/ray/tune/execution/
tune_controller.py:49): the experiment step loop.

Each trial runs in a dedicated **trial actor** (`_TrialActor`) holding one
Trainable. Actor lifecycle — acquisition of the trial's resources, creation,
process-death detection, tracked restarts, release — goes through the shared
AIR execution layer (`ray_tpu.air.execution.ActorManager`, the reference's
RayActorManager shape): the controller schedules `train`/`save` tasks with
callbacks and reacts to results with the searcher + scheduler. Failed trials
(application errors AND actor death) are retried up to ``max_failures`` by
recreating the actor from the latest checkpoint through the manager — the
same restart semantics Train's BackendExecutor gets from the same component.
"""

from __future__ import annotations

import json
import os
import time

import ray_tpu
from ray_tpu.air.execution import (
    ActorManager,
    FixedResourceManager,
    PlacementGroupResourceManager,
    ResourceRequest,
)
from ray_tpu.tune.experiment.trial import (
    ERROR,
    PAUSED,
    PENDING,
    RUNNING,
    TERMINATED,
    Trial,
)
from ray_tpu.tune.schedulers.pbt import EXPLOIT, PopulationBasedTraining
from ray_tpu.tune.schedulers.trial_scheduler import (
    CONTINUE,
    PAUSE,
    STOP,
    FIFOScheduler,
    TrialScheduler,
)
from ray_tpu.tune.search.searcher import Searcher
from ray_tpu.tune.trainable import RESULT_DONE, Trainable, wrap_function


class _TrialActor:
    """Actor hosting one Trainable instance (reference: the trainable-as-actor
    pattern, ray_trial_executor.py:382 _setup_remote_runner)."""

    def __init__(self, trainable_cls, config: dict, checkpoint=None, trial_resources: dict | None = None):
        self._trainable: Trainable = trainable_cls(config)
        # Current trial resources (reference: Trainable.trial_resources) —
        # updated on every (re)start so ResourceChangingScheduler resizes
        # are visible to the training code.
        self._trainable._trial_resources = dict(trial_resources or {})
        if checkpoint is not None:
            self._trainable.restore(checkpoint)

    def train(self) -> dict:
        return self._trainable.train()

    def save(self):
        return self._trainable.save()

    def restore(self, checkpoint) -> None:
        self._trainable.restore(checkpoint)

    def reset(self, new_config: dict, checkpoint=None) -> bool:
        ok = self._trainable.reset_config(new_config)
        if ok and checkpoint is not None:
            self._trainable.restore(checkpoint)
        return ok

    def stop(self) -> None:
        self._trainable.stop()


class TuneController:
    def __init__(
        self,
        trainable,
        *,
        param_space: dict | None = None,
        searcher: Searcher,
        scheduler: TrialScheduler | None = None,
        metric: str | None = None,
        mode: str = "max",
        num_samples: int = 1,
        max_concurrent: int | None = None,
        stop: dict | None = None,
        time_budget_s: float | None = None,
        max_failures: int = 0,
        resources_per_trial: dict | None = None,
        experiment_dir: str | None = None,
        experiment_name: str = "exp",
        checkpoint_frequency: int = 1,
        sync_config=None,
        resource_manager=None,
    ):
        if isinstance(trainable, type) and issubclass(trainable, Trainable):
            self.trainable_cls = trainable
        elif callable(trainable):
            self.trainable_cls = wrap_function(trainable)
        else:
            raise TypeError(f"trainable must be a Trainable subclass or function, got {trainable!r}")
        self.searcher = searcher
        self.scheduler = scheduler or FIFOScheduler()
        self.metric = metric
        self.mode = mode
        self.num_samples = num_samples
        self.max_concurrent = max_concurrent
        self.stop_criteria = stop or {}
        self.time_budget_s = time_budget_s
        self.max_failures = max_failures
        self.resources_per_trial = resources_per_trial or {"CPU": 1}
        self.experiment_dir = experiment_dir
        self.experiment_name = experiment_name
        self.checkpoint_frequency = checkpoint_frequency
        self._sync_manager = None
        if sync_config is not None and experiment_dir:
            from ray_tpu.tune.syncer import SyncManager

            self._sync_manager = SyncManager(sync_config, experiment_dir, experiment_name)
        self._logger_manager = None
        if experiment_dir:
            from ray_tpu.tune.logger import LoggerManager

            self._logger_manager = LoggerManager(experiment_dir)

        # The shared AIR execution substrate. TPU trials gang-reserve their
        # chips through placement groups (one ICI domain per trial); plain
        # CPU trials use budget bookkeeping with raylet enforcement.
        if resource_manager is None:
            resource_manager = (
                PlacementGroupResourceManager()
                if "TPU" in self.resources_per_trial
                else FixedResourceManager()
            )
        self._actor_manager = ActorManager(resource_manager)

        self.trials: list[Trial] = []
        self._searcher_done = False
        self._start_time = time.time()
        self._saved_ckpt_ids: dict[str, int] = {}

        self.searcher.set_search_properties(metric, mode, param_space or {})
        self.scheduler.set_search_properties(metric, mode)

    # -- trial lifecycle ----------------------------------------------------

    def _trial_resources(self, trial: Trial) -> dict:
        # Per-trial override (ResourceChangingScheduler) wins over the
        # experiment-wide default.
        return dict(
            trial.resources
            if trial.resources
            else self.resources_per_trial
        )

    def _start_trial(self, trial: Trial, checkpoint=None, config: dict | None = None):
        if config is not None:
            trial.config = config
        if checkpoint is not None:
            trial.checkpoint = checkpoint
        res = self._trial_resources(trial)

        def _constructor_kwargs():
            # Re-resolved on every (re)start by the manager, so a restart
            # after a failure picks up the LATEST checkpoint and config.
            return dict(
                trainable_cls=self.trainable_cls,
                config=trial.config,
                checkpoint=trial.checkpoint,
                trial_resources=self._trial_resources(trial),
            )

        trial.tracked_actor = self._actor_manager.add_actor(
            _TrialActor,
            kwargs_fn=_constructor_kwargs,
            resource_request=ResourceRequest([res]),
            on_start=self._make_on_start(trial),
            on_failure=self._make_on_failure(trial),
            # Process-death restarts share the trial's failure budget; the
            # manager recreates from the latest checkpoint via kwargs_fn.
            max_restarts=(-1 if self.max_failures < 0 else self.max_failures),
            restart_backoff_s=0.5,
            graceful_stop_method="stop",
        )
        trial.status = RUNNING

    def _stop_trial(self, trial: Trial, status: str = TERMINATED):
        if trial.tracked_actor is not None:
            self._actor_manager.remove_actor(trial.tracked_actor)
            trial.tracked_actor = None
        trial.status = status

    # -- manager callbacks --------------------------------------------------

    def _make_on_start(self, trial: Trial):
        def on_start(tracked):
            if trial.tracked_actor is not tracked:
                return  # stale callback from a replaced actor
            trial.start_time = time.time()
            self._schedule_train(trial)

        return on_start

    def _make_on_failure(self, trial: Trial):
        def on_failure(tracked, error, will_restart):
            if trial.tracked_actor is not tracked:
                return
            trial.num_failures += 1
            trial.error_msg = f"{type(error).__name__}: {error}"
            if will_restart:
                # The manager recreates the actor from the latest checkpoint
                # (kwargs_fn); on_start reschedules training.
                return
            self._fail_trial(trial)

        return on_failure

    def _fail_trial(self, trial: Trial):
        """Terminal failure (budget exhausted): same bookkeeping whether the
        last straw was a process death or an application exception."""
        self.searcher.on_trial_complete(trial.trial_id, error=True)
        self.scheduler.on_trial_error(self, trial)
        self._stop_trial(trial, ERROR)

    def _schedule_train(self, trial: Trial):
        tracked = trial.tracked_actor
        self._actor_manager.schedule_actor_task(
            tracked,
            "train",
            on_result=lambda value: self._on_result(trial, tracked, value),
            on_error=lambda err: self._on_app_error(trial, tracked, err),
        )

    def _save_then(self, trial: Trial, next_action: str):
        tracked = trial.tracked_actor
        self._actor_manager.schedule_actor_task(
            tracked,
            "save",
            on_result=lambda value: self._on_saved(trial, tracked, value, next_action),
            on_error=lambda err: self._on_app_error(trial, tracked, err),
        )

    def _on_saved(self, trial: Trial, tracked, value, next_action: str):
        if trial.tracked_actor is not tracked:
            return
        if value is not None:
            trial.checkpoint = value
        if next_action == "train":
            self._schedule_train(trial)
        else:  # pause
            self._stop_trial(trial, PAUSED)

    def _on_app_error(self, trial: Trial, tracked, err: Exception):
        """The trainable raised (the actor process is still alive). Shares
        the trial failure budget with process-death restarts: retry from the
        latest checkpoint through the manager, else surface the error."""
        if trial.tracked_actor is not tracked:
            return
        trial.num_failures += 1
        trial.error_msg = f"{type(err).__name__}: {err}"
        if trial.num_failures <= self.max_failures or self.max_failures < 0:
            # Manager-driven recreate: kwargs_fn re-resolves to the latest
            # checkpoint; on_start fires again and reschedules training.
            self._actor_manager.restart_actor(tracked)
        else:
            self._fail_trial(trial)

    def _maybe_add_trial(self) -> bool:
        """Ask the searcher for a new config; returns True if a trial was added."""
        if self._searcher_done:
            return False
        total = self.searcher.total_samples
        if total is not None and len(self.trials) >= total:
            self._searcher_done = True
            return False
        if total is None and len(self.trials) >= self.num_samples:
            self._searcher_done = True
            return False
        trial = Trial(config={})
        cfg = self.searcher.suggest(trial.trial_id)
        if cfg is None:
            return False  # limiter saturated or exhausted; retry later
        trial.config = cfg
        self.trials.append(trial)
        self.scheduler.on_trial_add(self, trial)
        return True

    def _live_trials(self) -> list[Trial]:
        return [t for t in self.trials if t.status == RUNNING]

    def _should_stop_trial(self, result: dict) -> bool:
        if result.get(RESULT_DONE):
            return True
        # Stop criteria are always "stop once value reaches bound", regardless
        # of optimisation mode (reference Ray semantics).
        for key, bound in self.stop_criteria.items():
            v = result.get(key)
            if v is not None and v >= bound:
                return True
        return False

    # -- result handling ----------------------------------------------------

    def _on_result(self, trial: Trial, tracked, result: dict):
        if trial.tracked_actor is not tracked:
            return  # stale callback from a replaced actor
        # A bare done sentinel (function trainable ending) carries no new
        # metrics — logging it would duplicate the last row. Trainable.train
        # decorates every result with iteration/timing bookkeeping, so only
        # non-bookkeeping keys count; a final step reporting real metrics
        # together with done is still logged.
        raw_has_metrics = any(
            k not in (RESULT_DONE, "training_iteration", "time_total_s", "time_this_iter_s")
            for k in result
        )
        # merge so the final done-sentinel step doesn't erase reported metrics
        trial.last_result = {**trial.last_result, **result}
        result = trial.last_result
        if self.metric and self.metric in result:
            trial.metric_history.append(result[self.metric])
        if self._logger_manager is not None and raw_has_metrics:
            self._logger_manager.on_result(trial, result)
        self.searcher.on_trial_result(trial.trial_id, result)

        if self._should_stop_trial(result):
            self._complete_trial(trial, result)
            return

        decision = self.scheduler.on_trial_result(self, trial, result)
        if decision == STOP:
            self._complete_trial(trial, result)
        elif decision == PAUSE:
            self._save_then(trial, next_action="pause")
        elif decision == EXPLOIT:
            donor, new_config = self.scheduler.pending_exploit.pop(trial.trial_id)
            self._exploit(trial, donor, new_config)
        else:  # CONTINUE
            if self.checkpoint_frequency and trial.iteration % self.checkpoint_frequency == 0:
                self._save_then(trial, next_action="train")
            else:
                self._schedule_train(trial)

    def _complete_trial(self, trial: Trial, result: dict):
        self.searcher.on_trial_complete(trial.trial_id, result)
        self.scheduler.on_trial_complete(self, trial, result)
        # capture a final checkpoint before teardown
        tracked = trial.tracked_actor
        if tracked is not None and tracked.actor_handle is not None:
            try:
                ckpt = ray_tpu.get(tracked.actor_handle.save.remote(), timeout=30)
                if ckpt is not None:
                    trial.checkpoint = ckpt
            except Exception:
                pass
        self._stop_trial(trial, TERMINATED)

    def stop_trial(self, trial: Trial):
        """Scheduler-initiated termination of a trial other than the one
        being processed (e.g. HyperBand halving losers). The scheduler has
        already accounted for it — only the searcher needs the completion."""
        if trial.status in (RUNNING, PENDING, PAUSED):
            self.searcher.on_trial_complete(trial.trial_id, trial.last_result)
            self._stop_trial(trial, TERMINATED)

    def _exploit(self, trial: Trial, donor: Trial, new_config: dict):
        """PBT: restart `trial` from donor's checkpoint with a mutated config."""
        self._stop_trial(trial, PENDING)
        self._start_trial(trial, checkpoint=donor.checkpoint, config=new_config)

    # -- main loop ----------------------------------------------------------

    def step(self):
        """One controller iteration: top up trials, drive the actor manager
        (starts, task results, failures), react via callbacks."""
        cap = self.max_concurrent or max(1, int(ray_tpu.cluster_resources().get("CPU", 1)))
        while len(self._live_trials()) < cap:
            pending = [t for t in self.trials if t.status in (PENDING, PAUSED)]
            if pending:
                t = self.scheduler.choose_trial_to_run(self)
                if t is None:
                    # Scheduler is gating the paused trials (e.g. sync
                    # HyperBand mid-rung). Try topping up with a fresh trial;
                    # otherwise respect the gate while work is running. With
                    # nothing running, ask the scheduler to release its gates
                    # consistently (finalize/halve incomplete rungs) and
                    # re-ask. As an absolute last resort prefer forcing a
                    # PENDING trial (safe); if only gated PAUSED trials
                    # remain, one IS forced past its milestone — a scheduler
                    # that must never allow that has to release the gate in
                    # its on_no_available_trials hook (livelock is worse
                    # than an invariant break we can't see from here).
                    if self._maybe_add_trial():
                        continue
                    if self._live_trials():
                        break
                    self.scheduler.on_no_available_trials(self)
                    t = self.scheduler.choose_trial_to_run(self)
                    if t is None:
                        pending = [x for x in self.trials if x.status in (PENDING, PAUSED)]
                        if not pending:
                            break
                        t = next((x for x in pending if x.status == PENDING), pending[0])
                self._start_trial(t)
                continue
            if not self._maybe_add_trial():
                break

        if not self._live_trials():
            return
        self._actor_manager.next(timeout=10.0)

    def is_finished(self) -> bool:
        if self.time_budget_s and time.time() - self._start_time > self.time_budget_s:
            return True
        active = [t for t in self.trials if t.status in (RUNNING, PENDING, PAUSED)]
        return self._searcher_done and not active

    def run(self):
        try:
            while not self.is_finished():
                self.step()
                self.save_experiment_state()
                if self._sync_manager is not None:
                    self._sync_manager.maybe_sync_up()
        finally:
            for t in self._live_trials():
                self._stop_trial(t, TERMINATED)
            # Guaranteed release: whatever the exit path, no trial actor nor
            # resource acquisition survives the controller.
            self._actor_manager.clear()
            self.save_experiment_state()
            if self._logger_manager is not None:
                self._logger_manager.close()
            if self._sync_manager is not None:
                self._sync_manager.maybe_sync_up(force=True)
        return self.trials

    # -- persistence (reference: execution/experiment_state.py) -------------

    def save_experiment_state(self):
        if not self.experiment_dir:
            return
        os.makedirs(self.experiment_dir, exist_ok=True)
        state = {
            "experiment_name": self.experiment_name,
            "metric": self.metric,
            "mode": self.mode,
            "trials": [t.summary() for t in self.trials],
            "timestamp": time.time(),
        }
        path = os.path.join(self.experiment_dir, "experiment_state.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f, indent=1, default=str)
        os.replace(tmp, path)
        for t in self.trials:
            # only re-serialise checkpoints that changed since the last save
            if t.checkpoint is not None and self._saved_ckpt_ids.get(t.trial_id) != id(t.checkpoint):
                try:
                    t.checkpoint.to_directory(
                        os.path.join(self.experiment_dir, f"checkpoint_{t.trial_id}")
                    )
                    self._saved_ckpt_ids[t.trial_id] = id(t.checkpoint)
                except Exception:
                    pass

    @staticmethod
    def load_experiment_state(experiment_dir: str) -> dict:
        # Shared loader: Tuner.restore and offline ExperimentAnalysis read
        # the experiment directory through the same schema/parser.
        from ray_tpu.tune.analysis import ExperimentAnalysis

        ea = ExperimentAnalysis(experiment_dir)
        state = ea._state
        for ts, rec in zip(state["trials"], ea.trials):
            ckpt = rec.checkpoint
            if ckpt is not None:
                ts["checkpoint"] = ckpt
        return state
