"""Tuner (analog of reference python/ray/tune/tuner.py:53, .fit:320) and
tune.run (tune/tune.py:293).

``Tuner(trainable, param_space=..., tune_config=..., run_config=...).fit()``
drives a TuneController experiment and returns a ResultGrid. Accepts a
BaseTrainer too (reference base_trainer.py:559 fit-via-Tune): its
ScalingConfig becomes the trial resource request and its ``as_trainable``
adapter the trial body.
"""

from __future__ import annotations

import os
import time

from ray_tpu.air.config import RunConfig
from ray_tpu.train.base_trainer import BaseTrainer, Result
from ray_tpu.tune.execution.tune_controller import TuneController
from ray_tpu.tune.experiment.trial import ERROR
from ray_tpu.tune.result_grid import ResultGrid
from ray_tpu.tune.search.basic_variant import BasicVariantGenerator
from ray_tpu.tune.tune_config import TuneConfig


def _experiment_dir(run_config: RunConfig, default_name: str) -> str:
    return run_config.resolve_dir(default_name)


class Tuner:
    def __init__(
        self,
        trainable=None,
        *,
        param_space: dict | None = None,
        tune_config: TuneConfig | None = None,
        run_config: RunConfig | None = None,
        _restore_dir: str | None = None,
    ):
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self._restore_dir = _restore_dir
        self._restore_state: dict | None = None

        if isinstance(trainable, BaseTrainer):
            self._trainer = trainable
            self.trainable = trainable.as_trainable()
            self.run_config = run_config or trainable.run_config
            res = trainable.scaling_config.worker_resources()
            # trial actor itself is light; workers carry the heavy resources
            self._resources_per_trial = {"CPU": 1} if res.get("TPU") else dict(res)
        else:
            self._trainer = None
            self.trainable = trainable
            self.run_config = run_config or RunConfig()
            self._resources_per_trial = {"CPU": 1}

    @classmethod
    def restore(cls, path: str, trainable, *, param_space: dict | None = None,
                tune_config: TuneConfig | None = None, run_config: RunConfig | None = None):
        """Resume an interrupted experiment from its directory (reference
        Tuner.restore): TERMINATED trials are kept as results; RUNNING/PENDING/
        ERROR trials are re-run from their last checkpoint."""
        run_config = run_config or RunConfig()
        run_config.storage_path = os.path.dirname(path)
        run_config.name = os.path.basename(path)
        t = cls(trainable, param_space=param_space, tune_config=tune_config,
                run_config=run_config, _restore_dir=path)
        t._restore_state = TuneController.load_experiment_state(path)
        return t

    def fit(self) -> ResultGrid:
        tc = self.tune_config
        searcher = tc.search_alg or BasicVariantGenerator(
            self.param_space, num_samples=tc.num_samples
        )
        exp_dir = self._restore_dir or _experiment_dir(
            self.run_config, getattr(self.trainable, "__name__", "exp")
        )
        controller = TuneController(
            self.trainable,
            param_space=self.param_space,
            searcher=searcher,
            scheduler=tc.scheduler,
            metric=tc.metric,
            mode=tc.mode,
            num_samples=tc.num_samples,
            max_concurrent=tc.max_concurrent_trials,
            stop=self.run_config.stop,
            time_budget_s=tc.time_budget_s,
            max_failures=self.run_config.failure_config.max_failures,
            resources_per_trial=self._resources_per_trial,
            experiment_dir=exp_dir,
            experiment_name=self.run_config.name or "exp",
            sync_config=self.run_config.sync_config,
        )
        if self._restore_state is not None:
            self._seed_from_restore(controller)
        trials = controller.run()
        results = [
            Result(
                metrics=t.last_result,
                checkpoint=t.checkpoint,
                error=t.error_msg if t.status == ERROR else None,
                path=exp_dir,
                config=dict(t.config),
            )
            for t in trials
        ]
        return ResultGrid(results, trials, default_metric=tc.metric, default_mode=tc.mode)

    def _seed_from_restore(self, controller: TuneController):
        from ray_tpu.tune.experiment.trial import PENDING, TERMINATED, Trial

        for ts in self._restore_state.get("trials", []):
            trial = Trial(
                config=ts["config"],
                trial_id=ts["trial_id"],
                status=TERMINATED if ts["status"] == TERMINATED else PENDING,
                last_result=ts.get("last_result") or {},
                num_failures=0,
                checkpoint=ts.get("checkpoint"),
            )
            controller.trials.append(trial)
        controller._searcher_done = True  # finish restored population only


def run(
    trainable,
    *,
    config: dict | None = None,
    metric: str | None = None,
    mode: str = "max",
    num_samples: int = 1,
    stop: dict | None = None,
    search_alg=None,
    scheduler=None,
    max_concurrent_trials: int | None = None,
    time_budget_s: float | None = None,
    storage_path: str | None = None,
    name: str | None = None,
    resources_per_trial: dict | None = None,
    max_failures: int = 0,
) -> ResultGrid:
    """Functional entrypoint (reference tune.run, tune/tune.py:293)."""
    from ray_tpu.air.config import FailureConfig

    tuner = Tuner(
        trainable,
        param_space=config,
        tune_config=TuneConfig(
            metric=metric,
            mode=mode,
            num_samples=num_samples,
            search_alg=search_alg,
            scheduler=scheduler,
            max_concurrent_trials=max_concurrent_trials,
            time_budget_s=time_budget_s,
        ),
        run_config=RunConfig(
            name=name,
            storage_path=storage_path,
            stop=stop,
            failure_config=FailureConfig(max_failures=max_failures),
        ),
    )
    if resources_per_trial:
        tuner._resources_per_trial = resources_per_trial
    return tuner.fit()
