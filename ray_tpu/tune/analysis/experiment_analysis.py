"""Offline experiment analysis (analog of reference
python/ray/tune/analysis/experiment_analysis.py:55 ``ExperimentAnalysis``).

Loads a finished (or foreign, or interrupted) experiment purely from its
directory — no live TuneController required:

    <experiment_dir>/
      experiment_state.json          <- trial summaries (tune_controller.py)
      <trial_id>/params.json         <- trial config (logger.py LoggerManager)
      <trial_id>/result.json         <- one JSON object per reported result
      <trial_id>/progress.csv        <- same rows, CSV
      checkpoint_<trial_id>/         <- latest persisted Checkpoint

``Tuner.restore`` and this class share the same on-disk schema; anything a
previous process wrote is enough.
"""

from __future__ import annotations

import json
import os
from typing import Any

from ray_tpu.air.checkpoint import Checkpoint


class _TrialRecord:
    """One trial as reconstructed from disk."""

    def __init__(self, trial_id: str, experiment_dir: str, summary: dict):
        self.trial_id = trial_id
        self.experiment_dir = experiment_dir
        self.summary = summary
        self.logdir = os.path.join(experiment_dir, trial_id)

    @property
    def config(self) -> dict:
        params = os.path.join(self.logdir, "params.json")
        if os.path.exists(params):
            try:
                with open(params) as f:
                    return json.load(f)
            except (OSError, ValueError):
                pass
        return dict(self.summary.get("config") or {})

    @property
    def last_result(self) -> dict:
        rows = self.results()
        if rows:
            return rows[-1]
        return dict(self.summary.get("last_result") or {})

    def results(self) -> list[dict]:
        """All reported results, in report order (result.json lines)."""
        path = os.path.join(self.logdir, "result.json")
        rows: list[dict] = []
        if os.path.exists(path):
            try:
                with open(path) as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            rows.append(json.loads(line))
            except (OSError, ValueError):
                pass
        return rows

    @property
    def checkpoint(self) -> Checkpoint | None:
        ckpt_dir = os.path.join(self.experiment_dir, f"checkpoint_{self.trial_id}")
        if os.path.isdir(ckpt_dir):
            try:
                return Checkpoint.from_directory(ckpt_dir)
            except Exception:
                return None
        return None


class ExperimentAnalysis:
    """Analyze an experiment directory written by a (possibly finished,
    possibly foreign) Tune run. Reference:
    python/ray/tune/analysis/experiment_analysis.py:55."""

    def __init__(
        self,
        experiment_path: str,
        default_metric: str | None = None,
        default_mode: str | None = None,
    ):
        self.experiment_path = experiment_path
        state_path = os.path.join(experiment_path, "experiment_state.json")
        if not os.path.exists(state_path):
            raise FileNotFoundError(
                f"no experiment_state.json under {experiment_path!r} — not a "
                "Tune experiment directory"
            )
        with open(state_path) as f:
            self._state = json.load(f)
        self.default_metric = default_metric or self._state.get("metric")
        self.default_mode = default_mode or self._state.get("mode")
        if self.default_mode not in (None, "min", "max"):
            raise ValueError(f"mode must be 'min'|'max', got {self.default_mode!r}")
        self.trials = [
            _TrialRecord(ts["trial_id"], experiment_path, ts)
            for ts in self._state.get("trials", [])
        ]

    # -- whole-experiment views ---------------------------------------------

    @property
    def stats(self) -> dict:
        return {
            "experiment_name": self._state.get("experiment_name"),
            "timestamp": self._state.get("timestamp"),
            "num_trials": len(self.trials),
        }

    def get_all_configs(self) -> dict[str, dict]:
        return {t.trial_id: t.config for t in self.trials}

    @property
    def results(self) -> dict[str, dict]:
        """trial_id -> last reported result."""
        return {t.trial_id: t.last_result for t in self.trials}

    @property
    def trial_dataframes(self) -> dict[str, Any]:
        """trial_id -> DataFrame of every reported result, in order."""
        import pandas as pd

        return {t.trial_id: pd.DataFrame(t.results()) for t in self.trials}

    def dataframe(self, metric: str | None = None, mode: str | None = None):
        """One row per trial. With an EXPLICIT metric, each trial's row is
        its best report for that metric; otherwise its last report (the
        experiment's recorded default metric does not flip this — matching
        the reference API's last-report default)."""
        import pandas as pd

        explicit = metric is not None
        metric, mode = self._resolve(metric, mode, require=explicit)
        rows = []
        for t in self.trials:
            row = self._pick_row(t, metric, mode) if explicit else t.last_result
            row = dict(row)
            row["trial_id"] = t.trial_id
            row["logdir"] = t.logdir
            rows.append(row)
        return pd.DataFrame(rows)

    # -- best-* lookups ------------------------------------------------------

    def get_best_trial(
        self, metric: str | None = None, mode: str | None = None, scope: str = "last"
    ) -> _TrialRecord | None:
        """scope='last' compares final reports; 'all' compares each trial's
        best-ever report (reference get_best_trial scopes)."""
        metric, mode = self._resolve(metric, mode)
        sign = 1 if mode == "max" else -1
        best, best_v = None, None
        for t in self.trials:
            row = t.last_result if scope == "last" else self._pick_row(t, metric, mode)
            v = row.get(metric)
            if v is None:
                continue
            if best_v is None or sign * v > sign * best_v:
                best, best_v = t, v
        return best

    def get_best_config(
        self, metric: str | None = None, mode: str | None = None, scope: str = "last"
    ) -> dict | None:
        t = self.get_best_trial(metric, mode, scope)
        return t.config if t else None

    def get_best_logdir(
        self, metric: str | None = None, mode: str | None = None, scope: str = "last"
    ) -> str | None:
        t = self.get_best_trial(metric, mode, scope)
        return t.logdir if t else None

    def get_best_checkpoint(
        self, trial: _TrialRecord | None = None, metric: str | None = None, mode: str | None = None
    ) -> Checkpoint | None:
        """The persisted checkpoint of the best trial (or the given trial)."""
        if trial is None:
            trial = self.get_best_trial(metric, mode)
        return trial.checkpoint if trial else None

    @property
    def best_trial(self) -> _TrialRecord:
        t = self.get_best_trial()
        if t is None:
            raise ValueError("no trial reported the default metric")
        return t

    @property
    def best_config(self) -> dict:
        return self.best_trial.config

    @property
    def best_checkpoint(self) -> Checkpoint:
        ckpt = self.get_best_checkpoint()
        if ckpt is None:
            raise ValueError("best trial has no persisted checkpoint")
        return ckpt

    @property
    def best_result(self) -> dict:
        return self.best_trial.last_result

    @property
    def best_dataframe(self):
        import pandas as pd

        return pd.DataFrame(self.best_trial.results())

    # -- internals -----------------------------------------------------------

    def _resolve(self, metric, mode, require: bool = True):
        metric = metric or self.default_metric
        mode = mode or self.default_mode or "max"
        if require and not metric:
            raise ValueError(
                "no metric given and the experiment recorded no default metric"
            )
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be 'min'|'max', got {mode!r}")
        return metric, mode

    def _pick_row(self, t: _TrialRecord, metric: str, mode: str) -> dict:
        sign = 1 if mode == "max" else -1
        best_row: dict = {}
        best_v = None
        for row in t.results():
            v = row.get(metric)
            if v is None:
                continue
            if best_v is None or sign * v > sign * best_v:
                best_row, best_v = row, v
        return best_row or t.last_result
