from ray_tpu.tune.analysis.experiment_analysis import ExperimentAnalysis

__all__ = ["ExperimentAnalysis"]
