"""Search-space domains (analog of reference python/ray/tune/search/sample.py
— Categorical/Float/Integer domains with .uniform/.loguniform/.quantized
samplers — and tune.grid_search / tune.sample_from markers).

A param_space dict may contain, at any nesting depth:
- Domain instances (``tune.choice/uniform/loguniform/randint/qrandint/...``)
- ``tune.grid_search([...])`` markers — expanded as a cross-product
- ``tune.sample_from(lambda spec: ...)`` — resolved last, sees sampled values
- plain values — passed through
"""

from __future__ import annotations

import math
import random
from typing import Any, Callable, Sequence


class Domain:
    """A sampleable hyperparameter domain."""

    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories: Sequence):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)

    def __repr__(self):
        return f"choice({self.categories!r})"


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False, q: float | None = None):
        if log and lower <= 0:
            raise ValueError("loguniform requires lower > 0")
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng):
        if self.log:
            v = math.exp(rng.uniform(math.log(self.lower), math.log(self.upper)))
        else:
            v = rng.uniform(self.lower, self.upper)
        if self.q:
            v = round(round(v / self.q) * self.q, 10)
        return min(max(v, self.lower), self.upper)

    def __repr__(self):
        kind = "loguniform" if self.log else "uniform"
        return f"{kind}({self.lower}, {self.upper})"


class Integer(Domain):
    def __init__(self, lower: int, upper: int, log: bool = False, q: int = 1):
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng):
        if self.log:
            v = int(math.exp(rng.uniform(math.log(max(self.lower, 1)), math.log(self.upper))))
        else:
            v = rng.randint(self.lower, self.upper - 1) if self.upper > self.lower else self.lower
        if self.q > 1:
            v = int(round(v / self.q) * self.q)
        return min(max(v, self.lower), self.upper - 1 if self.upper > self.lower else self.lower)

    def __repr__(self):
        return f"randint({self.lower}, {self.upper})"


class GridSearch:
    """Marker for exhaustive expansion (``tune.grid_search``)."""

    def __init__(self, values: Sequence):
        self.values = list(values)

    def __repr__(self):
        return f"grid_search({self.values!r})"


class SampleFrom:
    """Lazily-evaluated callable domain (``tune.sample_from``). The callable
    receives a ``spec`` object with attribute ``config`` = the partially
    resolved config dict."""

    def __init__(self, func: Callable):
        self.func = func


# -- public constructors (tune.choice etc.) ---------------------------------

def choice(categories: Sequence) -> Categorical:
    return Categorical(categories)


def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def quniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, q=q)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def qloguniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, log=True, q=q)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def qrandint(lower: int, upper: int, q: int) -> Integer:
    return Integer(lower, upper, q=q)


def lograndint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper, log=True)


def randn(mean: float = 0.0, sd: float = 1.0) -> "SampleFrom":
    return SampleFrom(lambda spec, m=mean, s=sd: random.gauss(m, s))


def grid_search(values: Sequence) -> GridSearch:
    return GridSearch(values)


def sample_from(func: Callable) -> SampleFrom:
    return SampleFrom(func)


# -- resolution --------------------------------------------------------------

class _Spec:
    def __init__(self, config):
        self.config = config


def grid_axes(space: dict, prefix: tuple = ()) -> list[tuple[tuple, list]]:
    """Collect (key-path, values) for every GridSearch in the space."""
    axes = []
    for k, v in space.items():
        if isinstance(v, GridSearch):
            axes.append((prefix + (k,), v.values))
        elif isinstance(v, dict):
            axes.extend(grid_axes(v, prefix + (k,)))
    return axes


def resolve(space: dict, rng: random.Random, grid_assignment: dict | None = None) -> dict:
    """Materialise one concrete config: apply grid assignment, sample Domains,
    then evaluate SampleFrom callables against the partially-built config."""
    grid_assignment = grid_assignment or {}
    deferred: list[tuple[tuple, SampleFrom]] = []

    def build(node: dict, prefix: tuple) -> dict:
        out = {}
        for k, v in node.items():
            path = prefix + (k,)
            if path in grid_assignment:
                out[k] = grid_assignment[path]
            elif isinstance(v, GridSearch):
                out[k] = v.values[0]
            elif isinstance(v, Domain):
                out[k] = v.sample(rng)
            elif isinstance(v, SampleFrom):
                out[k] = None
                deferred.append((path, v))
            elif isinstance(v, dict):
                out[k] = build(v, path)
            else:
                out[k] = v
        return out

    config = build(space, ())
    for path, sf in deferred:
        node = config
        for p in path[:-1]:
            node = node[p]
        node[path[-1]] = sf.func(_Spec(config))
    return config
