"""Trial bookkeeping (analog of reference python/ray/tune/experiment/
trial.py:282 — one hyperparameter configuration's lifecycle through the
controller)."""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Any

_trial_counter = itertools.count()

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


@dataclass
class Trial:
    config: dict
    trial_id: str = ""
    experiment_tag: str = ""
    status: str = PENDING
    last_result: dict = field(default_factory=dict)
    metric_history: list = field(default_factory=list)
    error_msg: str | None = None
    num_failures: int = 0
    checkpoint: Any = None  # latest air.Checkpoint
    start_time: float = 0.0
    # Per-trial resource override (ResourceChangingScheduler); None means
    # the experiment-wide resources_per_trial applies.
    resources: dict | None = None
    # runtime handles (not persisted)
    tracked_actor: Any = None  # air.execution.TrackedActor driving this trial

    def __post_init__(self):
        if not self.trial_id:
            self.trial_id = f"{int(time.time()) % 100000:05d}_{next(_trial_counter):05d}"

    @property
    def iteration(self) -> int:
        return int(self.last_result.get("training_iteration", 0))

    def metric_value(self, metric: str):
        return self.last_result.get(metric)

    def summary(self) -> dict:
        return {
            "trial_id": self.trial_id,
            "config": self.config,
            "status": self.status,
            "last_result": {k: v for k, v in self.last_result.items() if not callable(v)},
            "error_msg": self.error_msg,
            "num_failures": self.num_failures,
        }

    def __repr__(self):
        return f"Trial({self.trial_id}, {self.status}, iter={self.iteration})"
