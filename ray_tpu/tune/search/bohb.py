"""BOHB searcher — KDE density-ratio model (Falkner et al. 2018).

Analog of the reference's TuneBOHB (python/ray/tune/search/bohb/) but
self-contained: no ConfigSpace/hpbandster dependency. The TPE-like model:
observations split into "good" (top ``gamma`` fraction) and "bad"; a
per-dimension Gaussian KDE is fit to each over the unit hypercube (reusing
the bayesopt module's domain mapping); candidates sample from the good KDE
and the suggestion maximizes l(x)/g(x). Observations are bucketed by
budget (training_iteration) and the model uses the LARGEST budget with
enough points — the BOHB rule, so early HyperBand rungs inform the model
until higher-fidelity data accumulates. Pair with HyperBandForBOHB (or any
scheduler; the searcher is budget-aware on its own).
"""

from __future__ import annotations

import math
import random
from typing import Optional

import numpy as np

from ray_tpu.tune import sample as s
from ray_tpu.tune.search.bayesopt import _Dim
from ray_tpu.tune.search.searcher import Searcher


class TuneBOHB(Searcher):
    def __init__(
        self,
        space: Optional[dict] = None,
        metric: Optional[str] = None,
        mode: str = "max",
        min_points: int = 8,
        gamma: float = 0.25,
        candidates_per_suggest: int = 64,
        random_fraction: float = 0.2,
        seed: Optional[int] = None,
    ):
        super().__init__(metric, mode)
        self._space = space
        self._dims: Optional[list] = None
        self.min_points = min_points
        self.gamma = gamma
        self.n_candidates = candidates_per_suggest
        self.random_fraction = random_fraction
        self.rng = random.Random(seed)
        self._np_rng = np.random.default_rng(seed)
        self._passthrough: dict = {}
        # budget (training_iteration) -> list of (unit-cube x, metric)
        self._obs: dict[int, list] = {}
        self._live: dict[str, list] = {}  # trial_id -> unit x

    def set_search_properties(self, metric, mode, config) -> bool:
        super().set_search_properties(metric, mode, config)
        if self._space is None and config:
            self._space = config
        return True

    def _build_dims(self):
        if self._dims is None:
            self._dims = []
            self._passthrough: dict = {}
            for key, dom in (self._space or {}).items():
                if isinstance(dom, (s.Float, s.Integer, s.Categorical)):
                    self._dims.append(_Dim(key, dom))
                elif isinstance(dom, s.GridSearch):
                    raise ValueError("grid_search is not supported by TuneBOHB")
                else:
                    # Constants + sample_from markers resolve at suggest
                    # time (same contract as BayesOptSearch).
                    self._passthrough[key] = dom
        return self._dims

    def _config_from_unit(self, x: list) -> dict:
        cfg = dict(self._passthrough)
        for dim, u in zip(self._dims, x):
            cfg[dim.key] = dim.from_unit(u)
        for key, v in list(cfg.items()):
            if isinstance(v, s.SampleFrom):
                cfg[key] = v.func(s._Spec(cfg))
        return cfg

    def _random_unit(self) -> list:
        return [self.rng.random() for _ in self._build_dims()]

    def _model_budget(self) -> Optional[int]:
        """Largest budget holding enough observations (the BOHB rule)."""
        for budget in sorted(self._obs, reverse=True):
            if len(self._obs[budget]) >= self.min_points:
                return budget
        return None

    @staticmethod
    def _kde_logpdf(points: np.ndarray, x: np.ndarray) -> float:
        """Sum over dims of 1-D Gaussian KDE log densities (bandwidth by
        Scott's rule, floored so a degenerate dim can't yield inf)."""
        n, d = points.shape
        bw = max(n ** (-1.0 / (d + 4)), 1e-3) * 0.5
        logp = 0.0
        for j in range(d):
            diffs = (x[j] - points[:, j]) / bw
            dens = np.exp(-0.5 * diffs**2).mean() / (bw * math.sqrt(2 * math.pi))
            logp += math.log(max(dens, 1e-12))
        return logp

    def _sample_from(self, points: np.ndarray) -> list:
        """Draw one candidate from the KDE: pick a kernel center, add
        bandwidth noise, clip to the cube."""
        n, d = points.shape
        center = points[int(self._np_rng.integers(0, n))]
        bw = max(n ** (-1.0 / (d + 4)), 1e-3) * 0.5
        x = center + self._np_rng.normal(0.0, bw, d)
        return np.clip(x, 0.0, 1.0).tolist()

    def suggest(self, trial_id: str) -> Optional[dict]:
        dims = self._build_dims()
        if not dims:
            return dict(self._space or {})
        budget = self._model_budget()
        if budget is None or self.rng.random() < self.random_fraction:
            x = self._random_unit()
        else:
            obs = self._obs[budget]
            sign = 1.0 if self.mode == "max" else -1.0
            ranked = sorted(obs, key=lambda o: sign * o[1], reverse=True)
            n_good = max(2, int(len(ranked) * self.gamma))
            good = np.asarray([o[0] for o in ranked[:n_good]])
            bad = np.asarray([o[0] for o in ranked[n_good:]] or [self._random_unit()])
            best_x, best_score = None, -math.inf
            for _ in range(self.n_candidates):
                cand = np.asarray(self._sample_from(good))
                score = self._kde_logpdf(good, cand) - self._kde_logpdf(bad, cand)
                if score > best_score:
                    best_x, best_score = cand.tolist(), score
            x = best_x
        self._live[trial_id] = x
        return self._config_from_unit(x)

    def _record(self, trial_id: str, result: Optional[dict]):
        if not result or self.metric is None:
            return
        value = result.get(self.metric)
        x = self._live.get(trial_id)
        if value is None or x is None:
            return
        budget = int(result.get("training_iteration", 1))
        self._obs.setdefault(budget, []).append((x, float(value)))

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        # Every milestone report is a budget-tagged observation — this is
        # what lets low rungs seed the model before full-budget data
        # exists. The final result arrives through here too, so
        # on_trial_complete must NOT re-record it (the controller passes
        # the same merged dict — recording twice would double-weight the
        # point in the KDEs and double-count toward min_points).
        self._record(trial_id, result)

    def on_trial_complete(self, trial_id: str, result=None, error: bool = False) -> None:
        self._live.pop(trial_id, None)
