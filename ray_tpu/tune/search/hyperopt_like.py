"""Native model-based searcher (fills the role of the reference's wrapped BO
libraries — python/ray/tune/search/{hyperopt,bayesopt,optuna,...} — without
external dependencies).

TPE-flavoured: split observed trials into good/bad quantiles, then prefer
candidates (drawn from the raw space) whose numeric coordinates are nearer the
good set than the bad set. Falls back to pure random while fewer than
``n_initial_points`` observations exist.
"""

from __future__ import annotations

import math
import random

from ray_tpu.tune import sample as s
from ray_tpu.tune.search.searcher import Searcher


def _flatten(cfg: dict, prefix: str = "") -> dict:
    out = {}
    for k, v in cfg.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "."))
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            out[key] = float(v)
    return out


class HyperOptLikeSearch(Searcher):
    def __init__(
        self,
        space: dict | None = None,
        metric: str | None = None,
        mode: str = "max",
        n_initial_points: int = 5,
        n_candidates: int = 32,
        gamma: float = 0.25,
        seed: int | None = None,
    ):
        super().__init__(metric, mode)
        self.space = space or {}
        self.n_initial_points = n_initial_points
        self.n_candidates = n_candidates
        self.gamma = gamma
        self.rng = random.Random(seed)
        self._observed: list[tuple[dict, float]] = []  # (flat config, score)
        self._live: dict[str, dict] = {}

    def set_search_properties(self, metric, mode, config):
        super().set_search_properties(metric, mode, config)
        if config and not self.space:
            self.space = config
        return True

    def _score(self, result: dict) -> float | None:
        v = result.get(self.metric) if self.metric else None
        if v is None:
            return None
        return float(v) if self.mode == "max" else -float(v)

    def _distance(self, a: dict, b: dict, scales: dict) -> float:
        keys = set(a) | set(b)
        d = 0.0
        for k in keys:
            av, bv = a.get(k), b.get(k)
            if av is None or bv is None:
                d += 1.0
                continue
            sc = scales.get(k) or 1.0
            d += ((av - bv) / sc) ** 2
        return math.sqrt(d)

    def suggest(self, trial_id):
        cfg = s.resolve(self.space, self.rng)
        if len(self._observed) >= self.n_initial_points:
            ranked = sorted(self._observed, key=lambda t: -t[1])
            n_good = max(1, int(len(ranked) * self.gamma))
            good = [c for c, _ in ranked[:n_good]]
            bad = [c for c, _ in ranked[n_good:]] or good
            allv: dict[str, list[float]] = {}
            for c, _ in self._observed:
                for k, v in c.items():
                    allv.setdefault(k, []).append(v)
            scales = {
                k: (max(vs) - min(vs)) or 1.0 for k, vs in allv.items()
            }
            best, best_score = cfg, -math.inf
            for _ in range(self.n_candidates):
                cand = s.resolve(self.space, self.rng)
                flat = _flatten(cand)
                dg = min(self._distance(flat, g, scales) for g in good)
                db = min(self._distance(flat, b, scales) for b in bad)
                score = db - dg  # near good, far from bad
                if score > best_score:
                    best, best_score = cand, score
            cfg = best
        self._live[trial_id] = cfg
        return cfg

    def on_trial_complete(self, trial_id, result=None, error=False):
        cfg = self._live.pop(trial_id, None)
        if cfg is None or error or not result:
            return
        score = self._score(result)
        if score is not None:
            self._observed.append((_flatten(cfg), score))
