"""Repeater — evaluate each suggested config N times and report the mean.

Reference: python/ray/tune/search/repeater.py (Repeater + TrialGroup): wraps
a searcher so noisy objectives are averaged over `repeat` independent trials
before the underlying searcher learns from them.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.tune.search.searcher import Searcher


class _TrialGroup:
    def __init__(self, primary_id: str, config: dict, repeat: int):
        self.primary_id = primary_id
        self.config = config
        self.repeat = repeat
        self.scores: list[float] = []
        self.completed = 0

    def full(self) -> bool:
        return self.completed >= self.repeat


class Repeater(Searcher):
    def __init__(self, searcher: Searcher, repeat: int = 3, set_index: bool = True):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.repeat = repeat
        self.set_index = set_index
        self._groups: list[_TrialGroup] = []
        self._trial_group: dict[str, _TrialGroup] = {}
        self._current: _TrialGroup | None = None

    def set_search_properties(self, metric, mode, config):
        super().set_search_properties(metric, mode, config)
        return self.searcher.set_search_properties(metric, mode, config)

    def suggest(self, trial_id: str) -> dict | None:
        if self._current is None or self._current_assigned >= self.repeat:
            cfg = self.searcher.suggest(trial_id)
            if cfg is None:
                return None
            self._current = _TrialGroup(trial_id, cfg, self.repeat)
            self._current_assigned = 0
            self._groups.append(self._current)
        group = self._current
        self._trial_group[trial_id] = group
        cfg = dict(group.config)
        if self.set_index:
            cfg["__trial_index__"] = self._current_assigned
        self._current_assigned += 1
        return cfg

    _current_assigned = 0

    def on_trial_complete(self, trial_id: str, result=None, error: bool = False):
        group = self._trial_group.pop(trial_id, None)
        if group is None:
            return
        group.completed += 1
        if result and self.metric in result and not error:
            group.scores.append(float(result[self.metric]))
        if group.full():
            mean = float(np.mean(group.scores)) if group.scores else None
            self.searcher.on_trial_complete(
                group.primary_id,
                {self.metric: mean} if mean is not None else None,
                error=mean is None,
            )

    @property
    def total_samples(self):
        n = self.searcher.total_samples
        return n * self.repeat if n is not None else None
