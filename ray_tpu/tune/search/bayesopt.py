"""GP-based Bayesian optimization searcher.

Analog of the reference's BayesOptSearch wrapper (python/ray/tune/search/
bayesopt/bayesopt_search.py) — but self-contained on sklearn's
GaussianProcessRegressor instead of the external `bayesian-optimization`
package (not in this image): expected-improvement acquisition maximized over
random candidates, with Float/Integer/Categorical domains mapped to a unit
hypercube (categoricals one-hot-ish via index coordinates, log domains
searched in log space).
"""

from __future__ import annotations

import math
import random

import numpy as np

from ray_tpu.tune import sample as s
from ray_tpu.tune.search.searcher import Searcher


class _Dim:
    """One search dimension <-> one [0,1] coordinate."""

    def __init__(self, key: str, domain):
        self.key = key
        self.domain = domain

    def to_unit(self, value) -> float:
        d = self.domain
        if isinstance(d, s.Categorical):
            return d.categories.index(value) / max(len(d.categories) - 1, 1)
        lo, hi = float(d.lower), float(d.upper)
        if getattr(d, "log", False):
            return (math.log(float(value)) - math.log(lo)) / (math.log(hi) - math.log(lo))
        return (float(value) - lo) / (hi - lo)

    def from_unit(self, u: float):
        d = self.domain
        u = min(max(u, 0.0), 1.0)
        if isinstance(d, s.Categorical):
            return d.categories[int(round(u * (len(d.categories) - 1)))]
        lo, hi = float(d.lower), float(d.upper)
        if getattr(d, "log", False):
            v = math.exp(math.log(lo) + u * (math.log(hi) - math.log(lo)))
        else:
            v = lo + u * (hi - lo)
        if getattr(d, "q", None):
            v = round(v / d.q) * d.q
        if isinstance(d, s.Integer):
            return int(round(v))
        return float(v)


class BayesOptSearch(Searcher):
    def __init__(
        self,
        space: dict | None = None,
        metric: str | None = None,
        mode: str = "max",
        random_startup_trials: int = 5,
        candidates_per_suggest: int = 256,
        seed: int | None = None,
    ):
        super().__init__(metric, mode)
        self._space = space
        self._dims: list[_Dim] | None = None
        self.startup = random_startup_trials
        self.n_candidates = candidates_per_suggest
        self.rng = random.Random(seed)
        self._np_rng = np.random.default_rng(seed)
        self._X: list[list[float]] = []
        self._y: list[float] = []
        self._live: dict[str, list[float]] = {}

    def set_search_properties(self, metric, mode, config):
        super().set_search_properties(metric, mode, config)
        if self._space is None and config:
            self._space = config
        return True

    def _build_dims(self):
        assert self._space, "BayesOptSearch needs a param_space"
        self._dims = []
        self._passthrough = {}
        for key, dom in self._space.items():
            if isinstance(dom, (s.Float, s.Integer, s.Categorical)):
                self._dims.append(_Dim(key, dom))
            elif isinstance(dom, s.GridSearch):
                raise ValueError("grid_search is not supported by BayesOptSearch")
            else:
                self._passthrough[key] = dom
        if not self._dims:
            raise ValueError("param_space has no sampleable domains")

    def _config_from_unit(self, x: list[float]) -> dict:
        cfg = dict(self._passthrough)
        for dim, u in zip(self._dims, x):
            cfg[dim.key] = dim.from_unit(u)
        # sample_from markers resolve against the sampled values.
        for key, v in list(cfg.items()):
            if isinstance(v, s.SampleFrom):
                cfg[key] = v.func(s._Spec(cfg))
        return cfg

    def suggest(self, trial_id: str) -> dict | None:
        if self._dims is None:
            self._build_dims()
        d = len(self._dims)
        if len(self._X) < self.startup:
            x = [self.rng.random() for _ in range(d)]
        else:
            x = self._maximize_ei(d)
        self._live[trial_id] = x
        return self._config_from_unit(x)

    def _maximize_ei(self, d: int) -> list[float]:
        from sklearn.gaussian_process import GaussianProcessRegressor
        from sklearn.gaussian_process.kernels import ConstantKernel, Matern

        X = np.asarray(self._X)
        y = np.asarray(self._y)
        y_mu, y_sd = y.mean(), y.std() + 1e-9
        gp = GaussianProcessRegressor(
            kernel=ConstantKernel(1.0) * Matern(nu=2.5),
            alpha=1e-6,
            normalize_y=False,
            random_state=self.rng.randint(0, 1 << 31),
        )
        gp.fit(X, (y - y_mu) / y_sd)
        cand = self._np_rng.random((self.n_candidates, d))
        mu, sigma = gp.predict(cand, return_std=True)
        best = ((y - y_mu) / y_sd).max()
        from scipy.stats import norm  # scipy ships with sklearn's deps

        imp = mu - best - 0.01
        z = imp / np.maximum(sigma, 1e-9)
        ei = imp * norm.cdf(z) + sigma * norm.pdf(z)
        return [float(v) for v in cand[int(np.argmax(ei))]]

    def on_trial_complete(self, trial_id, result=None, error=False):
        x = self._live.pop(trial_id, None)
        if x is None or error or not result or self.metric not in result:
            return
        v = float(result[self.metric])
        self._X.append(x)
        self._y.append(v if self.mode == "max" else -v)
