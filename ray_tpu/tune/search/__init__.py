from ray_tpu.tune.search.searcher import (  # noqa: F401
    ConcurrencyLimiter,
    Searcher,
)
from ray_tpu.tune.search.basic_variant import BasicVariantGenerator  # noqa: F401
from ray_tpu.tune.search.bayesopt import BayesOptSearch  # noqa: F401
from ray_tpu.tune.search.gated import (  # noqa: F401
    AxSearch,
    DragonflySearch,
    HEBOSearch,
    HyperOptSearch,
    NevergradSearch,
    OptunaSearch,
    SigOptSearch,
    SkOptSearch,
    ZOOptSearch,
)
from ray_tpu.tune.search.bohb import TuneBOHB  # noqa: F401
from ray_tpu.tune.search.hyperopt_like import HyperOptLikeSearch  # noqa: F401
from ray_tpu.tune.search.repeater import Repeater  # noqa: F401
