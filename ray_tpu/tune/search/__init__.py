from ray_tpu.tune.search.searcher import (  # noqa: F401
    ConcurrencyLimiter,
    Searcher,
)
from ray_tpu.tune.search.basic_variant import BasicVariantGenerator  # noqa: F401
from ray_tpu.tune.search.hyperopt_like import HyperOptLikeSearch  # noqa: F401
