"""Gated external-searcher wrappers.

The reference wraps a dozen third-party optimizers (python/ray/tune/search/
{optuna,hyperopt,ax,bohb,dragonfly,flaml,hebo,nevergrad,sigopt,skopt,zoopt});
none of those packages are in this image, so each name constructs with a
clear install message (same behavior the reference shows when the backing
package is missing). BayesOptSearch (sklearn-GP) and HyperOptLikeSearch are
the in-image alternatives.
"""

from __future__ import annotations

from ray_tpu.tune.search.searcher import Searcher


def _gated(name: str, package: str):
    class _Gated(Searcher):
        def __init__(self, *a, **k):
            raise ImportError(
                f"{name} requires the '{package}' package, which is not "
                f"installed in this environment (pip install {package}). "
                "In-image alternatives: BayesOptSearch (sklearn GP) or "
                "HyperOptLikeSearch."
            )

    _Gated.__name__ = name
    return _Gated


OptunaSearch = _gated("OptunaSearch", "optuna")
HyperOptSearch = _gated("HyperOptSearch", "hyperopt")
AxSearch = _gated("AxSearch", "ax-platform")
DragonflySearch = _gated("DragonflySearch", "dragonfly-opt")
NevergradSearch = _gated("NevergradSearch", "nevergrad")
SigOptSearch = _gated("SigOptSearch", "sigopt")
SkOptSearch = _gated("SkOptSearch", "scikit-optimize")
ZOOptSearch = _gated("ZOOptSearch", "zoopt")
HEBOSearch = _gated("HEBOSearch", "HEBO")
