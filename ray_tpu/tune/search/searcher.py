"""Searcher base + ConcurrencyLimiter (analog of reference
python/ray/tune/search/{searcher.py,concurrency_limiter.py})."""

from __future__ import annotations


class Searcher:
    """Suggests configs; learns from completed trials."""

    def __init__(self, metric: str | None = None, mode: str = "max"):
        self.metric = metric
        self.mode = mode

    def set_search_properties(self, metric: str | None, mode: str | None, config: dict) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True

    def suggest(self, trial_id: str) -> dict | None:
        """Next config, or None = exhausted, or FINISHED sentinel."""
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        pass

    def on_trial_complete(self, trial_id: str, result: dict | None = None, error: bool = False) -> None:
        pass

    @property
    def total_samples(self) -> int | None:
        """Total trials this searcher will produce, if known."""
        return None


class ConcurrencyLimiter(Searcher):
    """Caps in-flight suggestions from the wrapped searcher."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set[str] = set()

    def set_search_properties(self, metric, mode, config):
        return self.searcher.set_search_properties(metric, mode, config)

    def suggest(self, trial_id):
        if len(self._live) >= self.max_concurrent:
            return None
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None:
            self._live.add(trial_id)
        return cfg

    def on_trial_result(self, trial_id, result):
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)

    @property
    def total_samples(self):
        return self.searcher.total_samples
