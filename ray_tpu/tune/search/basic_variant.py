"""Grid/random variant generation (analog of reference
python/ray/tune/search/basic_variant.py — grid_search cross-product ×
num_samples random repetitions)."""

from __future__ import annotations

import itertools
import random

from ray_tpu.tune import sample as s
from ray_tpu.tune.search.searcher import Searcher


class BasicVariantGenerator(Searcher):
    def __init__(self, param_space: dict | None = None, num_samples: int = 1, seed: int | None = None):
        super().__init__()
        self.param_space = param_space or {}
        self.num_samples = num_samples
        self.rng = random.Random(seed)
        self._queue: list[dict] | None = None
        self._idx = 0

    def set_search_properties(self, metric, mode, config):
        super().set_search_properties(metric, mode, config)
        if config:
            self.param_space = config
        return True

    def _materialise(self):
        axes = s.grid_axes(self.param_space)
        assignments: list[dict] = [{}]
        if axes:
            paths, value_lists = zip(*axes)
            assignments = [
                dict(zip(paths, combo)) for combo in itertools.product(*value_lists)
            ]
        self._queue = [
            s.resolve(self.param_space, self.rng, ga)
            for _ in range(self.num_samples)
            for ga in assignments
        ]

    def suggest(self, trial_id):
        if self._queue is None:
            self._materialise()
        if self._idx >= len(self._queue):
            return None
        cfg = self._queue[self._idx]
        self._idx += 1
        return cfg

    @property
    def total_samples(self):
        if self._queue is None:
            self._materialise()
        return len(self._queue)
