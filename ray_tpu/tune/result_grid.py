"""ResultGrid (analog of reference python/ray/tune/result_grid.py)."""

from __future__ import annotations

from ray_tpu.train.base_trainer import Result


class ResultGrid:
    def __init__(self, results: list[Result], trials: list | None = None,
                 default_metric: str | None = None, default_mode: str | None = None):
        self._results = results
        self._trials = trials or []
        self._default_metric = default_metric
        self._default_mode = default_mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self) -> list[str]:
        return [r.error for r in self._results if r.error]

    def get_best_result(self, metric: str | None = None, mode: str | None = None) -> Result:
        metric = metric or self._default_metric
        mode = mode or self._default_mode or "max"
        scored = [
            (r, r.metrics.get(metric)) for r in self._results if r.metrics.get(metric) is not None
        ]
        if not scored:
            ok = [r for r in self._results if not r.error]
            if ok:
                return ok[0]
            raise ValueError(f"no trial reported metric {metric!r}")
        sign = 1 if mode == "max" else -1
        return max(scored, key=lambda rv: sign * rv[1])[0]

    def get_dataframe(self):
        try:
            import pandas as pd
        except ImportError as e:  # pragma: no cover
            raise ImportError("pandas not available") from e
        return pd.DataFrame([dict(r.metrics, error=r.error) for r in self._results])
