"""Node providers.

Analog of the reference's pluggable NodeProvider
(python/ray/autoscaler/node_provider.py; fake test provider
autoscaler/_private/fake_multi_node/node_provider.py; GCP TPU provisioning
autoscaler/_private/gcp/node_provider.py + tpu.yaml): providers own the
machine lifecycle; the autoscaler only decides counts per node type.

``FakeMultiNodeProvider`` launches real worker-node processes on this host
(the multi-node-without-a-cluster trick) so autoscaling is testable
end-to-end. ``TPUPodProvider`` documents the GCE/TPU-VM shape but is gated —
this environment has no cloud egress.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import uuid


class NodeProvider:
    """Provider interface (create/terminate/list)."""

    def __init__(self, provider_config: dict, cluster_name: str):
        self.provider_config = provider_config
        self.cluster_name = cluster_name

    def non_terminated_nodes(self) -> list[str]:
        raise NotImplementedError

    def node_tags(self, node_id: str) -> dict:
        raise NotImplementedError

    def create_node(self, node_config: dict, tags: dict, count: int) -> list[str]:
        raise NotImplementedError

    def terminate_node(self, node_id: str):
        raise NotImplementedError

    def is_running(self, node_id: str) -> bool:
        return node_id in self.non_terminated_nodes()

    def shutdown(self):
        for nid in list(self.non_terminated_nodes()):
            self.terminate_node(nid)


class FakeMultiNodeProvider(NodeProvider):
    """Worker nodes as local subprocesses joining the head's GCS.

    Each created node runs ``python -m ray_tpu.scripts.scripts start
    --address <gcs> --block`` in its own session with the node type's
    resources, so the autoscaled "machines" are real raylets with real worker
    pools — exactly what the reference's fake_multi_node provider simulates.
    """

    def __init__(self, provider_config: dict, cluster_name: str):
        super().__init__(provider_config, cluster_name)
        self.gcs_address = provider_config["gcs_address"]  # "host:port"
        self._nodes: dict[str, dict] = {}  # provider node id -> {proc, tags}
        self._lock = threading.Lock()

    def non_terminated_nodes(self) -> list[str]:
        with self._lock:
            dead = [nid for nid, n in self._nodes.items() if n["proc"].poll() is not None]
            for nid in dead:
                del self._nodes[nid]
            return list(self._nodes)

    def node_tags(self, node_id: str) -> dict:
        with self._lock:
            node = self._nodes.get(node_id)
            return dict(node["tags"]) if node else {}

    def create_node(self, node_config: dict, tags: dict, count: int) -> list[str]:
        created = []
        for _ in range(count):
            nid = f"fake-{uuid.uuid4().hex[:8]}"
            resources = dict(node_config.get("resources", {}))
            num_cpus = resources.pop("CPU", 1)
            num_tpus = resources.pop("TPU", 0)
            cmd = [
                sys.executable,
                "-m",
                "ray_tpu.scripts.scripts",
                "start",
                "--address",
                self.gcs_address,
                "--num-cpus",
                str(int(num_cpus)),
                "--num-tpus",
                str(int(num_tpus)),
                # The label lets the autoscaler match this provider node to
                # its GCS node record exactly.
                "--labels",
                json.dumps({"provider_node_id": nid}),
                "--block",
            ]
            if resources:
                cmd += ["--resources", json.dumps(resources)]
            env = dict(os.environ)
            env["PYTHONPATH"] = (
                os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
                + os.pathsep
                + env.get("PYTHONPATH", "")
            )
            log_dir = "/tmp/ray_tpu/autoscaler_nodes"
            os.makedirs(log_dir, exist_ok=True)
            log_f = open(os.path.join(log_dir, f"{nid}.log"), "ab")
            try:
                proc = subprocess.Popen(
                    cmd, stdout=log_f, stderr=subprocess.STDOUT, env=env, start_new_session=True
                )
            finally:
                # The child inherited the fd; keeping the parent copy open
                # leaks one fd per launch in the monitor process.
                log_f.close()
            with self._lock:
                self._nodes[nid] = {"proc": proc, "tags": dict(tags), "created": time.time()}
            created.append(nid)
        return created

    def terminate_node(self, node_id: str):
        with self._lock:
            node = self._nodes.pop(node_id, None)
        if node is None:
            return
        proc = node["proc"]
        if proc.poll() is None:
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
            except Exception:
                proc.terminate()
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()


class TPUPodProvider(NodeProvider):
    """TPU pod-slice provisioning via GCE TPU-VM API (reference:
    autoscaler/_private/gcp/node_provider.py + autoscaler/gcp/tpu.yaml).

    Each node type maps to an ``accelerator_type`` (e.g. ``v5e-8``) and one
    created "node" is one TPU VM worker of a slice. Gated: requires cloud
    credentials and network egress, neither of which exist in this
    environment — instantiating raises with setup instructions.
    """

    def __init__(self, provider_config: dict, cluster_name: str):
        raise RuntimeError(
            "TPUPodProvider requires GCP credentials and network egress. "
            "Configure provider.type=fake for local testing, or run on a GCP "
            "project with the TPU API enabled (fields: project_id, zone, "
            "accelerator_type, runtime_version)."
        )
