"""Node providers.

Analog of the reference's pluggable NodeProvider
(python/ray/autoscaler/node_provider.py; fake test provider
autoscaler/_private/fake_multi_node/node_provider.py; GCP TPU provisioning
autoscaler/_private/gcp/node_provider.py + tpu.yaml): providers own the
machine lifecycle; the autoscaler only decides counts per node type.

``FakeMultiNodeProvider`` launches real worker-node processes on this host
(the multi-node-without-a-cluster trick) so autoscaling is testable
end-to-end. ``TPUPodProvider`` implements the GCE TPU-VM REST surface
(create + operation polling, list-by-label, delete) with an injectable
endpoint/token so it runs against a mock TPU API in tests; real use needs
credentials and egress.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import uuid


DEFAULT_STARTUP_TEMPLATE = (
    "#! /bin/bash\n"
    "python -m ray_tpu.scripts.scripts start --address {gcs_address} "
    "--labels '{{\"provider_node_id\": \"{node_id}\"}}' --block\n"
)


def bearer_json_request(
    method: str, url: str, body: dict | None = None, token: str | None = None,
    timeout: float = 60.0,
) -> dict:
    """JSON-over-HTTP with optional bearer auth — the one REST transport
    shared by every GCE-style provider (TPU pods, GCE VMs, Azure ARM)."""
    import urllib.request

    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    req.add_header("Content-Type", "application/json")
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        payload = resp.read()
    return json.loads(payload) if payload else {}


class NodeProvider:
    """Provider interface (create/terminate/list)."""

    def __init__(self, provider_config: dict, cluster_name: str):
        self.provider_config = provider_config
        self.cluster_name = cluster_name

    def non_terminated_nodes(self) -> list[str]:
        raise NotImplementedError

    def node_tags(self, node_id: str) -> dict:
        raise NotImplementedError

    def create_node(self, node_config: dict, tags: dict, count: int) -> list[str]:
        raise NotImplementedError

    def terminate_node(self, node_id: str):
        raise NotImplementedError

    def is_running(self, node_id: str) -> bool:
        return node_id in self.non_terminated_nodes()

    def shutdown(self):
        for nid in list(self.non_terminated_nodes()):
            self.terminate_node(nid)


class FakeMultiNodeProvider(NodeProvider):
    """Worker nodes as local subprocesses joining the head's GCS.

    Each created node runs ``python -m ray_tpu.scripts.scripts start
    --address <gcs> --block`` in its own session with the node type's
    resources, so the autoscaled "machines" are real raylets with real worker
    pools — exactly what the reference's fake_multi_node provider simulates.
    """

    def __init__(self, provider_config: dict, cluster_name: str):
        super().__init__(provider_config, cluster_name)
        self.gcs_address = provider_config["gcs_address"]  # "host:port"
        self._nodes: dict[str, dict] = {}  # provider node id -> {proc, tags}
        self._lock = threading.Lock()

    def non_terminated_nodes(self) -> list[str]:
        with self._lock:
            dead = [nid for nid, n in self._nodes.items() if n["proc"].poll() is not None]
            for nid in dead:
                del self._nodes[nid]
            return list(self._nodes)

    def node_tags(self, node_id: str) -> dict:
        with self._lock:
            node = self._nodes.get(node_id)
            return dict(node["tags"]) if node else {}

    def create_node(self, node_config: dict, tags: dict, count: int) -> list[str]:
        created = []
        for _ in range(count):
            nid = f"fake-{uuid.uuid4().hex[:8]}"
            resources = dict(node_config.get("resources", {}))
            num_cpus = resources.pop("CPU", 1)
            num_tpus = resources.pop("TPU", 0)
            cmd = [
                sys.executable,
                "-m",
                "ray_tpu.scripts.scripts",
                "start",
                "--address",
                self.gcs_address,
                "--num-cpus",
                str(int(num_cpus)),
                "--num-tpus",
                str(int(num_tpus)),
                # The label lets the autoscaler match this provider node to
                # its GCS node record exactly.
                "--labels",
                json.dumps({"provider_node_id": nid}),
                "--block",
            ]
            if resources:
                cmd += ["--resources", json.dumps(resources)]
            env = dict(os.environ)
            env["PYTHONPATH"] = (
                os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
                + os.pathsep
                + env.get("PYTHONPATH", "")
            )
            log_dir = "/tmp/ray_tpu/autoscaler_nodes"
            os.makedirs(log_dir, exist_ok=True)
            log_f = open(os.path.join(log_dir, f"{nid}.log"), "ab")
            try:
                proc = subprocess.Popen(
                    cmd, stdout=log_f, stderr=subprocess.STDOUT, env=env, start_new_session=True
                )
            finally:
                # The child inherited the fd; keeping the parent copy open
                # leaks one fd per launch in the monitor process.
                log_f.close()
            with self._lock:
                self._nodes[nid] = {"proc": proc, "tags": dict(tags), "created": time.time()}
            created.append(nid)
        return created

    def terminate_node(self, node_id: str):
        with self._lock:
            node = self._nodes.pop(node_id, None)
        if node is None:
            return
        proc = node["proc"]
        if proc.poll() is None:
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
            except Exception:
                proc.terminate()
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()


class TPUPodProvider(NodeProvider):
    """TPU pod-slice provisioning via the GCE TPU-VM REST API (reference:
    autoscaler/_private/gcp/node_provider.py + autoscaler/gcp/tpu.yaml).

    Each node type maps to an ``accelerator_type`` (e.g. ``v5e-8``); one
    created "node" is one TPU VM slice. The API endpoint and token source
    are injectable so the provider is exercised end-to-end against a mock
    TPU API in tests (create -> operation poll -> READY, list-by-label,
    delete); against the real service it needs credentials + egress.

    provider_config fields: project_id, zone, and optionally api_endpoint
    (default https://tpu.googleapis.com), api_version (v2), access_token /
    _token_provider (callable), poll_interval_s, create_timeout_s.
    """

    def __init__(self, provider_config: dict, cluster_name: str):
        super().__init__(provider_config, cluster_name)
        self.project = provider_config["project_id"]
        self.zone = provider_config["zone"]
        self.endpoint = provider_config.get("api_endpoint", "https://tpu.googleapis.com").rstrip("/")
        version = provider_config.get("api_version", "v2")
        self.base = f"{self.endpoint}/{version}/projects/{self.project}/locations/{self.zone}"
        self._token_provider = provider_config.get("_token_provider")
        self._token = provider_config.get("access_token")
        self.poll_interval_s = provider_config.get("poll_interval_s", 2.0)
        self.create_timeout_s = provider_config.get("create_timeout_s", 600.0)
        # Block create_node until slices are READY (tests); the autoscaler
        # path leaves this False — CREATING nodes already count as alive and
        # boot-timeout recycling handles stuck creations, so a tick must not
        # freeze for minutes inside the provider.
        self.wait_for_ready = provider_config.get("wait_for_ready", False)
        self._tags_cache: dict[str, dict] = {}
        # Bootstrap: without a startup script the created VM never runs
        # `ray_tpu start` and can never register — the autoscaler would then
        # recycle (billable) slices forever on boot timeout. Template fields:
        # {node_id}, {gcs_address}.
        self.startup_script_template = provider_config.get(
            "startup_script_template", DEFAULT_STARTUP_TEMPLATE
        )
        self.gcs_address_for_workers = provider_config.get("gcs_address", "")
        if self.endpoint == "https://tpu.googleapis.com" and not (self._token or self._token_provider):
            raise RuntimeError(
                "TPUPodProvider against the real TPU API needs credentials: "
                "pass access_token or _token_provider in the provider config "
                "(or api_endpoint for a test/mock API)."
            )

    # -- HTTP plumbing -------------------------------------------------

    def _request(self, method: str, path: str, body: dict | None = None) -> dict:
        url = path if path.startswith("http") else self.base + path
        token = self._token_provider() if self._token_provider else self._token
        return bearer_json_request(method, url, body, token)

    def _op_url(self, name: str) -> str:
        # Operation names come back WITHOUT the API version segment
        # ("projects/P/locations/Z/operations/ID"); the poll URL needs it.
        if name.startswith("http"):
            return name
        return f"{self.base.split('/projects/')[0]}/{name.lstrip('/')}"

    def _wait_operations(self, ops: list[dict]) -> None:
        """Poll a batch of operations round-robin until all complete — total
        wall time tracks the SLOWEST operation, not the sum."""
        import time as _time

        deadline = _time.monotonic() + self.create_timeout_s
        pending = [op for op in ops if not op.get("done")]
        while pending:
            if _time.monotonic() > deadline:
                raise TimeoutError(f"TPU operations timed out: {[o.get('name') for o in pending]}")
            _time.sleep(self.poll_interval_s)
            refreshed = [self._request("GET", self._op_url(op["name"])) for op in pending]
            for op in refreshed:
                if op.get("error"):
                    raise RuntimeError(f"TPU operation failed: {op['error']}")
            pending = [op for op in refreshed if not op.get("done")]

    # -- NodeProvider API ----------------------------------------------

    def _list_nodes(self) -> list[dict]:
        resp = self._request("GET", "/nodes")
        nodes = [
            n for n in resp.get("nodes", [])
            if n.get("labels", {}).get("ray-cluster-name") == self.cluster_name
        ]
        # Labels are immutable after create: cache them from the list call so
        # node_tags doesn't add an N+1 GET per node per autoscaler tick. The
        # cache is REPLACED wholesale — deleted nodes drop out instead of
        # accumulating (and serving stale tags) forever.
        self._tags_cache = {
            n["name"].rsplit("/", 1)[-1]: dict(n.get("labels", {})) for n in nodes
        }
        return nodes

    def non_terminated_nodes(self) -> list[str]:
        return [
            n["name"].rsplit("/", 1)[-1]
            for n in self._list_nodes()
            if n.get("state") in ("CREATING", "READY", "RESTARTING", "STARTING")
        ]

    def node_tags(self, node_id: str) -> dict:
        import urllib.error

        cached = self._tags_cache.get(node_id)
        if cached is not None:
            return dict(cached)
        try:
            n = self._request("GET", f"/nodes/{node_id}")
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return {}  # deleted out-of-band mid-tick; don't abort the tick
            raise
        tags = dict(n.get("labels", {}))
        self._tags_cache[node_id] = tags
        return tags

    def create_node(self, node_config: dict, tags: dict, count: int) -> list[str]:
        import uuid

        # The autoscaler passes the whole node-type dict; provider-specific
        # fields live under its "node_config" key (same shape the reference's
        # GCP provider consumes). A flat dict (direct use) also works.
        conf = node_config.get("node_config", node_config)
        created, ops = [], []
        node_type = tags.get("node_type") or tags.get("ray-node-type", "worker")
        for _ in range(count):
            # uuid suffix: an in-memory counter would collide with live nodes
            # after an autoscaler restart (real API: 409 ALREADY_EXISTS).
            node_id = f"{self.cluster_name}-{node_type}-{uuid.uuid4().hex[:8]}"
            labels = {k.replace(":", "_"): v for k, v in tags.items()}
            labels["ray-cluster-name"] = self.cluster_name
            labels["provider_node_id"] = node_id  # autoscaler matches on this
            body = {
                "acceleratorType": conf.get("accelerator_type", "v5e-8"),
                "runtimeVersion": conf.get("runtime_version", "tpu-ubuntu2204-base"),
                "labels": labels,
            }
            if self.gcs_address_for_workers:
                # Literal replacement, not str.format: shell scripts are full
                # of braces (${VAR}, $(...){...}) that .format would choke on.
                script = (
                    self.startup_script_template
                    .replace("{node_id}", node_id)
                    .replace("{gcs_address}", self.gcs_address_for_workers)
                )
                body["metadata"] = {"startup-script": script}
            if conf.get("network_config"):
                body["networkConfig"] = conf["network_config"]
            ops.append(self._request("POST", f"/nodes?nodeId={node_id}", body))
            created.append(node_id)
        if self.wait_for_ready:
            self._wait_operations(ops)
        return created

    def terminate_node(self, node_id: str):
        import urllib.error

        self._tags_cache.pop(node_id, None)
        try:
            op = self._request("DELETE", f"/nodes/{node_id}")
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return  # already gone (deleted out-of-band) — not an error
            raise
        if self.wait_for_ready:
            self._wait_operations([op])

    def is_running(self, node_id: str) -> bool:
        try:
            n = self._request("GET", f"/nodes/{node_id}")
        except Exception:
            return False
        return n.get("state") == "READY"
