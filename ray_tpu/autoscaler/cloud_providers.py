"""Cloud VM node providers: AWS EC2, GCP GCE, Azure ARM.

Analogs of the reference's provider tree (python/ray/autoscaler/_private/
aws/node_provider.py, gcp/node_provider.py, _azure/node_provider.py). The
reference leans on boto3 / google-api-python-client / azure-mgmt; none of
those SDKs are in this image, so each provider speaks its cloud's public
HTTP API directly over urllib:

- ``AWSNodeProvider`` — EC2 Query API (RunInstances / DescribeInstances /
  TerminateInstances, XML responses) with a self-contained SigV4 request
  signer (hmac+hashlib; no SDK needed).
- ``GCENodeProvider`` — GCE compute REST (instances insert/list/delete,
  zone-operation polling) with bearer-token auth.
- ``AzureNodeProvider`` — ARM REST (virtualMachines PUT/GET/DELETE,
  api-version pinned) with bearer-token auth.

All three take an injectable ``api_endpoint`` (tests run them end-to-end
against in-process mock APIs — create, list-by-tag, tags, terminate) and an
injectable credential source; real use needs credentials and egress. Nodes
bootstrap with a startup script that runs ``ray_tpu start --address <gcs>``
labeled with ``provider_node_id``, the tag the autoscaler matches GCS node
records against (same contract as TPUPodProvider / FakeMultiNodeProvider).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import logging
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid
import xml.etree.ElementTree as ET

from ray_tpu.autoscaler.node_provider import (
    DEFAULT_STARTUP_TEMPLATE,
    NodeProvider,
    bearer_json_request,
)

logger = logging.getLogger(__name__)


def _render_startup(template: str, node_id: str, gcs_address: str) -> str:
    # Literal replacement, not str.format: shell scripts are full of braces
    # (${VAR}, $(...){...}) that .format would choke on.
    return template.replace("{node_id}", node_id).replace("{gcs_address}", gcs_address)


class _CloudProviderBase(NodeProvider):
    """Shared config plumbing: endpoint, startup script, tag cache."""

    def __init__(self, provider_config: dict, cluster_name: str):
        super().__init__(provider_config, cluster_name)
        self.gcs_address_for_workers = provider_config.get("gcs_address", "")
        self.startup_script_template = provider_config.get(
            "startup_script_template", DEFAULT_STARTUP_TEMPLATE
        )
        self.poll_interval_s = provider_config.get("poll_interval_s", 2.0)
        self.create_timeout_s = provider_config.get("create_timeout_s", 600.0)
        # Tests block until creation lands; autoscaler ticks must not.
        self.wait_for_ready = provider_config.get("wait_for_ready", False)
        self._tags_cache: dict[str, dict] = {}
        self._token_provider = provider_config.get("_token_provider")
        self._token = provider_config.get("access_token")

    def _bearer_token(self) -> str | None:
        return self._token_provider() if self._token_provider else self._token

    def _startup(self, node_id: str) -> str:
        return _render_startup(
            self.startup_script_template, node_id, self.gcs_address_for_workers
        )

    def node_tags(self, node_id: str) -> dict:
        cached = self._tags_cache.get(node_id)
        if cached is None:
            self.non_terminated_nodes()  # refreshes the cache via one list call
            cached = self._tags_cache.get(node_id, {})
        return dict(cached)


# ---------------------------------------------------------------------------
# AWS
# ---------------------------------------------------------------------------


def _sigv4_headers(
    method: str,
    url: str,
    body: bytes,
    region: str,
    service: str,
    access_key: str,
    secret_key: str,
    session_token: str | None = None,
    now: time.struct_time | None = None,
) -> dict:
    """AWS Signature Version 4 (public spec), self-contained.

    Returns the headers to attach (x-amz-date, authorization, and the
    content-type/security-token that participate in signing).
    """
    t = now or time.gmtime()
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", t)
    datestamp = time.strftime("%Y%m%d", t)
    parts = urllib.parse.urlsplit(url)
    headers = {
        "content-type": "application/x-www-form-urlencoded; charset=utf-8",
        "host": parts.netloc,
        "x-amz-date": amz_date,
    }
    if session_token:
        headers["x-amz-security-token"] = session_token
    signed_names = ";".join(sorted(headers))
    canonical_headers = "".join(f"{k}:{headers[k].strip()}\n" for k in sorted(headers))
    canonical_request = "\n".join(
        [
            method,
            urllib.parse.quote(parts.path or "/"),
            parts.query,
            canonical_headers,
            signed_names,
            hashlib.sha256(body).hexdigest(),
        ]
    )
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join(
        [
            "AWS4-HMAC-SHA256",
            amz_date,
            scope,
            hashlib.sha256(canonical_request.encode()).hexdigest(),
        ]
    )
    key = f"AWS4{secret_key}".encode()
    for part in (datestamp, region, service, "aws4_request"):
        key = hmac.new(key, part.encode(), hashlib.sha256).digest()
    signature = hmac.new(key, string_to_sign.encode(), hashlib.sha256).hexdigest()
    out = {k: v for k, v in headers.items() if k != "host"}  # urllib sets Host
    out["authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={signed_names}, Signature={signature}"
    )
    return out


class AWSNodeProvider(_CloudProviderBase):
    """EC2 instances via the Query API (reference: _private/aws/node_provider.py).

    provider_config: region, access_key + secret_key (and optional
    session_token) or _credentials_provider (callable -> (ak, sk, token)),
    api_endpoint (default https://ec2.{region}.amazonaws.com — inject a mock
    in tests), api_version, gcs_address, startup_script_template.
    Node-type node_config: instance_type, image_id, subnet_id, and any
    literal ``Param.N``-style extras under "query_extras".
    """

    _API_VERSION = "2016-11-15"

    def __init__(self, provider_config: dict, cluster_name: str):
        super().__init__(provider_config, cluster_name)
        self.region = provider_config.get("region", "us-west-2")
        self.endpoint = provider_config.get(
            "api_endpoint", f"https://ec2.{self.region}.amazonaws.com"
        ).rstrip("/")
        self.api_version = provider_config.get("api_version", self._API_VERSION)
        self._creds_provider = provider_config.get("_credentials_provider")
        self._access_key = provider_config.get("access_key", "")
        self._secret_key = provider_config.get("secret_key", "")
        self._session_token = provider_config.get("session_token")
        self._instance_ids: dict[str, str] = {}  # provider node id -> EC2 id
        if self.endpoint.endswith(".amazonaws.com") and not (
            self._creds_provider or (self._access_key and self._secret_key)
        ):
            raise RuntimeError(
                "AWSNodeProvider against the real EC2 API needs credentials: "
                "pass access_key/secret_key or _credentials_provider (or "
                "api_endpoint for a test/mock API)."
            )

    def _call(self, action: str, params: dict) -> ET.Element:
        form = {"Action": action, "Version": self.api_version}
        form.update(params)
        body = urllib.parse.urlencode(sorted(form.items())).encode()
        if self._creds_provider:
            ak, sk, tok = self._creds_provider()
        else:
            ak, sk, tok = self._access_key, self._secret_key, self._session_token
        headers = _sigv4_headers(
            "POST", self.endpoint + "/", body, self.region, "ec2", ak, sk, tok
        )
        req = urllib.request.Request(self.endpoint + "/", data=body, method="POST")
        for k, v in headers.items():
            req.add_header(k, v)
        with urllib.request.urlopen(req, timeout=60) as resp:
            payload = resp.read()
        root = ET.fromstring(payload)
        # EC2 XML carries a default namespace; strip it so find() stays sane.
        for el in root.iter():
            if "}" in el.tag:
                el.tag = el.tag.split("}", 1)[1]
        return root

    @staticmethod
    def _tag_params(prefix: str, tags: dict) -> dict:
        params = {}
        for i, (k, v) in enumerate(sorted(tags.items()), start=1):
            params[f"{prefix}.Tag.{i}.Key"] = k
            params[f"{prefix}.Tag.{i}.Value"] = str(v)
        return params

    def _list_instances(self) -> list[dict]:
        """Nodes keyed by their provider_node_id tag — NOT the EC2 instance
        id. The autoscaler matches provider node ids against the
        ``provider_node_id`` label worker raylets register with (stamped
        into UserData before the instance id exists), so the tag value must
        BE the node id everywhere; ``_instance_ids`` maps back to the EC2
        id for terminate calls."""
        root = self._call(
            "DescribeInstances",
            {
                "Filter.1.Name": "tag:ray-cluster-name",
                "Filter.1.Value.1": self.cluster_name,
            },
        )
        out = []
        for inst in root.iter("instancesSet"):
            for item in inst.findall("item"):
                iid = item.findtext("instanceId")
                state = item.findtext("instanceState/name") or ""
                tags = {
                    t.findtext("key"): t.findtext("value")
                    for t in item.findall("tagSet/item")
                }
                nid = tags.get("provider_node_id") or iid
                out.append({"id": nid, "instance_id": iid, "state": state, "tags": tags})
        self._tags_cache = {n["id"]: n["tags"] for n in out}
        # Merge, don't replace: a just-created instance can be missing from
        # an eventually-consistent DescribeInstances response, and dropping
        # its mapping would leave terminate_node without the EC2 id.
        self._instance_ids.update({n["id"]: n["instance_id"] for n in out})
        return out

    def non_terminated_nodes(self) -> list[str]:
        return [
            n["id"]
            for n in self._list_instances()
            if n["state"] in ("pending", "running")
        ]

    def create_node(self, node_config: dict, tags: dict, count: int) -> list[str]:
        conf = node_config.get("node_config", node_config)
        node_type = tags.get("node_type") or tags.get("ray-node-type", "worker")
        all_tags = dict(tags)
        all_tags["ray-cluster-name"] = self.cluster_name
        created = []
        for _ in range(count):
            node_id = f"{self.cluster_name}-{node_type}-{uuid.uuid4().hex[:8]}"
            per_node = dict(all_tags)
            per_node["provider_node_id"] = node_id
            per_node["Name"] = node_id
            params = {
                "ImageId": conf.get("image_id", "ami-ray-tpu"),
                "InstanceType": conf.get("instance_type", "m5.large"),
                "MinCount": "1",
                "MaxCount": "1",
                "TagSpecification.1.ResourceType": "instance",
            }
            params.update(self._tag_params("TagSpecification.1", per_node))
            if conf.get("subnet_id"):
                params["SubnetId"] = conf["subnet_id"]
            if self.gcs_address_for_workers:
                params["UserData"] = base64.b64encode(
                    self._startup(node_id).encode()
                ).decode()
            params.update(conf.get("query_extras", {}))
            root = self._call("RunInstances", params)
            iid = root.findtext(".//instancesSet/item/instanceId")
            if not iid:
                raise RuntimeError("RunInstances returned no instanceId")
            # The generated id (== provider_node_id tag == what the booted
            # raylet registers with) is the provider node id; the EC2
            # instance id stays an internal detail for terminate calls.
            created.append(node_id)
            self._instance_ids[node_id] = iid
            self._tags_cache[node_id] = per_node
        if self.wait_for_ready:
            self._wait_running(created)
        return created

    def _wait_running(self, ids: list[str]):
        deadline = time.monotonic() + self.create_timeout_s
        pending = set(ids)
        while pending:
            if time.monotonic() > deadline:
                raise TimeoutError(f"EC2 instances not running: {sorted(pending)}")
            time.sleep(self.poll_interval_s)
            states = {n["id"]: n["state"] for n in self._list_instances()}
            pending = {i for i in pending if states.get(i) != "running"}

    def terminate_node(self, node_id: str):
        iid = self._instance_ids.get(node_id)
        if iid is None:
            self._list_instances()  # refresh the id map (autoscaler restart)
            iid = self._instance_ids.get(node_id)
        if iid is None and node_id.startswith("i-"):
            iid = node_id  # caller already holds a raw EC2 id
        self._tags_cache.pop(node_id, None)
        self._instance_ids.pop(node_id, None)
        if iid is None:
            # Unknown to EC2 (already terminated + aged out of Describe):
            # sending the provider node id would be InvalidInstanceID —
            # treat like the 404 path of the other providers.
            logger.warning("terminate_node: no EC2 instance id for %s; skipping", node_id)
            return
        self._call("TerminateInstances", {"InstanceId.1": iid})

    def is_running(self, node_id: str) -> bool:
        states = {n["id"]: n["state"] for n in self._list_instances()}
        return states.get(node_id) == "running"


# ---------------------------------------------------------------------------
# GCP (GCE VMs; TPU pod slices live in node_provider.TPUPodProvider)
# ---------------------------------------------------------------------------


def _gce_safe(value: str, max_len: int = 63, name: bool = False) -> str:
    """GCE labels must match ``[a-z0-9_-]{0,63}``; instance NAMES are
    stricter — ``[a-z]([-a-z0-9]*[a-z0-9])?`` (no underscores, must start
    with a letter). Lowercase and replace everything else with '-'."""
    allowed = "-" if name else "-_"
    out = "".join(c if c.isalnum() or c in allowed else "-" for c in str(value).lower())
    if name and (not out or not out[0].isalpha()):
        out = "ray-" + out
    return out[:max_len]


class GCENodeProvider(_CloudProviderBase):
    """GCE VM instances via the compute REST API (reference:
    _private/gcp/node_provider.py, compute path).

    provider_config: project_id, zone, access_token or _token_provider,
    api_endpoint (default https://compute.googleapis.com — inject a mock in
    tests), gcs_address. Node-type node_config: machine_type, image,
    disk_size_gb, network.
    """

    def __init__(self, provider_config: dict, cluster_name: str):
        super().__init__(provider_config, cluster_name)
        self.project = provider_config["project_id"]
        self.zone = provider_config["zone"]
        endpoint = provider_config.get(
            "api_endpoint", "https://compute.googleapis.com"
        ).rstrip("/")
        self.base = f"{endpoint}/compute/v1/projects/{self.project}/zones/{self.zone}"
        if endpoint == "https://compute.googleapis.com" and not (
            self._token or self._token_provider
        ):
            raise RuntimeError(
                "GCENodeProvider against the real compute API needs credentials: "
                "pass access_token or _token_provider (or api_endpoint for a "
                "test/mock API)."
            )

    def _request(self, method: str, path: str, body: dict | None = None) -> dict:
        url = path if path.startswith("http") else self.base + path
        return bearer_json_request(method, url, body, self._bearer_token())

    def _list_nodes(self) -> list[dict]:
        resp = self._request(
            "GET",
            "/instances?filter="
            + urllib.parse.quote(f"labels.ray-cluster-name={_gce_safe(self.cluster_name)}"),
        )
        items = resp.get("items", [])
        cache = {}
        for n in items:
            labels = dict(n.get("labels", {}))
            # Labels are _gce_safe-sanitized; the ORIGINAL node_type (which
            # must match config["node_types"] keys exactly for autoscaler
            # reconciliation) rides free-form instance metadata.
            for item in (n.get("metadata") or {}).get("items", []):
                if item.get("key") == "ray-node-type":
                    labels["node_type"] = item.get("value", labels.get("node_type"))
            cache[n["name"]] = labels
        self._tags_cache = cache
        return items

    def non_terminated_nodes(self) -> list[str]:
        return [
            n["name"]
            for n in self._list_nodes()
            if n.get("status") in ("PROVISIONING", "STAGING", "RUNNING")
        ]

    def create_node(self, node_config: dict, tags: dict, count: int) -> list[str]:
        conf = node_config.get("node_config", node_config)
        node_type = tags.get("node_type") or tags.get("ray-node-type", "worker")
        created, ops = [], []
        for _ in range(count):
            # The generated name IS the provider node id AND the
            # provider_node_id label value, so it must already be GCE-safe
            # (and the sanitized cluster label must match the list filter).
            node_id = _gce_safe(
                f"{self.cluster_name}-{node_type}-{uuid.uuid4().hex[:8]}", name=True
            )
            labels = {_gce_safe(k): _gce_safe(v) for k, v in tags.items()}
            labels["ray-cluster-name"] = _gce_safe(self.cluster_name)
            labels["provider_node_id"] = node_id
            machine_type = conf.get("machine_type", "n2-standard-8")
            body = {
                "name": node_id,
                "machineType": f"zones/{self.zone}/machineTypes/{machine_type}",
                "labels": labels,
                "disks": [
                    {
                        "boot": True,
                        "autoDelete": True,
                        "initializeParams": {
                            "sourceImage": conf.get(
                                "image", "projects/debian-cloud/global/images/family/debian-12"
                            ),
                            "diskSizeGb": str(conf.get("disk_size_gb", 100)),
                        },
                    }
                ],
                "networkInterfaces": [
                    {"network": conf.get("network", "global/networks/default")}
                ],
            }
            meta_items = [{"key": "ray-node-type", "value": node_type}]
            if self.gcs_address_for_workers:
                meta_items.append(
                    {"key": "startup-script", "value": self._startup(node_id)}
                )
            body["metadata"] = {"items": meta_items}
            ops.append(self._request("POST", "/instances", body))
            created.append(node_id)
            labels["node_type"] = node_type  # original, metadata-backed
            self._tags_cache[node_id] = labels
        if self.wait_for_ready:
            self._wait_operations(ops)
        return created

    def _wait_operations(self, ops: list[dict]):
        deadline = time.monotonic() + self.create_timeout_s
        pending = [op for op in ops if op.get("status") != "DONE"]
        while pending:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"GCE operations timed out: {[o.get('name') for o in pending]}"
                )
            time.sleep(self.poll_interval_s)
            refreshed = [
                self._request("GET", f"/operations/{op['name']}") for op in pending
            ]
            for op in refreshed:
                if op.get("error"):
                    raise RuntimeError(f"GCE operation failed: {op['error']}")
            pending = [op for op in refreshed if op.get("status") != "DONE"]

    def terminate_node(self, node_id: str):
        self._tags_cache.pop(node_id, None)
        try:
            self._request("DELETE", f"/instances/{node_id}")
        except urllib.error.HTTPError as e:
            if e.code != 404:  # already gone — not an error
                raise

    def is_running(self, node_id: str) -> bool:
        try:
            n = self._request("GET", f"/instances/{node_id}")
        except Exception:
            return False
        return n.get("status") == "RUNNING"


# ---------------------------------------------------------------------------
# Azure
# ---------------------------------------------------------------------------


class AzureNodeProvider(_CloudProviderBase):
    """Azure VMs via the ARM REST API (reference: _private/_azure/
    node_provider.py; the reference drives ARM templates via azure-mggmt —
    here the virtualMachines resource surface directly).

    provider_config: subscription_id, resource_group, location, access_token
    or _token_provider, api_endpoint (default https://management.azure.com —
    inject a mock in tests), gcs_address. Node-type node_config: vm_size,
    image (ARM imageReference dict), admin_username.
    """

    _API = "api-version=2023-03-01"

    def __init__(self, provider_config: dict, cluster_name: str):
        super().__init__(provider_config, cluster_name)
        self.subscription = provider_config["subscription_id"]
        self.resource_group = provider_config["resource_group"]
        self.location = provider_config.get("location", "westus2")
        endpoint = provider_config.get(
            "api_endpoint", "https://management.azure.com"
        ).rstrip("/")
        self.base = (
            f"{endpoint}/subscriptions/{self.subscription}/resourceGroups/"
            f"{self.resource_group}/providers/Microsoft.Compute/virtualMachines"
        )
        if endpoint == "https://management.azure.com" and not (
            self._token or self._token_provider
        ):
            raise RuntimeError(
                "AzureNodeProvider against the real ARM API needs credentials: "
                "pass access_token or _token_provider (or api_endpoint for a "
                "test/mock API)."
            )

    def _request(self, method: str, url: str, body: dict | None = None) -> dict:
        return bearer_json_request(method, url, body, self._bearer_token())

    def _list_nodes(self) -> list[dict]:
        resp = self._request("GET", f"{self.base}?{self._API}")
        vms = [
            vm
            for vm in resp.get("value", [])
            if (vm.get("tags") or {}).get("ray-cluster-name") == self.cluster_name
        ]
        self._tags_cache = {vm["name"]: dict(vm.get("tags") or {}) for vm in vms}
        return vms

    def non_terminated_nodes(self) -> list[str]:
        return [
            vm["name"]
            for vm in self._list_nodes()
            if (vm.get("properties") or {}).get("provisioningState")
            in ("Creating", "Updating", "Succeeded")
        ]

    def create_node(self, node_config: dict, tags: dict, count: int) -> list[str]:
        conf = node_config.get("node_config", node_config)
        node_type = tags.get("node_type") or tags.get("ray-node-type", "worker")
        created = []
        for _ in range(count):
            node_id = f"{self.cluster_name}-{node_type}-{uuid.uuid4().hex[:8]}"
            vm_tags = {str(k): str(v) for k, v in tags.items()}
            vm_tags["ray-cluster-name"] = self.cluster_name
            vm_tags["provider_node_id"] = node_id
            admin = conf.get("admin_username", "ray")
            os_profile = {"computerName": node_id, "adminUsername": admin}
            if self.gcs_address_for_workers:
                os_profile["customData"] = base64.b64encode(
                    self._startup(node_id).encode()
                ).decode()
            # Real ARM requires credentials on the osProfile: an SSH public
            # key (preferred) or a password. Absent both, the create only
            # works against a mock API — same honesty gate as endpoint auth.
            if conf.get("ssh_public_key"):
                os_profile["linuxConfiguration"] = {
                    "disablePasswordAuthentication": True,
                    "ssh": {
                        "publicKeys": [
                            {
                                "path": f"/home/{admin}/.ssh/authorized_keys",
                                "keyData": conf["ssh_public_key"],
                            }
                        ]
                    },
                }
            elif conf.get("admin_password"):
                os_profile["adminPassword"] = conf["admin_password"]
            body = {
                "location": self.location,
                "tags": vm_tags,
                "properties": {
                    "hardwareProfile": {"vmSize": conf.get("vm_size", "Standard_D8s_v5")},
                    "storageProfile": {
                        "imageReference": conf.get(
                            "image",
                            {
                                "publisher": "Canonical",
                                "offer": "ubuntu-24_04-lts",
                                "sku": "server",
                                "version": "latest",
                            },
                        )
                    },
                    "osProfile": os_profile,
                },
            }
            # Real ARM also mandates a networkProfile; pre-created NICs are
            # the reference provider's pattern too (one NIC per VM from its
            # ARM template). network_interface_id may be a template with
            # {node_id} for per-VM NIC naming conventions.
            if conf.get("network_interface_id"):
                body["properties"]["networkProfile"] = {
                    "networkInterfaces": [
                        {"id": conf["network_interface_id"].replace("{node_id}", node_id)}
                    ]
                }
            self._request("PUT", f"{self.base}/{node_id}?{self._API}", body)
            created.append(node_id)
            self._tags_cache[node_id] = vm_tags
        if self.wait_for_ready:
            self._wait_succeeded(created)
        return created

    def _wait_succeeded(self, ids: list[str]):
        deadline = time.monotonic() + self.create_timeout_s
        pending = set(ids)
        while pending:
            if time.monotonic() > deadline:
                raise TimeoutError(f"Azure VMs not provisioned: {sorted(pending)}")
            time.sleep(self.poll_interval_s)
            states = {
                vm["name"]: (vm.get("properties") or {}).get("provisioningState")
                for vm in self._list_nodes()
            }
            pending = {i for i in pending if states.get(i) != "Succeeded"}

    def terminate_node(self, node_id: str):
        self._tags_cache.pop(node_id, None)
        try:
            self._request("DELETE", f"{self.base}/{node_id}?{self._API}")
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise

    def is_running(self, node_id: str) -> bool:
        try:
            vm = self._request("GET", f"{self.base}/{node_id}?{self._API}")
        except Exception:
            return False
        return (vm.get("properties") or {}).get("provisioningState") == "Succeeded"
