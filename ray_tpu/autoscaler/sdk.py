"""Programmatic autoscaler SDK.

Reference: python/ray/autoscaler/sdk/sdk.py:206 ``request_resources`` — an
explicit, STANDING demand floor the autoscaler provisions for regardless of
queued work, until overridden by the next call (an empty request clears it).
The request rides GCS KV (the same channel the reference uses via its
resource-request gRPC into the monitor), so any driver in the cluster can
set it and the autoscaler's reconcile tick picks it up.
"""

from __future__ import annotations

import json
from typing import Optional

RESOURCE_REQUEST_KEY = "autoscaler/resource_request"


def request_resources(num_cpus: Optional[int] = None, bundles: Optional[list] = None):
    """Command the cluster to scale to accommodate the given resources.

    ``num_cpus`` expands to that many 1-CPU bundles (reference semantics);
    ``bundles`` is a list of resource-shape dicts (e.g. ``[{"TPU": 4}]``).
    Calling with neither (or empty) clears the standing request.
    """
    from ray_tpu._private import worker_context

    shapes: list[dict] = []
    if num_cpus:
        shapes.extend([{"CPU": 1.0}] * int(num_cpus))
    for b in bundles or []:
        if b:
            shapes.append({k: float(v) for k, v in b.items()})
    cw = worker_context.get_core_worker()
    cw.gcs.call(
        "kv_put",
        {
            "key": RESOURCE_REQUEST_KEY,
            "value": json.dumps(shapes).encode(),
            "overwrite": True,
        },
    )


def read_resource_request(gcs) -> list[dict]:
    """Autoscaler-side: the standing request as demand shapes ([] if none).
    Takes an open GCS RpcClient (the autoscaler's tick already holds one)."""
    try:
        resp = gcs.call("kv_get", {"key": RESOURCE_REQUEST_KEY})
    except Exception:
        return []
    if not resp.get("found"):
        return []
    try:
        shapes = json.loads(bytes(resp["value"]).decode())
    except (ValueError, TypeError):
        return []
    return [s for s in shapes if isinstance(s, dict) and s]
