"""Autoscaler monitor — the background reconcile loop.

Analog of the reference's monitor process (autoscaler/_private/monitor.py):
runs StandardAutoscaler.update() on a fixed tick. Runs as a thread next to
the head node (this framework's daemons are in-process, see _private/node.py)
rather than a separate OS process.
"""

from __future__ import annotations

import logging
import threading

logger = logging.getLogger(__name__)


class Monitor:
    def __init__(self, config: dict, interval_s: float = 5.0):
        from ray_tpu.autoscaler.autoscaler import StandardAutoscaler

        self.autoscaler = StandardAutoscaler(config)
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, name="autoscaler-monitor", daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.autoscaler.update()
            except Exception:
                logger.exception("autoscaler tick failed")

    def stop(self, terminate_nodes: bool = True):
        self._stop.set()
        self._thread.join(timeout=10)
        if terminate_nodes:
            self.autoscaler.shutdown()
