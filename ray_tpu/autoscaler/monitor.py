"""Autoscaler monitor — the background reconcile loop.

Analog of the reference's monitor process (autoscaler/_private/monitor.py):
runs StandardAutoscaler.update() on a fixed tick. Runs as a thread next to
the head node (this framework's daemons are in-process, see _private/node.py)
rather than a separate OS process.
"""

from __future__ import annotations

import logging
import threading

logger = logging.getLogger(__name__)


class Monitor:
    def __init__(self, config: dict, interval_s: float = 5.0):
        from ray_tpu.autoscaler.autoscaler import StandardAutoscaler

        self.autoscaler = StandardAutoscaler(config)
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, name="autoscaler-monitor", daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.autoscaler.update()
            except Exception:
                logger.exception("autoscaler tick failed")

    def stop(self, terminate_nodes: bool = True):
        self._stop.set()
        self._thread.join(timeout=10)
        if terminate_nodes:
            self.autoscaler.shutdown()


def main():
    """Standalone monitor process for `ray_tpu up` (the reference's
    monitor.py process)."""
    import argparse
    import json
    import signal
    import time

    parser = argparse.ArgumentParser()
    parser.add_argument("--config-file", required=True)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO, format="[monitor] %(levelname)s %(message)s")
    with open(args.config_file) as f:
        config = json.load(f)
    monitor = Monitor(config)
    stopping = {"done": False}

    def _term(signum, frame):
        if not stopping["done"]:
            stopping["done"] = True
            # Terminate provider nodes here: this process holds the only
            # in-memory handles for subprocess-backed providers (fake) —
            # `ray_tpu down` keeps a provider-rebuild fallback for providers
            # with external state (TPU pods) in case the monitor died early.
            monitor.stop(terminate_nodes=True)
    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    while not stopping["done"]:
        time.sleep(1)


if __name__ == "__main__":
    main()
