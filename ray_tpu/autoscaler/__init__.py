"""Autoscaler — demand-driven node provisioning.

TPU-native analog of the reference's autoscaler
(python/ray/autoscaler/_private/autoscaler.py:172 StandardAutoscaler,
resource_demand_scheduler.py:101 ResourceDemandScheduler, pluggable
NodeProvider, fake_multi_node/ test provider): pending task shapes and
unplaced placement-group bundles are read from the GCS, bin-packed onto
configured node types, and nodes are launched/terminated through a provider.

TPU-first: a node type can model an entire TPU pod slice (``TPU: 4`` +
``tpu_accelerator_type`` label), so STRICT_PACK placement groups demanding a
slice trigger a slice-sized node launch.
"""

from ray_tpu.autoscaler.autoscaler import StandardAutoscaler  # noqa: F401
from ray_tpu.autoscaler.monitor import Monitor  # noqa: F401
from ray_tpu.autoscaler.node_provider import (  # noqa: F401
    FakeMultiNodeProvider,
    NodeProvider,
)
from ray_tpu.autoscaler.resource_demand_scheduler import (  # noqa: F401
    ResourceDemandScheduler,
)
from ray_tpu.autoscaler.sdk import request_resources  # noqa: F401
