from ray_tpu.autoscaler.v2.instance_manager import (  # noqa: F401
    Instance,
    InstanceManager,
    InstanceStatus,
    InstanceStorage,
)
from ray_tpu.autoscaler.v2.batching_node_provider import (  # noqa: F401
    BatchingNodeProvider,
    NodeData,
    ScaleRequest,
)
from ray_tpu.autoscaler.v2.autoscaler_v2 import AutoscalerV2  # noqa: F401
