"""Instance manager — the autoscaler v2 instance-lifecycle state machine.

Reference: python/ray/autoscaler/v2/instance_manager/ (instance_manager.py,
instance_storage.py, common.py InstanceUtil): every cluster node is an
INSTANCE record owned by the manager and driven through an explicit
lifecycle:

    QUEUED -> REQUESTED -> ALLOCATED -> RAY_RUNNING -> RAY_STOPPING
                                                -> TERMINATING -> TERMINATED
    (+ ALLOCATION_FAILED from REQUESTED, RAY_FAILED from RAY_RUNNING)

v1's autoscaler infers state by diffing provider tags each tick; v2 makes
state explicit and versioned so concurrent reconcilers can't clobber each
other (instance_storage.py batch_upsert CAS semantics) and stuck
transitions are detectable by timestamp (InstanceUtil.has_timeout). The
reconciler maps cloud instances and live ray nodes onto the records each
tick.

TPU-native note: an instance's ``node_type`` may be a multi-host pod slice
(TPUPodProvider); the lifecycle is the same — gang-ness lives in the
node-type resource shape, not in the state machine.
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, List, Optional, Set, Tuple


class InstanceStatus(str, Enum):
    QUEUED = "QUEUED"                      # wanted, not yet asked of the cloud
    REQUESTED = "REQUESTED"                # create issued to the provider
    ALLOCATED = "ALLOCATED"                # cloud instance exists
    RAY_RUNNING = "RAY_RUNNING"            # raylet registered with the GCS
    RAY_STOPPING = "RAY_STOPPING"          # drain requested
    RAY_FAILED = "RAY_FAILED"              # raylet died; instance may remain
    TERMINATING = "TERMINATING"            # terminate issued to the provider
    TERMINATED = "TERMINATED"              # gone (terminal)
    ALLOCATION_FAILED = "ALLOCATION_FAILED"  # provider refused (terminal)


# Legal transitions (reference: InstanceUtil.get_valid_transitions).
_TRANSITIONS: Dict[InstanceStatus, Set[InstanceStatus]] = {
    InstanceStatus.QUEUED: {InstanceStatus.REQUESTED, InstanceStatus.TERMINATED},
    InstanceStatus.REQUESTED: {
        InstanceStatus.ALLOCATED,
        InstanceStatus.ALLOCATION_FAILED,
        InstanceStatus.QUEUED,  # retry after request timeout
    },
    InstanceStatus.ALLOCATED: {
        InstanceStatus.RAY_RUNNING,
        InstanceStatus.TERMINATING,
        InstanceStatus.RAY_FAILED,
    },
    InstanceStatus.RAY_RUNNING: {
        InstanceStatus.RAY_STOPPING,
        InstanceStatus.RAY_FAILED,
        InstanceStatus.TERMINATING,
    },
    InstanceStatus.RAY_STOPPING: {InstanceStatus.TERMINATING, InstanceStatus.RAY_FAILED},
    InstanceStatus.RAY_FAILED: {InstanceStatus.TERMINATING, InstanceStatus.QUEUED},
    InstanceStatus.TERMINATING: {InstanceStatus.TERMINATED},
    InstanceStatus.TERMINATED: set(),
    InstanceStatus.ALLOCATION_FAILED: {InstanceStatus.QUEUED},
}


@dataclass
class Instance:
    instance_id: str
    node_type: str
    status: InstanceStatus = InstanceStatus.QUEUED
    cloud_instance_id: Optional[str] = None
    ray_node_id: Optional[str] = None
    launch_attempts: int = 0
    # status -> last time it was entered (reference keeps the full history;
    # timestamps are what timeout detection needs).
    status_times: Dict[str, float] = field(default_factory=dict)

    def __post_init__(self):
        self.status_times.setdefault(self.status.value, time.time())

    def time_in_status(self) -> float:
        return time.time() - self.status_times.get(self.status.value, time.time())

    @staticmethod
    def new(node_type: str) -> "Instance":
        return Instance(instance_id=uuid.uuid4().hex[:12], node_type=node_type)


class InstanceStorage:
    """Versioned record store (reference: instance_storage.py). Every batch
    upsert carries the version the writer read; a stale writer loses —
    the CAS discipline that lets reconciler and scheduler run unlocked."""

    def __init__(self):
        self._instances: Dict[str, Instance] = {}
        self._version = 0

    @property
    def version(self) -> int:
        return self._version

    def get_instances(self) -> Tuple[Dict[str, Instance], int]:
        return dict(self._instances), self._version

    def batch_upsert(self, instances: List[Instance], expected_version: int) -> bool:
        if expected_version != self._version:
            return False
        for inst in instances:
            self._instances[inst.instance_id] = inst
        self._version += 1
        return True

    def delete(self, instance_ids: List[str], expected_version: int) -> bool:
        if expected_version != self._version:
            return False
        for iid in instance_ids:
            self._instances.pop(iid, None)
        self._version += 1
        return True


class InstanceManager:
    """Owns the storage; validates every state change (reference:
    instance_manager.py update_instance_manager_state)."""

    def __init__(self, storage: Optional[InstanceStorage] = None,
                 request_timeout_s: float = 120.0, max_launch_attempts: int = 3):
        self.storage = storage or InstanceStorage()
        self.request_timeout_s = request_timeout_s
        self.max_launch_attempts = max_launch_attempts

    # -- state changes -----------------------------------------------------
    def add_instances(self, node_types: List[str]) -> List[Instance]:
        """Queue new desired instances."""
        while True:
            _, version = self.storage.get_instances()
            fresh = [Instance.new(t) for t in node_types]
            if self.storage.batch_upsert(fresh, version):
                return fresh

    def set_status(self, instance_id: str, status: InstanceStatus, **fields) -> Instance:
        """One validated transition; raises on an illegal edge."""
        while True:
            instances, version = self.storage.get_instances()
            inst = instances[instance_id]
            if status not in _TRANSITIONS[inst.status]:
                raise ValueError(
                    f"illegal transition {inst.status.value} -> {status.value} "
                    f"for instance {instance_id}"
                )
            inst.status = status
            inst.status_times[status.value] = time.time()
            for k, v in fields.items():
                setattr(inst, k, v)
            if self.storage.batch_upsert([inst], version):
                return inst

    def instances(self, *statuses: InstanceStatus) -> List[Instance]:
        insts, _ = self.storage.get_instances()
        if not statuses:
            return list(insts.values())
        want = set(statuses)
        return [i for i in insts.values() if i.status in want]

    # -- reconciliation ----------------------------------------------------
    def reconcile(self, cloud_instances: Dict[str, str],
                  ray_nodes: Dict[str, str]) -> None:
        """Fold provider + GCS truth into the records.

        cloud_instances: cloud_instance_id -> node_type (currently existing)
        ray_nodes: cloud_instance_id -> ray_node_id (raylets alive in GCS)
        """
        insts, _ = self.storage.get_instances()
        known_cloud = {
            i.cloud_instance_id for i in insts.values() if i.cloud_instance_id
        }
        # 1. REQUESTED instances that the provider has now satisfied: adopt
        # unclaimed cloud instances of the matching type (oldest request
        # first — provider APIs don't echo request ids back).
        unclaimed = [cid for cid in cloud_instances if cid not in known_cloud]
        for inst in sorted(
            self.instances(InstanceStatus.REQUESTED),
            key=lambda i: i.status_times.get(InstanceStatus.REQUESTED.value, 0),
        ):
            match = next(
                (cid for cid in unclaimed if cloud_instances[cid] == inst.node_type),
                None,
            )
            if match is not None:
                unclaimed.remove(match)
                self.set_status(
                    inst.instance_id, InstanceStatus.ALLOCATED, cloud_instance_id=match
                )
            elif inst.time_in_status() > self.request_timeout_s:
                # Stuck request: retry or give up (reference: stuck-instance
                # reconciliation).
                if inst.launch_attempts + 1 >= self.max_launch_attempts:
                    self.set_status(inst.instance_id, InstanceStatus.ALLOCATION_FAILED)
                else:
                    self.set_status(
                        inst.instance_id, InstanceStatus.QUEUED,
                        launch_attempts=inst.launch_attempts + 1,
                    )
        # 2. ALLOCATED instances whose raylet registered -> RAY_RUNNING;
        # RAY_RUNNING whose raylet vanished -> RAY_FAILED; cloud instance
        # gone entirely -> TERMINATED.
        for inst in self.instances(
            InstanceStatus.ALLOCATED, InstanceStatus.RAY_RUNNING,
            InstanceStatus.RAY_STOPPING, InstanceStatus.TERMINATING,
        ):
            cid = inst.cloud_instance_id
            if cid not in cloud_instances:
                if inst.status in (InstanceStatus.ALLOCATED, InstanceStatus.RAY_RUNNING):
                    # Cloud killed it under us; route through TERMINATING so
                    # the transition table stays the single source of edges.
                    self.set_status(inst.instance_id, InstanceStatus.TERMINATING)
                if inst.status in (InstanceStatus.RAY_STOPPING,):
                    self.set_status(inst.instance_id, InstanceStatus.TERMINATING)
                self.set_status(inst.instance_id, InstanceStatus.TERMINATED)
                continue
            if inst.status == InstanceStatus.ALLOCATED and cid in ray_nodes:
                self.set_status(
                    inst.instance_id, InstanceStatus.RAY_RUNNING,
                    ray_node_id=ray_nodes[cid],
                )
            elif inst.status == InstanceStatus.RAY_RUNNING and cid not in ray_nodes:
                self.set_status(inst.instance_id, InstanceStatus.RAY_FAILED)
