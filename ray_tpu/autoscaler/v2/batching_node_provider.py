"""Batching node provider — one desired-state request per autoscaler tick.

Reference: python/ray/autoscaler/batching_node_provider.py
(BatchingNodeProvider, NodeData, ScaleRequest): cloud backends whose API is
"declare the replica count" (k8s operators, GKE/TPU pod managers, managed
instance groups) can't efficiently serve v1's per-node create_node/
terminate_node calls. The batching provider records what the autoscaler
wants during an update and flushes ONE ScaleRequest at the end
(post_process), and reads cluster membership in ONE get_node_data call at
the start.

Subclasses implement exactly two methods (get_node_data /
submit_scale_request); the v1 NodeProvider surface is adapted on top so
both StandardAutoscaler (v1) and AutoscalerV2 can drive it unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Set

from ray_tpu.autoscaler.node_provider import NodeProvider


@dataclass
class NodeData:
    """Provider-side view of one node (reference: batching_node_provider.py
    NodeData)."""

    kind: str            # "head" | "worker"
    type: str            # node type name (cluster-config key)
    ip: str = ""
    status: str = "running"


@dataclass
class ScaleRequest:
    """The one batched ask (reference: ScaleRequest)."""

    desired_num_workers: Dict[str, int] = field(default_factory=dict)
    workers_to_delete: Set[str] = field(default_factory=set)


class BatchingNodeProvider(NodeProvider):
    """Adapter: v1 NodeProvider calls accumulate into a ScaleRequest that
    flushes in post_process() — called once per autoscaler update."""

    def __init__(self, provider_config: dict, cluster_name: str):
        super().__init__(provider_config, cluster_name)
        self.node_data_dict: Dict[str, NodeData] = {}
        self.scale_request = ScaleRequest()
        self.scale_change_needed = False

    # -- subclass surface --------------------------------------------------
    def get_node_data(self) -> Dict[str, NodeData]:
        raise NotImplementedError

    def submit_scale_request(self, scale_request: ScaleRequest) -> None:
        raise NotImplementedError

    # -- v1 NodeProvider adaptation ---------------------------------------
    def non_terminated_nodes(self) -> List[str]:
        """Refreshes the cached membership AND resets the pending scale
        request to current reality — the autoscaler calls this exactly once
        at the top of each update (reference: same contract)."""
        self.node_data_dict = self.get_node_data()
        counts: Dict[str, int] = {}
        for data in self.node_data_dict.values():
            if data.kind == "worker":
                counts[data.type] = counts.get(data.type, 0) + 1
        self.scale_request = ScaleRequest(desired_num_workers=counts)
        self.scale_change_needed = False
        return list(self.node_data_dict)

    def node_tags(self, node_id: str) -> dict:
        data = self.node_data_dict[node_id]
        return {
            "ray-node-kind": data.kind,
            "ray-user-node-type": data.type,
            "ray-node-status": data.status,
        }

    def is_running(self, node_id: str) -> bool:
        return self.node_data_dict.get(node_id, NodeData("", "", status="gone")).status == "running"

    def create_node(self, node_config: dict, tags: dict, count: int) -> List[str]:
        node_type = tags["ray-user-node-type"]
        self.scale_request.desired_num_workers[node_type] = (
            self.scale_request.desired_num_workers.get(node_type, 0) + count
        )
        self.scale_change_needed = True
        return []  # ids are assigned by the backend; visible next tick

    def terminate_node(self, node_id: str) -> None:
        data = self.node_data_dict.get(node_id)
        if data is None:
            return
        cur = self.scale_request.desired_num_workers.get(data.type, 0)
        self.scale_request.desired_num_workers[data.type] = max(0, cur - 1)
        self.scale_request.workers_to_delete.add(node_id)
        self.scale_change_needed = True

    def post_process(self) -> None:
        """Flush the batch (reference: called at the end of every
        StandardAutoscaler.update)."""
        if self.scale_change_needed:
            self.submit_scale_request(self.scale_request)
            self.scale_change_needed = False
