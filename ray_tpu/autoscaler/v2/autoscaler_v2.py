"""Autoscaler v2 — explicit instance lifecycle driving a (batching) provider.

Reference: python/ray/autoscaler/v2/autoscaler.py + instance_manager/
reconciler: the v2 loop separates DESIRE (demand -> queued instances) from
ACTUATION (queued -> provider requests) from OBSERVATION (reconcile
provider + GCS truth into the records), where v1 fused all three into
StandardAutoscaler.update's tag-diffing. The payoff is auditability (every
node has a lifecycle history) and providers that want one batched
desired-state call per tick (BatchingNodeProvider).

The demand calculation is shared with v1 (ResourceDemandScheduler) — the planner
didn't change between versions, the bookkeeping did.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, Optional, Tuple

from ray_tpu.autoscaler.resource_demand_scheduler import ResourceDemandScheduler
from ray_tpu.autoscaler.v2.instance_manager import (
    InstanceManager,
    InstanceStatus,
)

logger = logging.getLogger(__name__)


class AutoscalerV2:
    """One update() per tick; injectable cluster-state reader so the loop
    is testable without a live GCS (the reference's v2 tests do the same
    through fake GCS clients)."""

    def __init__(self, config: dict, provider,
                 state_reader: Optional[Callable[[], Tuple[list, list]]] = None,
                 instance_manager: Optional[InstanceManager] = None):
        self.config = config
        self.provider = provider
        self.scheduler = ResourceDemandScheduler(
            config.get("node_types", {}), config.get("max_workers", 8)
        )
        self.im = instance_manager or InstanceManager(
            request_timeout_s=config.get("request_timeout_s", 120.0),
            max_launch_attempts=config.get("max_launch_attempts", 3),
        )
        self.idle_timeout_s = config.get("idle_timeout_s", 60.0)
        self._state_reader = state_reader or self._read_gcs_state
        self._idle_since: Dict[str, float] = {}

    def _read_gcs_state(self):
        from ray_tpu._private.rpc import RpcClient

        host, port = self.config["provider"]["gcs_address"].rsplit(":", 1)
        gcs = RpcClient((host, int(port)), label="autoscaler_v2")
        try:
            nodes = [
                n for n in gcs.call("get_nodes")["nodes"].values()
                if n["state"] == "ALIVE"
            ]
            pgs = gcs.call("list_placement_groups").get("placement_groups", [])
        finally:
            gcs.close()
        return nodes, pgs

    # ------------------------------------------------------------------
    def update(self):
        nodes, pgs = self._state_reader()

        # ---- OBSERVE: fold provider + GCS truth into the records -------
        provider_ids = self.provider.non_terminated_nodes()
        cloud_instances = {
            nid: (self.provider.node_tags(nid).get("ray-user-node-type")
                  or self.provider.node_tags(nid).get("node_type", ""))
            for nid in provider_ids
        }
        ray_nodes = {}
        for n in nodes:
            pid = (n.get("labels") or {}).get("provider_node_id")
            if pid:
                ray_nodes[pid] = n["node_id"]
        self.im.reconcile(cloud_instances, ray_nodes)

        # ---- DESIRE: demand -> queued instances ------------------------
        demands = self._collect_demands(nodes, pgs)
        avail = [dict(n.get("available", {})) for n in nodes]
        live = self.im.instances(
            InstanceStatus.QUEUED, InstanceStatus.REQUESTED,
            InstanceStatus.ALLOCATED, InstanceStatus.RAY_RUNNING,
        )
        counts_by_type: Dict[str, int] = {}
        for inst in live:
            counts_by_type[inst.node_type] = counts_by_type.get(inst.node_type, 0) + 1
        # In-flight (not yet running) capacity joins the planning pool so a
        # demand wave doesn't double-launch while instances boot.
        node_types = self.config.get("node_types", {})
        for inst in live:
            if inst.status != InstanceStatus.RAY_RUNNING:
                avail.append(dict(node_types.get(inst.node_type, {}).get("resources", {})))
        to_launch = self.scheduler.get_nodes_to_launch(
            avail, demands, counts_by_type, total_existing=len(live)
        )
        for node_type, count in to_launch.items():
            self.im.add_instances([node_type] * count)

        # ---- ACTUATE: queued -> provider create (batched) --------------
        for inst in self.im.instances(InstanceStatus.QUEUED):
            node_cfg = node_types.get(inst.node_type, {})
            try:
                self.provider.create_node(
                    node_cfg,
                    {"ray-user-node-type": inst.node_type, "node_type": inst.node_type},
                    1,
                )
                self.im.set_status(inst.instance_id, InstanceStatus.REQUESTED)
            except Exception:
                logger.exception("create_node failed for %s", inst.instance_id)
                self.im.set_status(
                    inst.instance_id, InstanceStatus.REQUESTED,
                )
                self.im.set_status(inst.instance_id, InstanceStatus.ALLOCATION_FAILED)

        # ---- idle scale-down ------------------------------------------
        self._scale_down_idle(nodes)
        # ---- dead-raylet cleanup: release the cloud instance -----------
        for inst in self.im.instances(InstanceStatus.RAY_FAILED):
            self._terminate(inst)
        # Flush a batching provider's accumulated scale request.
        post = getattr(self.provider, "post_process", None)
        if post:
            post()

    # ------------------------------------------------------------------
    def _collect_demands(self, nodes, pgs):
        demands = []
        for n in nodes:
            for entry in n.get("load", []) or []:
                shape = entry.get("resources", {})
                if shape:
                    demands.extend([shape] * int(entry.get("count", 1)))
        for pg in pgs:
            if pg.get("state") == "PENDING":
                bundles = pg.get("bundles", [])
                if pg.get("strategy", "PACK") == "STRICT_PACK":
                    merged: dict = {}
                    for b in bundles:
                        for k, v in b.items():
                            merged[k] = merged.get(k, 0) + v
                    if merged:
                        demands.append(merged)
                else:
                    demands.extend([b for b in bundles if b])
        return demands

    def _scale_down_idle(self, nodes):
        now = time.time()
        by_ray_id = {n["node_id"]: n for n in nodes}
        for inst in self.im.instances(InstanceStatus.RAY_RUNNING):
            n = by_ray_id.get(inst.ray_node_id)
            if n is None:
                continue
            total = n.get("total", {})
            used = {
                k: total.get(k, 0) - v
                for k, v in n.get("available", {}).items()
            }
            busy = any(v > 0 for v in used.values()) or bool(n.get("load"))
            if busy:
                self._idle_since.pop(inst.instance_id, None)
                continue
            first = self._idle_since.setdefault(inst.instance_id, now)
            if now - first >= self.idle_timeout_s:
                self._idle_since.pop(inst.instance_id, None)
                self.im.set_status(inst.instance_id, InstanceStatus.RAY_STOPPING)
                self._terminate(self.im.instances(InstanceStatus.RAY_STOPPING)[-1])

    def _terminate(self, inst):
        try:
            if inst.cloud_instance_id:
                self.provider.terminate_node(inst.cloud_instance_id)
        except Exception:
            logger.exception("terminate_node failed for %s", inst.instance_id)
        self.im.set_status(inst.instance_id, InstanceStatus.TERMINATING)
