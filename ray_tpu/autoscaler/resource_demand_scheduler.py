"""Resource demand → node launch planning.

Analog of the reference's ResourceDemandScheduler
(autoscaler/_private/resource_demand_scheduler.py:101): first-fit bin-packing
of pending resource shapes onto existing capacity, then greedy selection of
new nodes from the configured node types for whatever doesn't fit.
"""

from __future__ import annotations


def _fits(avail: dict, shape: dict) -> bool:
    return all(avail.get(k, 0) >= v for k, v in shape.items())


def _take(avail: dict, shape: dict):
    for k, v in shape.items():
        avail[k] = avail.get(k, 0) - v


class ResourceDemandScheduler:
    def __init__(self, node_types: dict[str, dict], max_workers: int):
        """``node_types``: name -> {"resources": {...}, "max_workers": int}."""
        self.node_types = node_types
        self.max_workers = max_workers

    def get_nodes_to_launch(
        self,
        existing_avail: list[dict],
        demands: list[dict],
        counts_by_type: dict[str, int],
        total_existing: int,
    ) -> dict[str, int]:
        """Plan launches.

        - ``existing_avail``: available-resource dicts of current nodes
          (copies; consumed during planning).
        - ``demands``: resource shapes, one entry per pending unit.
        - ``counts_by_type``: current worker count per node type.
        Returns {node_type: count_to_launch}.
        """
        avail = [dict(a) for a in existing_avail]
        unmet: list[dict] = []
        # Pack biggest demands first so small ones fill the gaps.
        for shape in sorted(demands, key=lambda s: -sum(s.values())):
            placed = False
            for a in avail:
                if _fits(a, shape):
                    _take(a, shape)
                    placed = True
                    break
            if not placed:
                unmet.append(shape)

        to_launch: dict[str, int] = {}
        counts = dict(counts_by_type)
        total = total_existing
        pending_new: list[tuple[str, dict]] = []  # (type, remaining avail)
        # Baseline workers first (reference: min_workers in
        # available_node_types) — held up regardless of demand; their
        # capacity joins the pool so demand packs into them before
        # launching more.
        for name, nt in self.node_types.items():
            deficit = int(nt.get("min_workers", 0)) - counts.get(name, 0)
            while deficit > 0 and total < self.max_workers:
                to_launch[name] = to_launch.get(name, 0) + 1
                counts[name] = counts.get(name, 0) + 1
                pending_new.append((name, dict(nt.get("resources", {}))))
                total += 1
                deficit -= 1
        if not unmet:
            return to_launch
        for shape in unmet:
            placed = False
            for _, a in pending_new:
                if _fits(a, shape):
                    _take(a, shape)
                    placed = True
                    break
            if placed:
                continue
            # Pick the cheapest node type that can hold the shape (fewest
            # total resources — avoids launching a TPU pod for a CPU task).
            candidates = []
            for name, nt in self.node_types.items():
                res = nt.get("resources", {})
                if not _fits(dict(res), shape):
                    continue
                if counts.get(name, 0) >= nt.get("max_workers", self.max_workers):
                    continue
                candidates.append((sum(res.values()), name, res))
            if not candidates or total >= self.max_workers:
                continue  # infeasible or at cluster cap; demand stays unmet
            _, name, res = min(candidates, key=lambda c: (c[0], c[1]))
            a = dict(res)
            _take(a, shape)
            pending_new.append((name, a))
            to_launch[name] = to_launch.get(name, 0) + 1
            counts[name] = counts.get(name, 0) + 1
            total += 1
        return to_launch
