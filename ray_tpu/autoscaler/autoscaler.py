"""StandardAutoscaler — the reconcile loop.

Analog of the reference's StandardAutoscaler
(autoscaler/_private/autoscaler.py:172 ``update()``): each tick reads cluster
state from the GCS (alive nodes, per-node available resources, pending task
shapes from raylet heartbeats, unplaced placement-group bundles), plans
launches with the ResourceDemandScheduler, and terminates nodes idle longer
than ``idle_timeout_s``.

Config dict (YAML-equivalent of the reference's cluster config):

    {
      "cluster_name": "default",
      "max_workers": 8,
      "idle_timeout_s": 60,
      "provider": {"type": "fake", "gcs_address": "host:port"},
      "node_types": {
        "cpu_worker": {"resources": {"CPU": 2}, "max_workers": 4},
        "tpu_slice":  {"resources": {"TPU": 4, "CPU": 8}, "max_workers": 2},
      },
    }
"""

from __future__ import annotations

import logging
import time

from ray_tpu._private.rpc import RpcClient
from ray_tpu.autoscaler.node_provider import FakeMultiNodeProvider, NodeProvider
from ray_tpu.autoscaler.resource_demand_scheduler import ResourceDemandScheduler

logger = logging.getLogger(__name__)


def _make_provider(config: dict) -> NodeProvider:
    pconf = config.get("provider", {})
    ptype = pconf.get("type", "fake")
    if ptype == "fake":
        return FakeMultiNodeProvider(pconf, config.get("cluster_name", "default"))
    if ptype == "tpu":
        from ray_tpu.autoscaler.node_provider import TPUPodProvider

        return TPUPodProvider(pconf, config.get("cluster_name", "default"))
    if ptype in ("aws", "gcp", "gce", "azure"):
        from ray_tpu.autoscaler import cloud_providers

        cls = {
            "aws": cloud_providers.AWSNodeProvider,
            "gcp": cloud_providers.GCENodeProvider,
            "gce": cloud_providers.GCENodeProvider,
            "azure": cloud_providers.AzureNodeProvider,
        }[ptype]
        return cls(pconf, config.get("cluster_name", "default"))
    raise ValueError(f"unknown provider type {ptype!r}")


class StandardAutoscaler:
    def __init__(self, config: dict, provider: NodeProvider | None = None):
        self.config = config
        self.provider = provider or _make_provider(config)
        host, port = config["provider"]["gcs_address"].rsplit(":", 1)
        self._gcs_address = (host, int(port))
        self.scheduler = ResourceDemandScheduler(
            config.get("node_types", {}), config.get("max_workers", 8)
        )
        self.idle_timeout_s = config.get("idle_timeout_s", 60.0)
        # provider node id -> node type
        self._node_type_of: dict[str, str] = {}
        # provider node id -> launch ts; nodes that never register within
        # boot_timeout_s are recycled so their demand can re-launch.
        self._launch_time: dict[str, float] = {}
        self.boot_timeout_s = config.get("boot_timeout_s", 120.0)
        # gcs node id -> first time seen fully idle
        self._idle_since: dict[str, float] = {}
        self._head_node_id: str | None = None

    def _gcs(self) -> RpcClient:
        return RpcClient(self._gcs_address, label="autoscaler")

    def _read_state(self) -> tuple[list[dict], list[dict], list[dict]]:
        from ray_tpu.autoscaler.sdk import read_resource_request

        gcs = self._gcs()
        try:
            nodes = [
                n
                for n in gcs.call("get_nodes")["nodes"].values()
                if n["state"] == "ALIVE"
            ]
            pgs = gcs.call("list_placement_groups").get("placement_groups", [])
            requested = read_resource_request(gcs)
        finally:
            gcs.close()
        return nodes, pgs, requested

    def update(self):
        """One reconcile tick. Safe to call from any thread/process."""
        nodes, pgs, requested = self._read_state()
        if self._head_node_id is None and nodes:
            # First-seen node is the head (started before the autoscaler);
            # never terminate it.
            self._head_node_id = nodes[0]["node_id"]

        # ---- demand ----
        # sdk.request_resources shapes are a STANDING floor satisfied from
        # TOTAL cluster capacity (reference semantics): shapes no live node
        # could hold join the launch demand; shapes a node covers instead
        # protect that node from idle reaping below. Fitting the launch
        # side against availability would relaunch forever while a covering
        # node is merely busy (launch/reap churn).
        protected, uncovered = self._cover_request(requested, nodes)
        demands: list[dict] = list(uncovered)
        for n in nodes:
            for entry in n.get("load", []) or []:
                shape = entry.get("resources", {})
                if not shape:
                    continue
                demands.extend([shape] * int(entry.get("count", 1)))
        for pg in pgs:
            if pg.get("state") == "PENDING":
                strategy = pg.get("strategy", "PACK")
                bundles = pg.get("bundles", [])
                if strategy == "STRICT_PACK":
                    # Gang demand: one node must hold every bundle — present
                    # it as a single merged shape (a TPU slice request).
                    merged: dict = {}
                    for b in bundles:
                        for k, v in b.items():
                            merged[k] = merged.get(k, 0) + v
                    if merged:
                        demands.append(merged)
                else:
                    demands.extend([b for b in bundles if b])

        # ---- launch ----
        provider_nodes = self.provider.non_terminated_nodes()
        counts_by_type: dict[str, int] = {}
        booting_avail: list[dict] = []
        registered = {(n.get("labels") or {}).get("provider_node_id") for n in nodes}
        for nid in provider_nodes:
            t = self._node_type_of.get(nid) or self.provider.node_tags(nid).get("node_type")
            if t:
                counts_by_type[t] = counts_by_type.get(t, 0) + 1
            if nid not in registered and t in self.config.get("node_types", {}):
                launched = self._launch_time.get(nid)
                if launched is not None and time.time() - launched > self.boot_timeout_s:
                    # Never registered within the boot timeout: recycle it so
                    # the pending demand can launch a replacement.
                    logger.warning("autoscaler: node %s failed to boot; recycling", nid)
                    self.provider.terminate_node(nid)
                    self._node_type_of.pop(nid, None)
                    self._launch_time.pop(nid, None)
                    counts_by_type[t] = counts_by_type.get(t, 1) - 1
                    continue
                # Launched but not yet registered with the GCS: count its
                # full capacity so the same demand doesn't re-launch a node
                # on every tick while the first one boots.
                booting_avail.append(dict(self.config["node_types"][t].get("resources", {})))
        to_launch = self.scheduler.get_nodes_to_launch(
            existing_avail=[n.get("resources_available", {}) for n in nodes] + booting_avail,
            demands=demands,
            counts_by_type=counts_by_type,
            total_existing=len(provider_nodes),
        )
        for node_type, count in to_launch.items():
            node_config = self.config["node_types"][node_type]
            logger.info("autoscaler: launching %d x %s", count, node_type)
            created = self.provider.create_node(
                node_config, tags={"node_type": node_type}, count=count
            )
            for nid in created:
                self._node_type_of[nid] = node_type
                self._launch_time[nid] = time.time()

        # ---- idle termination ----
        now = time.time()
        # Live (task/PG) demand pins the whole cluster; the standing
        # sdk.request_resources floor pins only the nodes needed to COVER
        # it — extra idle capacity beyond the request still scales down.
        live_demands = demands[len(uncovered):]
        feasible_demand = bool(to_launch) or any(
            self._shape_feasible(s, nodes) for s in live_demands
        )
        if feasible_demand:
            # Busy cluster: reset idle clocks to avoid flapping. Demand no
            # node type (or node) could ever satisfy must NOT pin the
            # cluster at peak size forever.
            self._idle_since.clear()
            return
        idle_gcs_nodes = []
        for n in nodes:
            if n["node_id"] == self._head_node_id:
                continue
            if n["node_id"] in protected:
                self._idle_since.pop(n["node_id"], None)
                continue
            total, avail = n.get("resources_total", {}), n.get("resources_available", {})
            resources_idle = all(avail.get(k, 0) >= v for k, v in total.items())
            # Zero-resource actors don't show in the ledger; never reap a
            # node with active workers.
            if resources_idle and n.get("num_active_workers", 0) == 0:
                first = self._idle_since.setdefault(n["node_id"], now)
                if now - first >= self.idle_timeout_s:
                    idle_gcs_nodes.append(n)
            else:
                self._idle_since.pop(n["node_id"], None)
        # Never scale a node type below its configured min_workers baseline
        # (counts_by_type from the launch phase is current: reaching here
        # means feasible_demand was false, so nothing launched this tick).
        live_counts = dict(counts_by_type)
        for n in idle_gcs_nodes:
            pid = self._provider_node_for(n)
            if pid is None:
                continue
            node_type = self._node_type_of.get(pid) or self.provider.node_tags(pid).get("node_type")
            if node_type:
                floor = int(self.config.get("node_types", {}).get(node_type, {}).get("min_workers", 0))
                if live_counts.get(node_type, 0) <= floor:
                    continue
                live_counts[node_type] -= 1
            logger.info("autoscaler: terminating idle node %s", n["node_id"][:8])
            gcs = self._gcs()
            try:
                gcs.call("drain_node", {"node_id": n["node_id"]})
            except Exception:
                pass
            finally:
                gcs.close()
            self.provider.terminate_node(pid)
            self._node_type_of.pop(pid, None)
            self._idle_since.pop(n["node_id"], None)

    def _cover_request(self, shapes: list[dict], nodes: list[dict]) -> tuple[set, list[dict]]:
        """First-fit the requested shapes onto live nodes by TOTAL capacity.

        Returns (protected node ids — they hold at least one shape and the
        standing request shields them from idle reaping; uncovered shapes —
        launch demand no live node could hold)."""
        protected: set = set()
        uncovered: list[dict] = []
        remaining = [dict(n.get("resources_total", {})) for n in nodes]
        for shape in shapes:
            for i, cap in enumerate(remaining):
                if all(cap.get(k, 0) >= v for k, v in shape.items()):
                    for k, v in shape.items():
                        cap[k] = cap.get(k, 0) - v
                    protected.add(nodes[i]["node_id"])
                    break
            else:
                uncovered.append(shape)
        return protected, uncovered

    def _shape_feasible(self, shape: dict, nodes: list[dict]) -> bool:
        """Could this demand ever be satisfied — by a configured node type or
        by the total capacity of an existing node?"""
        for nt in self.config.get("node_types", {}).values():
            res = nt.get("resources", {})
            if all(res.get(k, 0) >= v for k, v in shape.items()):
                return True
        for n in nodes:
            total = n.get("resources_total", {})
            if all(total.get(k, 0) >= v for k, v in shape.items()):
                return True
        return False

    def _provider_node_for(self, gcs_node: dict) -> str | None:
        """Match a GCS node to its provider node via the provider_node_id
        label the provider stamps on every node it launches."""
        want = (gcs_node.get("labels", {}) or {}).get("provider_node_id")
        if want and want in self.provider.non_terminated_nodes():
            return want
        return None

    def shutdown(self):
        self.provider.shutdown()
