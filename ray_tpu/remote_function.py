"""RemoteFunction — the @ray_tpu.remote function wrapper.

Analog of the reference's RemoteFunction (python/ray/remote_function.py:39,
_remote at :245): holds the user function plus default options; ``.remote()``
submits through the core worker, ``.options()`` returns an overridden view.
"""

from __future__ import annotations

import functools

_OPTION_KEYS = {
    "num_returns",
    "num_cpus",
    "num_tpus",
    "resources",
    "max_retries",
    "retry_exceptions",
    "name",
    "scheduling_strategy",
    "placement_group",
    "placement_group_bundle_index",
    "runtime_env",
}


def _build_resources(opts: dict) -> dict:
    resources = dict(opts.get("resources") or {})
    if "num_cpus" in opts and opts["num_cpus"] is not None:
        resources["CPU"] = opts["num_cpus"]
    if "num_tpus" in opts and opts["num_tpus"] is not None:
        resources["TPU"] = opts["num_tpus"]
    resources.setdefault("CPU", 1)
    return {k: v for k, v in resources.items() if v}


def _scheduling_opts(opts: dict) -> dict:
    out = {}
    strategy = opts.get("scheduling_strategy")
    pg = opts.get("placement_group")
    if pg is not None:
        out["placement_group_id"] = pg.id.hex() if hasattr(pg, "id") else str(pg)
        out["placement_group_bundle_index"] = opts.get("placement_group_bundle_index", 0)
    elif strategy is not None:
        if isinstance(strategy, str):
            out["scheduling_strategy"] = strategy
        else:  # PlacementGroupSchedulingStrategy / NodeAffinitySchedulingStrategy
            out.update(strategy.to_options())
    return out


class RemoteFunction:
    def __init__(self, func, **default_opts):
        self._func = func
        self._opts = default_opts
        functools.update_wrapper(self, func)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{self._func.__name__}' cannot be called directly; "
            f"use '{self._func.__name__}.remote()'."
        )

    def options(self, **opts):
        bad = set(opts) - _OPTION_KEYS
        if bad:
            raise ValueError(f"invalid .options() keys: {sorted(bad)}")
        merged = {**self._opts, **opts}
        return RemoteFunction(self._func, **merged)

    def remote(self, *args, **kwargs):
        from ray_tpu._private import worker_context

        cw = worker_context.get_core_worker()
        opts = self._opts
        refs = cw.submit_task(
            self._func,
            args=args,
            kwargs=kwargs,
            num_returns=opts.get("num_returns", 1),
            resources=_build_resources(opts),
            max_retries=opts.get("max_retries", 3),
            retry_exceptions=opts.get("retry_exceptions", False),
            name=opts.get("name"),
            runtime_env=opts.get("runtime_env"),
            **_scheduling_opts(opts),
        )
        if opts.get("num_returns", 1) == 1:
            return refs[0]
        return refs

    def bind(self, *args, **kwargs):
        """Build a lazy DAG node instead of executing (reference:
        python/ray/dag — f.bind(x))."""
        from ray_tpu.dag.dag_node import FunctionNode

        return FunctionNode(self, args, kwargs)

    @property
    def underlying_function(self):
        return self._func
