// Shared-memory object arena: the data plane of the per-node object store.
//
// TPU-native analog of the reference's Plasma store arena
// (src/ray/object_manager/plasma/{store.h:55,dlmalloc.cc}): one POSIX shm
// segment per node, mmap'd by every process on the node, so object payloads
// are written once and read zero-copy everywhere. Unlike plasma there is no
// fd-passing protocol: the segment has a well-known name per node and clients
// attach directly; allocation metadata lives only in the store daemon (the
// single process that calls alloc/free), which hands out offsets over RPC.
//
// Exposed as a plain C API for ctypes binding (no pybind11 in this image).

#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <map>
#include <mutex>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>

namespace {

struct Arena {
  std::string name;
  uint8_t* base = nullptr;
  uint64_t capacity = 0;
  bool owner = false;
  // First-fit free list with coalescing. Only meaningful in the owner
  // (daemon) process; attachers never allocate.
  std::map<uint64_t, uint64_t> free_blocks;   // offset -> size
  std::map<uint64_t, uint64_t> alloc_blocks;  // offset -> size
  uint64_t used = 0;
  std::mutex mu;
};

std::mutex g_mu;
std::vector<Arena*> g_arenas;

int register_arena(Arena* a) {
  std::lock_guard<std::mutex> lock(g_mu);
  g_arenas.push_back(a);
  return static_cast<int>(g_arenas.size() - 1);
}

Arena* get_arena(int handle) {
  std::lock_guard<std::mutex> lock(g_mu);
  if (handle < 0 || handle >= static_cast<int>(g_arenas.size())) return nullptr;
  return g_arenas[handle];
}

constexpr uint64_t kAlign = 64;  // cache-line align payloads

uint64_t align_up(uint64_t v) { return (v + kAlign - 1) & ~(kAlign - 1); }

}  // namespace

extern "C" {

// Create (daemon) or attach (client) the node's arena segment.
// Returns handle >= 0, or -1 on failure.
int arena_create(const char* name, uint64_t capacity) {
  shm_unlink(name);  // stale segment from a crashed session
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return -1;
  if (ftruncate(fd, static_cast<off_t>(capacity)) != 0) {
    close(fd);
    shm_unlink(name);
    return -1;
  }
  void* base = mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) {
    shm_unlink(name);
    return -1;
  }
  Arena* a = new Arena();
  a->name = name;
  a->base = static_cast<uint8_t*>(base);
  a->capacity = capacity;
  a->owner = true;
  a->free_blocks[0] = capacity;
  return register_arena(a);
}

int arena_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return -1;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return -1;
  }
  uint64_t capacity = static_cast<uint64_t>(st.st_size);
  void* base = mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return -1;
  Arena* a = new Arena();
  a->name = name;
  a->base = static_cast<uint8_t*>(base);
  a->capacity = capacity;
  a->owner = false;
  return register_arena(a);
}

uint64_t arena_capacity(int handle) {
  Arena* a = get_arena(handle);
  return a ? a->capacity : 0;
}

void* arena_base(int handle) {
  Arena* a = get_arena(handle);
  return a ? a->base : nullptr;
}

// Allocate `size` bytes; returns offset, or UINT64_MAX if out of memory.
// Daemon-only.
uint64_t arena_alloc(int handle, uint64_t size) {
  Arena* a = get_arena(handle);
  if (!a || !a->owner || size == 0) return UINT64_MAX;
  uint64_t need = align_up(size);
  std::lock_guard<std::mutex> lock(a->mu);
  for (auto it = a->free_blocks.begin(); it != a->free_blocks.end(); ++it) {
    if (it->second >= need) {
      uint64_t off = it->first;
      uint64_t remaining = it->second - need;
      a->free_blocks.erase(it);
      if (remaining > 0) a->free_blocks[off + need] = remaining;
      a->alloc_blocks[off] = need;
      a->used += need;
      return off;
    }
  }
  return UINT64_MAX;
}

// Free a previously allocated offset. Returns 0 on success. Daemon-only.
int arena_free(int handle, uint64_t offset) {
  Arena* a = get_arena(handle);
  if (!a || !a->owner) return -1;
  std::lock_guard<std::mutex> lock(a->mu);
  auto it = a->alloc_blocks.find(offset);
  if (it == a->alloc_blocks.end()) return -1;
  uint64_t size = it->second;
  a->alloc_blocks.erase(it);
  a->used -= size;
  // Insert into free list and coalesce with neighbors.
  auto ins = a->free_blocks.emplace(offset, size).first;
  if (ins != a->free_blocks.begin()) {
    auto prev = std::prev(ins);
    if (prev->first + prev->second == ins->first) {
      prev->second += ins->second;
      a->free_blocks.erase(ins);
      ins = prev;
    }
  }
  auto next = std::next(ins);
  if (next != a->free_blocks.end() && ins->first + ins->second == next->first) {
    ins->second += next->second;
    a->free_blocks.erase(next);
  }
  return 0;
}

uint64_t arena_used(int handle) {
  Arena* a = get_arena(handle);
  if (!a) return 0;
  std::lock_guard<std::mutex> lock(a->mu);
  return a->used;
}

uint64_t arena_largest_free(int handle) {
  Arena* a = get_arena(handle);
  if (!a) return 0;
  std::lock_guard<std::mutex> lock(a->mu);
  uint64_t best = 0;
  for (auto& kv : a->free_blocks)
    if (kv.second > best) best = kv.second;
  return best;
}

// Detach; if unlink != 0 also remove the shm segment (daemon, at shutdown).
int arena_close(int handle, int unlink_seg) {
  Arena* a = get_arena(handle);
  if (!a) return -1;
  munmap(a->base, a->capacity);
  if (unlink_seg) shm_unlink(a->name.c_str());
  {
    std::lock_guard<std::mutex> lock(g_mu);
    g_arenas[handle] = nullptr;
  }
  delete a;
  return 0;
}

}  // extern "C"
