// Scheduler core: fixed-point resource accounting + placement scoring.
//
// TPU-native analog of the reference's C++ scheduler substrate
// (src/ray/raylet/scheduling/: ClusterResourceScheduler/LocalResourceManager
// with FixedPoint arithmetic, fixed_point.h, and the hybrid/spread policies
// in policy/*.h). The Python raylet delegates the hot per-task math here:
//   - acquire/release on the node's main pool and placement-group bundle
//     pools (exact integer milli-units — no float drift after thousands of
//     fractional-resource acquire/release cycles),
//   - cluster-wide feasibility and best-node selection (hybrid pack /
//     spread scoring over the heartbeat-synced cluster view).
//
// Exposed as a plain C API for ctypes (no pybind11 in this image). One
// handle per raylet; all methods take an internal mutex — calls arrive from
// the raylet's event loop and state handlers.

#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// Milli-unit fixed point (reference fixed_point.h uses 1e-4; 1e-3 matches
// the Python side's 0.001-granular fractional resources).
constexpr int64_t kScale = 1000;

int64_t to_fp(double v) {
  return static_cast<int64_t>(v * kScale + (v >= 0 ? 0.5 : -0.5));
}
double from_fp(int64_t v) { return static_cast<double>(v) / kScale; }

using Vec = std::unordered_map<uint32_t, int64_t>;  // resource idx -> amount

bool fits(const Vec& avail, const Vec& demand) {
  for (const auto& [idx, amt] : demand) {
    auto it = avail.find(idx);
    if (amt > 0 && (it == avail.end() || it->second < amt)) return false;
  }
  return true;
}

void sub(Vec& avail, const Vec& demand) {
  for (const auto& [idx, amt] : demand) avail[idx] -= amt;
}

void add(Vec& avail, const Vec& demand) {
  for (const auto& [idx, amt] : demand) avail[idx] += amt;
}

struct Node {
  Vec total;
  Vec avail;
};

struct Core {
  std::mutex mu;
  std::unordered_map<std::string, uint32_t> intern;
  std::vector<std::string> names;
  std::map<std::string, Node> nodes;                 // node_id -> node view
  std::map<std::string, Vec> pools;                  // bundle pool -> avail
  std::map<std::string, Vec> pool_caps;              // bundle pool -> capacity
};

std::mutex g_mu;
std::vector<Core*> g_cores;

Core* core(int h) {
  std::lock_guard<std::mutex> g(g_mu);
  if (h < 0 || h >= static_cast<int>(g_cores.size())) return nullptr;
  return g_cores[h];
}

Vec make_vec(int n, const uint32_t* idx, const double* vals) {
  Vec v;
  for (int i = 0; i < n; i++) v[idx[i]] = to_fp(vals[i]);
  return v;
}

}  // namespace

extern "C" {

int sc_create() {
  std::lock_guard<std::mutex> g(g_mu);
  g_cores.push_back(new Core());
  return static_cast<int>(g_cores.size()) - 1;
}

void sc_destroy(int h) {
  std::lock_guard<std::mutex> g(g_mu);
  if (h >= 0 && h < static_cast<int>(g_cores.size()) && g_cores[h]) {
    delete g_cores[h];
    g_cores[h] = nullptr;
  }
}

uint32_t sc_intern(int h, const char* name) {
  Core* c = core(h);
  if (!c) return 0;
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->intern.find(name);
  if (it != c->intern.end()) return it->second;
  uint32_t idx = static_cast<uint32_t>(c->names.size());
  c->names.push_back(name);
  c->intern[name] = idx;
  return idx;
}

// Upsert a node's total+available view (heartbeat sync path).
void sc_node_upsert(int h, const char* node_id, int n, const uint32_t* idx,
                    const double* total, const double* avail) {
  Core* c = core(h);
  if (!c) return;
  std::lock_guard<std::mutex> g(c->mu);
  Node& node = c->nodes[node_id];
  node.total = make_vec(n, idx, total);
  node.avail = make_vec(n, idx, avail);
}

void sc_node_remove(int h, const char* node_id) {
  Core* c = core(h);
  if (!c) return;
  std::lock_guard<std::mutex> g(c->mu);
  c->nodes.erase(node_id);
}

// Acquire from a node's main pool. Returns 1 on success, 0 if insufficient.
int sc_try_acquire(int h, const char* node_id, int n, const uint32_t* idx,
                   const double* vals) {
  Core* c = core(h);
  if (!c) return 0;
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->nodes.find(node_id);
  if (it == c->nodes.end()) return 0;
  Vec demand = make_vec(n, idx, vals);
  if (!fits(it->second.avail, demand)) return 0;
  sub(it->second.avail, demand);
  return 1;
}

void sc_release(int h, const char* node_id, int n, const uint32_t* idx,
                const double* vals) {
  Core* c = core(h);
  if (!c) return;
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->nodes.find(node_id);
  if (it == c->nodes.end()) return;
  Vec demand = make_vec(n, idx, vals);
  add(it->second.avail, demand);
  // Clamp to capacity: a release after a concurrent view reset must not
  // inflate availability past the node's total.
  for (auto& [ridx, amt] : it->second.avail) {
    auto t = it->second.total.find(ridx);
    int64_t cap = t == it->second.total.end() ? 0 : t->second;
    if (amt > cap) amt = cap;
  }
}

// Bundle pools (placement groups): create with capacity, acquire/release.
void sc_pool_upsert(int h, const char* pool_key, int n, const uint32_t* idx,
                    const double* caps) {
  Core* c = core(h);
  if (!c) return;
  std::lock_guard<std::mutex> g(c->mu);
  Vec cap = make_vec(n, idx, caps);
  c->pool_caps[pool_key] = cap;
  c->pools[pool_key] = cap;
}

void sc_pool_remove(int h, const char* pool_key) {
  Core* c = core(h);
  if (!c) return;
  std::lock_guard<std::mutex> g(c->mu);
  c->pools.erase(pool_key);
  c->pool_caps.erase(pool_key);
}

int sc_pool_exists(int h, const char* pool_key) {
  Core* c = core(h);
  if (!c) return 0;
  std::lock_guard<std::mutex> g(c->mu);
  return c->pools.count(pool_key) ? 1 : 0;
}

int sc_pool_try_acquire(int h, const char* pool_key, int n, const uint32_t* idx,
                        const double* vals) {
  Core* c = core(h);
  if (!c) return 0;
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->pools.find(pool_key);
  if (it == c->pools.end()) return 0;
  Vec demand = make_vec(n, idx, vals);
  if (!fits(it->second, demand)) return 0;
  sub(it->second, demand);
  return 1;
}

void sc_pool_release(int h, const char* pool_key, int n, const uint32_t* idx,
                     const double* vals) {
  Core* c = core(h);
  if (!c) return;
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->pools.find(pool_key);
  if (it == c->pools.end()) return;
  Vec demand = make_vec(n, idx, vals);
  add(it->second, demand);
  auto cap = c->pool_caps.find(pool_key);
  if (cap != c->pool_caps.end()) {
    for (auto& [ridx, amt] : it->second) {
      auto t = cap->second.find(ridx);
      int64_t lim = t == cap->second.end() ? 0 : t->second;
      if (amt > lim) amt = lim;
    }
  }
}

// Read back a pool/node availability for one resource (view mirroring).
double sc_node_avail(int h, const char* node_id, uint32_t idx) {
  Core* c = core(h);
  if (!c) return 0;
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->nodes.find(node_id);
  if (it == c->nodes.end()) return 0;
  auto v = it->second.avail.find(idx);
  return v == it->second.avail.end() ? 0.0 : from_fp(v->second);
}

double sc_pool_avail(int h, const char* pool_key, uint32_t idx) {
  Core* c = core(h);
  if (!c) return 0;
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->pools.find(pool_key);
  if (it == c->pools.end()) return 0;
  auto v = it->second.find(idx);
  return v == it->second.end() ? 0.0 : from_fp(v->second);
}

// Cluster-wide feasibility: does any node's TOTAL hold the shape?
// Returns: 2 = fits-now somewhere, 1 = feasible (total) somewhere, 0 = no.
int sc_cluster_feasibility(int h, int n, const uint32_t* idx, const double* vals) {
  Core* c = core(h);
  if (!c) return 0;
  std::lock_guard<std::mutex> g(c->mu);
  Vec demand = make_vec(n, idx, vals);
  int best = 0;
  for (const auto& [nid, node] : c->nodes) {
    if (fits(node.avail, demand)) return 2;
    if (fits(node.total, demand)) best = 1;
  }
  return best;
}

// Best-node selection.
//   strategy 0 = hybrid (reference hybrid_scheduling_policy.h: prefer the
//     local node while it fits-now or is feasible, else the first feasible
//     peer — pack-then-spillback),
//   strategy 1 = spread (highest free-fraction score among feasible nodes).
// Writes the chosen node id into out; returns 1 if chosen, 0 if infeasible
// everywhere.
int sc_best_node(int h, int n, const uint32_t* idx, const double* vals,
                 int strategy, const char* local_node, char* out, int out_len) {
  Core* c = core(h);
  if (!c) return 0;
  std::lock_guard<std::mutex> g(c->mu);
  Vec demand = make_vec(n, idx, vals);

  auto emit = [&](const std::string& nid) {
    std::strncpy(out, nid.c_str(), out_len - 1);
    out[out_len - 1] = '\0';
    return 1;
  };

  if (strategy == 1) {  // SPREAD: max free-fraction over feasible-by-total
    const std::string* best = nullptr;
    double best_score = -1.0;
    for (const auto& [nid, node] : c->nodes) {
      if (!fits(node.total, demand)) continue;
      double score = 0.0;
      for (const auto& [ridx, tot] : node.total) {
        if (tot <= 0) continue;
        auto a = node.avail.find(ridx);
        score += a == node.avail.end() ? 0.0
                                       : static_cast<double>(a->second) / tot;
      }
      if (score > best_score) {
        best_score = score;
        best = &nid;
      }
    }
    return best ? emit(*best) : 0;
  }

  // Hybrid: local first (fits now, or at least feasible), then any
  // fits-now peer, then any feasible peer.
  auto local = c->nodes.find(local_node);
  if (local != c->nodes.end() && fits(local->second.avail, demand)) {
    return emit(local->first);
  }
  const std::string* feasible_peer = nullptr;
  for (const auto& [nid, node] : c->nodes) {
    if (nid == local_node) continue;
    if (fits(node.avail, demand)) return emit(nid);
    if (!feasible_peer && fits(node.total, demand)) feasible_peer = &nid;
  }
  if (local != c->nodes.end() && fits(local->second.total, demand)) {
    return emit(local->first);
  }
  return feasible_peer ? emit(*feasible_peer) : 0;
}

}  // extern "C"
