// Shared-memory object index: lock-free local object lookup.
//
// TPU-native analog of the plasma client's local object table
// (src/ray/object_manager/plasma/{store.h,client.h}): the store daemon
// (raylet) publishes every local object's (offset, size, sealed) into a
// fixed open-addressing hash table in its own shm segment; clients resolve
// `get` of local SEALED objects with two atomic loads and a pin — no RPC
// round-trip on the hottest path in ray.get.
//
// Concurrency protocol (single writer = daemon, many reader processes):
//   reader:  state==SEALED? -> readers.fetch_add -> re-check state+version
//            -> read payload -> readers.fetch_sub
//   daemon:  remove = state:=TOMBSTONE (no new pins) -> readers==0?
//            -> version++ -> slot reusable; else report busy and the
//            daemon defers the arena free until readers drains to 0.
// version is the ABA guard: a slot reused for a new object bumps it, so a
// stale release can never unpin someone else's object.
//
// Exposed as a plain C API for ctypes binding (no pybind11 in this image).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>

namespace {

constexpr uint32_t kKeySize = 28;  // ObjectID binary size
constexpr uint32_t kEmpty = 0;
constexpr uint32_t kPending = 1;
constexpr uint32_t kSealed = 2;
constexpr uint32_t kTombstone = 3;

// All cross-process-shared fields are atomics: the pin/version protocol makes
// stale reads harmless, but plain fields would still be formal data races
// (and TSAN reports) — payload reads are relaxed, ordered by the
// release-store of `state` (seal) / acquire-load on the reader side.
struct Slot {
  std::atomic<uint32_t> state;
  std::atomic<uint32_t> version;
  std::atomic<uint32_t> readers;
  uint32_t pad;
  std::atomic<uint64_t> offset;
  std::atomic<uint64_t> size;
  std::atomic<uint64_t> key0, key1, key2;  // 24 bytes of key
  std::atomic<uint32_t> key3;              // + 4 = kKeySize (28)
  uint32_t pad2;
};
static_assert(sizeof(Slot) == 64, "slot must be one cache line");
static_assert(std::atomic<uint64_t>::is_always_lock_free, "need lock-free u64");

struct Header {
  uint64_t magic;
  uint64_t nslots;
};
constexpr uint64_t kMagic = 0x7470755f69647831ULL;  // "tpu_idx1"

struct Index {
  std::string name;
  Header* hdr = nullptr;
  Slot* slots = nullptr;
  uint64_t nslots = 0;
  void* base = nullptr;
  uint64_t map_size = 0;
  bool owner = false;
};

std::mutex g_mu;
std::vector<Index*> g_indexes;

int register_index(Index* ix) {
  std::lock_guard<std::mutex> lock(g_mu);
  g_indexes.push_back(ix);
  return static_cast<int>(g_indexes.size() - 1);
}

Index* get_index(int handle) {
  std::lock_guard<std::mutex> lock(g_mu);
  if (handle < 0 || handle >= static_cast<int>(g_indexes.size())) return nullptr;
  return g_indexes[handle];
}

uint64_t fnv1a(const uint8_t* key) {
  uint64_t h = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kKeySize; ++i) {
    h ^= key[i];
    h *= 1099511628211ULL;
  }
  return h;
}

void key_split(const uint8_t* key, uint64_t& a, uint64_t& b, uint64_t& c, uint32_t& d) {
  std::memcpy(&a, key, 8);
  std::memcpy(&b, key + 8, 8);
  std::memcpy(&c, key + 16, 8);
  std::memcpy(&d, key + 24, 4);
}

bool key_eq(const Slot& s, const uint8_t* key) {
  uint64_t a, b, c;
  uint32_t d;
  key_split(key, a, b, c, d);
  return s.key0.load(std::memory_order_relaxed) == a &&
         s.key1.load(std::memory_order_relaxed) == b &&
         s.key2.load(std::memory_order_relaxed) == c &&
         s.key3.load(std::memory_order_relaxed) == d;
}

void key_store(Slot& s, const uint8_t* key) {
  uint64_t a, b, c;
  uint32_t d;
  key_split(key, a, b, c, d);
  s.key0.store(a, std::memory_order_relaxed);
  s.key1.store(b, std::memory_order_relaxed);
  s.key2.store(c, std::memory_order_relaxed);
  s.key3.store(d, std::memory_order_relaxed);
}

// Find the LIVE (pending/sealed) slot holding `key`, or nullptr. Probe stops
// at EMPTY; tombstoned slots are skipped for lookups (their key bytes remain
// only so draining releases can still be accounted — see idx_release, which
// addresses slots by index, not key).
Slot* find_live(Index* ix, const uint8_t* key) {
  uint64_t mask = ix->nslots - 1;
  uint64_t i = fnv1a(key) & mask;
  for (uint64_t probe = 0; probe < ix->nslots; ++probe, i = (i + 1) & mask) {
    Slot& s = ix->slots[i];
    uint32_t st = s.state.load(std::memory_order_acquire);
    if (st == kEmpty) return nullptr;
    if ((st == kPending || st == kSealed) && key_eq(s, key)) return &s;
  }
  return nullptr;
}

}  // namespace

extern "C" {

// Create (daemon) or attach (client) the index segment. nslots rounded up to
// a power of two. Returns handle >= 0, or -1.
int idx_create(const char* name, uint64_t nslots) {
  uint64_t n = 1;
  while (n < nslots) n <<= 1;
  uint64_t size = sizeof(Header) + n * sizeof(Slot);
  shm_unlink(name);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return -1;
  if (ftruncate(fd, static_cast<off_t>(size)) != 0) {
    close(fd);
    shm_unlink(name);
    return -1;
  }
  void* base = mmap(nullptr, size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) {
    shm_unlink(name);
    return -1;
  }
  std::memset(base, 0, size);
  Index* ix = new Index();
  ix->name = name;
  ix->base = base;
  ix->map_size = size;
  ix->hdr = static_cast<Header*>(base);
  ix->slots = reinterpret_cast<Slot*>(static_cast<uint8_t*>(base) + sizeof(Header));
  ix->nslots = n;
  ix->owner = true;
  ix->hdr->nslots = n;
  std::atomic_thread_fence(std::memory_order_release);
  ix->hdr->magic = kMagic;
  return register_index(ix);
}

int idx_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return -1;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return -1;
  }
  uint64_t size = static_cast<uint64_t>(st.st_size);
  void* base = mmap(nullptr, size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return -1;
  Header* hdr = static_cast<Header*>(base);
  if (hdr->magic != kMagic) {
    munmap(base, size);
    return -1;
  }
  Index* ix = new Index();
  ix->name = name;
  ix->base = base;
  ix->map_size = size;
  ix->hdr = hdr;
  ix->slots = reinterpret_cast<Slot*>(static_cast<uint8_t*>(base) + sizeof(Header));
  ix->nslots = hdr->nslots;
  ix->owner = false;
  return register_index(ix);
}

// Daemon: publish a created (not yet sealed) object. Returns 0, or -1 full.
int idx_put(int handle, const uint8_t* key, uint64_t offset, uint64_t size) {
  Index* ix = get_index(handle);
  if (!ix || !ix->owner) return -1;
  uint64_t mask = ix->nslots - 1;
  uint64_t i = fnv1a(key) & mask;
  Slot* target = nullptr;   // existing slot for this key (live, or drained tombstone)
  Slot* fallback = nullptr; // first reusable slot in the chain
  for (uint64_t probe = 0; probe < ix->nslots; ++probe, i = (i + 1) & mask) {
    Slot& s = ix->slots[i];
    uint32_t st = s.state.load(std::memory_order_relaxed);
    if (st == kEmpty) {
      if (!fallback) fallback = &s;
      break;  // end of probe chain
    }
    if (key_eq(s, key)) {
      if (st == kPending || st == kSealed) {
        // Re-create (idempotent). Refuse while pinned: bumping the version
        // under a live pin would orphan that reader's release. The pin
        // window must be CLOSED before the readers check (same store-load
        // seq_cst pairing as idx_remove): demote to kPending first so no
        // new pin can succeed its re-validation, then check readers — a
        // plain check while state stayed kSealed would race a concurrent
        // pin and hand that reader a torn offset/size pair mid-overwrite.
        s.state.store(kPending, std::memory_order_seq_cst);
        if (s.readers.load(std::memory_order_seq_cst) != 0) {
          s.state.store(st, std::memory_order_release);  // payload untouched
          return -1;
        }
        target = &s;
        break;
      }
      // Tombstoned same-key slot: reuse it ONLY once its readers drained —
      // a second slot for the same key would break pin accounting.
      if (s.readers.load(std::memory_order_acquire) == 0) {
        target = &s;
        break;
      }
      return -1;  // old entry still pinned; caller retries later
    }
    if (st == kTombstone && !fallback && s.readers.load(std::memory_order_acquire) == 0) {
      fallback = &s;
    }
  }
  if (!target) target = fallback;
  if (!target) return -1;
  // Order matters for concurrent readers: bump version first (invalidates
  // stale pins), write payload fields, then flip state last with release.
  target->version.fetch_add(1, std::memory_order_acq_rel);
  key_store(*target, key);
  target->offset.store(offset, std::memory_order_relaxed);
  target->size.store(size, std::memory_order_relaxed);
  target->state.store(kPending, std::memory_order_release);
  return 0;
}

// Daemon: mark sealed (payload fully written). Returns 0 or -1.
int idx_seal(int handle, const uint8_t* key) {
  Index* ix = get_index(handle);
  if (!ix || !ix->owner) return -1;
  Slot* s = find_live(ix, key);
  if (!s) return -1;
  s->state.store(kSealed, std::memory_order_release);
  return 0;
}

// Daemon: remove. Returns 0 = removed (safe to free arena block),
// 1 = tombstoned but readers still pinned (defer the free), -1 = not found.
int idx_remove(int handle, const uint8_t* key) {
  Index* ix = get_index(handle);
  if (!ix || !ix->owner) return -1;
  Slot* s = find_live(ix, key);
  if (!s) return -1;
  // seq_cst pair with the reader's pin (fetch_add; state re-check): without
  // it the daemon could miss a concurrent pin AND the reader could miss the
  // tombstone (store-load reordering), freeing memory under a reader.
  s->state.store(kTombstone, std::memory_order_seq_cst);
  if (s->readers.load(std::memory_order_seq_cst) == 0) return 0;
  return 1;
}

// Daemon: total readers pinning any slot of `key`, including drained
// tombstones in the probe chain (post-remove drain polling).
uint32_t idx_readers(int handle, const uint8_t* key) {
  Index* ix = get_index(handle);
  if (!ix) return 0;
  uint64_t mask = ix->nslots - 1;
  uint64_t i = fnv1a(key) & mask;
  uint32_t total = 0;
  for (uint64_t probe = 0; probe < ix->nslots; ++probe, i = (i + 1) & mask) {
    Slot& s = ix->slots[i];
    uint32_t st = s.state.load(std::memory_order_acquire);
    if (st == kEmpty) break;
    if (key_eq(s, key)) total += s.readers.load(std::memory_order_acquire);
  }
  return total;
}

// Client: resolve + pin a SEALED object. On hit returns 1 and fills
// (*offset, *size, *version, *slot); the caller MUST idx_release(slot,
// version). Returns 0 on miss (not local / not sealed / being deleted).
int idx_get_pinned(int handle, const uint8_t* key, uint64_t* offset,
                   uint64_t* size, uint32_t* version, uint64_t* slot) {
  Index* ix = get_index(handle);
  if (!ix) return 0;
  Slot* s = find_live(ix, key);
  if (!s) return 0;
  if (s->state.load(std::memory_order_acquire) != kSealed) return 0;
  uint32_t v = s->version.load(std::memory_order_acquire);
  s->readers.fetch_add(1, std::memory_order_seq_cst);
  // Re-validate under the pin (seq_cst pairs with idx_remove): the daemon
  // may have tombstoned or reused the slot between first check and pin.
  if (s->state.load(std::memory_order_seq_cst) != kSealed ||
      s->version.load(std::memory_order_acquire) != v || !key_eq(*s, key)) {
    s->readers.fetch_sub(1, std::memory_order_acq_rel);
    return 0;
  }
  *offset = s->offset.load(std::memory_order_relaxed);
  *size = s->size.load(std::memory_order_relaxed);
  *version = v;
  *slot = static_cast<uint64_t>(s - ix->slots);
  return 1;
}

// Client: release a pin taken by idx_get_pinned. Addressed by slot index so
// re-created keys (new slot or bumped version) can never absorb or drop a
// stale release.
int idx_release(int handle, uint64_t slot, uint32_t version) {
  Index* ix = get_index(handle);
  if (!ix || slot >= ix->nslots) return -1;
  Slot* s = &ix->slots[slot];
  if (s->version.load(std::memory_order_acquire) != version) return -1;
  s->readers.fetch_sub(1, std::memory_order_acq_rel);
  return 0;
}

int idx_close(int handle, int unlink_seg) {
  Index* ix = get_index(handle);
  if (!ix) return -1;
  munmap(ix->base, ix->map_size);
  if (unlink_seg) shm_unlink(ix->name.c_str());
  {
    std::lock_guard<std::mutex> lock(g_mu);
    g_indexes[handle] = nullptr;
  }
  delete ix;
  return 0;
}

}  // extern "C"
