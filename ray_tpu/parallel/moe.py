"""Mixture-of-experts with expert parallelism over the ``ep`` mesh axis.

Absent from the reference (SURVEY.md §2.3: EP nowhere in-tree); TPU-native
version: Switch-style top-1/top-k routing with a capacity factor, dispatch and
combine expressed as einsums against a one-hot dispatch tensor. Experts'
weights are sharded over ``ep``; under pjit the dispatch einsum lowers to an
all_to_all over ICI. No data-dependent shapes — capacity is static, overflow
tokens drop (standard Switch semantics), so the whole layer jits cleanly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def top_k_routing(gate_logits, num_experts: int, capacity: int, k: int = 1):
    """Returns (dispatch [B,T,E,C] one-hot, combine [B,T,E,C] weights).

    Tokens beyond an expert's capacity are dropped (combine weight 0).
    """
    B, T, E = gate_logits.shape
    probs = jax.nn.softmax(gate_logits, axis=-1)
    combine = jnp.zeros((B, T, E, capacity), probs.dtype)
    dispatch = jnp.zeros((B, T, E, capacity), jnp.bool_)
    remaining = probs
    # Track how many tokens each expert has accepted so far (per batch).
    for _ in range(k):
        expert_idx = jnp.argmax(remaining, axis=-1)  # [B,T]
        onehot = jax.nn.one_hot(expert_idx, E, dtype=probs.dtype)  # [B,T,E]
        gate = (remaining * onehot).sum(-1)  # [B,T]
        # Position of each token within its expert's queue.
        pos = jnp.cumsum(onehot, axis=1) * onehot - 1.0  # [B,T,E], -1 where unrouted
        pos = pos.max(-1)  # [B,T]
        in_cap = pos < capacity
        pos_clamped = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)
        cap_onehot = jax.nn.one_hot(pos_clamped, capacity, dtype=probs.dtype)  # [B,T,C]
        contrib = (
            onehot[..., None] * cap_onehot[:, :, None, :] * (gate * in_cap)[..., None, None]
        )
        combine = combine + contrib
        dispatch = dispatch | (contrib > 0)
        remaining = remaining * (1.0 - onehot)
    return dispatch.astype(probs.dtype), combine


def moe_layer(params, x, *, capacity_factor: float = 1.25, k: int = 1):
    """params: {"gate": [D,E], "wi": [E,D,F], "wo": [E,F,D]} (E sharded on ep).

    x: [B, T, D]. Returns [B, T, D] plus the load-balancing aux loss.
    """
    B, T, D = x.shape
    E = params["gate"].shape[-1]
    capacity = max(1, int(capacity_factor * T * k / E))
    logits = jnp.einsum("btd,de->bte", x, params["gate"])
    dispatch, combine = top_k_routing(logits, E, capacity, k)
    # Dispatch tokens: [B,T,E,C] x [B,T,D] -> [E, B*C? ] — keep batch dim:
    expert_in = jnp.einsum("btec,btd->ebcd", dispatch, x)  # [E,B,C,D]
    h = jnp.einsum("ebcd,edf->ebcf", expert_in, params["wi"])
    h = jax.nn.gelu(h)
    expert_out = jnp.einsum("ebcf,efd->ebcd", h, params["wo"])
    out = jnp.einsum("btec,ebcd->btd", combine, expert_out)
    # Load-balance aux loss (Switch): E * sum_e f_e * p_e.
    probs = jax.nn.softmax(logits, axis=-1)
    frac_tokens = dispatch.sum(axis=(1, 3)) / jnp.maximum(dispatch.sum(), 1.0)  # [B,E]
    frac_probs = probs.mean(axis=1)
    aux = E * jnp.mean(jnp.sum(frac_tokens * frac_probs, axis=-1))
    return out, aux


def init_moe_params(key, d_model: int, d_ff: int, num_experts: int, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    scale = d_model**-0.5
    return {
        "gate": jax.random.normal(k1, (d_model, num_experts), dtype) * scale,
        "wi": jax.random.normal(k2, (num_experts, d_model, d_ff), dtype) * scale,
        "wo": jax.random.normal(k3, (num_experts, d_ff, d_model), dtype) * (d_ff**-0.5),
    }
