"""Ulysses (DeepSpeed-style) sequence parallelism via all_to_all.

Absent from the reference (SURVEY.md §5.7); TPU-native version: inputs are
sequence-sharded [B, T/n, H, D]; an ``all_to_all`` over the ``sp`` axis
re-shards to head-sharded [B, T, H/n, D], each device runs *full-sequence*
attention for its head subset (any kernel — here ops.attention.flash_attention),
and a second all_to_all restores sequence sharding. Two all_to_alls ride ICI;
attention itself needs no communication — the right trade when
heads >= sp_degree and sequence lengths are moderate (ring_attention.py covers
the long-sequence regime).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ray_tpu.ops.attention import flash_attention


def _shard_map():
    from ray_tpu.util.jax_compat import shard_map

    return shard_map()


def ulysses_attention(
    q,
    k,
    v,
    mesh,
    *,
    axis_name: str = "sp",
    causal: bool = False,
    sm_scale: float | None = None,
):
    """Exact attention over sequence-sharded inputs via head re-sharding.

    [B, T, H, D] sharded on T over `axis_name`; H must be divisible by the
    axis size.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    n = mesh.shape[axis_name]
    H = q.shape[2]
    if H % n:
        raise ValueError(f"heads ({H}) must be divisible by sp axis size ({n})")

    def local_fn(q_loc, k_loc, v_loc):
        # [B, T/n, H, D] -> all_to_all -> [B, T, H/n, D]
        def seq_to_heads(x):
            return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

        def heads_to_seq(x):
            return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

        qh, kh, vh = seq_to_heads(q_loc), seq_to_heads(k_loc), seq_to_heads(v_loc)
        out = flash_attention(qh, kh, vh, causal=causal, sm_scale=sm_scale)
        return heads_to_seq(out)

    spec = P(None, axis_name, None, None)
    fn = _shard_map()(
        local_fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
