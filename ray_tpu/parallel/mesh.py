"""Device mesh construction and sharding rules.

The TPU-native replacement for everything the reference delegates to external
parallelism frameworks (SURVEY.md §2.3: TP/PP via Accelerate/DeepSpeed/Alpa;
SP/CP/EP absent): parallelism here is a *named mesh axis*, and a strategy is a
set of PartitionSpec rules over those axes.

Axes (any subset, any sizes whose product = device count):
- ``dp``  — data parallel (batch dim; grads psum over dp)
- ``fsdp`` — fully-sharded data parallel (params sharded over fsdp, gathered
  per-layer; batch also sharded — zero-3 style)
- ``tp``  — tensor parallel (hidden/heads dims; activations all-reduce over tp)
- ``pp``  — pipeline parallel (layers dim; activations ppermute between stages)
- ``sp``  — sequence/context parallel (sequence dim; ring attention/Ulysses)
- ``ep``  — expert parallel (experts dim; all_to_all token dispatch)
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np

AXIS_ORDER = ("pp", "dp", "fsdp", "sp", "ep", "tp")


@dataclass(frozen=True)
class MeshConfig:
    """Declarative mesh shape. -1 on at most one axis = fill with remaining devices."""

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1

    def axis_sizes(self) -> dict[str, int]:
        return {a: getattr(self, a) for a in AXIS_ORDER}

    def resolve(self, num_devices: int) -> dict[str, int]:
        sizes = self.axis_sizes()
        unknown = [a for a, s in sizes.items() if s == -1]
        if len(unknown) > 1:
            raise ValueError("at most one mesh axis may be -1")
        known = math.prod(s for s in sizes.values() if s != -1)
        if unknown:
            if num_devices % known:
                raise ValueError(
                    f"{num_devices} devices not divisible by fixed axes product {known}"
                )
            sizes[unknown[0]] = num_devices // known
        if math.prod(sizes.values()) != num_devices:
            raise ValueError(
                f"mesh {sizes} does not cover {num_devices} devices"
            )
        return sizes


def create_mesh(config: MeshConfig | None = None, devices=None, **axis_sizes):
    """Build a jax Mesh. ICI-aware ordering: the innermost (fastest-varying)
    axes are tp/ep/sp — the axes with the heaviest collectives — so their
    collectives ride neighbouring ICI links; pp/dp are outermost, matching the
    scaling-book recipe (DCN-tolerant axes outermost)."""
    import jax
    from jax.sharding import Mesh

    if config is None:
        config = MeshConfig(**axis_sizes)
    devices = np.asarray(devices if devices is not None else jax.devices())
    sizes = config.resolve(devices.size)
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    return Mesh(devices.reshape(shape), AXIS_ORDER)


def single_axis_mesh(axis: str = "dp", devices=None):
    import jax
    from jax.sharding import Mesh

    devices = np.asarray(devices if devices is not None else jax.devices())
    shape = tuple(devices.size if a == axis else 1 for a in AXIS_ORDER)
    return Mesh(devices.reshape(shape), AXIS_ORDER)


# ---------------------------------------------------------------------------
# Logical-axis sharding rules (flax-style rules, applied to pytrees)
# ---------------------------------------------------------------------------

# Default rules for transformer-family models (models/transformer.py annotates
# params with these logical names).
DEFAULT_RULES: dict[str, tuple] = {
    "batch": ("dp", "fsdp"),
    "seq": ("sp",),
    "embed": ("fsdp",),
    "mlp": ("tp",),
    "heads": ("tp",),
    "kv": (),
    "vocab": ("tp",),
    "layers": ("pp",),
    "expert": ("ep",),
}


def logical_to_spec(logical_axes: tuple, rules: dict | None = None):
    """('batch','seq','embed') -> PartitionSpec(('dp','fsdp'), 'sp', 'fsdp')."""
    from jax.sharding import PartitionSpec as P

    rules = rules or DEFAULT_RULES
    out = []
    for name in logical_axes:
        mapped = rules.get(name, ())
        if isinstance(mapped, str):
            mapped = (mapped,)
        if len(mapped) == 0:
            out.append(None)
        elif len(mapped) == 1:
            out.append(mapped[0])
        else:
            out.append(tuple(mapped))
    return P(*out)


def shard_pytree(tree, mesh, spec_fn):
    """device_put a pytree with per-leaf NamedShardings from spec_fn(path, leaf)."""
    import jax
    from jax.sharding import NamedSharding

    def place(path, leaf):
        spec = spec_fn(path, leaf)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, tree)


def replicate_pytree(tree, mesh):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.tree.map(lambda x: jax.device_put(x, NamedSharding(mesh, P())), tree)
