"""Pipeline parallelism over the ``pp`` mesh axis.

The reference delegates PP to external frameworks (SURVEY.md §2.3); here it is
a collective program: layer parameters are stacked [n_stages, ...] and sharded
over ``pp``; activations flow stage-to-stage via ``lax.ppermute`` inside a
``lax.scan`` over microbatches + bubble steps (GPipe schedule). Everything is
one jitted SPMD program — XLA overlaps the ppermute with the next microbatch's
compute on ICI.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def _shard_map():
    from ray_tpu.util.jax_compat import shard_map

    return shard_map()


def pipeline_apply(
    stage_fn,
    stacked_params,
    x,
    mesh,
    *,
    axis_name: str = "pp",
    num_microbatches: int | None = None,
):
    """Run ``num_stages`` stacked stages over microbatched input.

    stage_fn(params_slice, x_mb) -> y_mb, where activations keep one shape.
    stacked_params: pytree with leading dim = num_stages (sharded over pp).
    x: [num_microbatches * mb, ...] global batch (replicated over pp).
    Returns y with x's batch shape.
    """
    n_stages = mesh.shape[axis_name]
    B = x.shape[0]
    M = num_microbatches or n_stages
    assert B % M == 0, f"batch {B} not divisible by microbatches {M}"
    mb = B // M
    x_mbs = x.reshape(M, mb, *x.shape[1:])

    def local_fn(params_loc, x_all):
        # params_loc: stage slice with leading dim 1; x_all: [M, mb, ...].
        params_stage = jax.tree.map(lambda p: p[0], params_loc)
        stage = lax.axis_index(axis_name)
        T = M + n_stages - 1  # total schedule steps incl. bubble
        fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        state = jnp.zeros_like(x_all[0])
        outputs = jnp.zeros((M, mb) + x_all.shape[2:], x_all.dtype)

        def step(carry, t):
            state, outputs = carry
            # Stage 0 ingests microbatch t (while t < M); other stages use
            # the activation ppermuted from the previous stage.
            feed = jnp.where(t < M, 1, 0)
            x_in = x_all[jnp.minimum(t, M - 1)]
            state = jnp.where((stage == 0) & (feed == 1), x_in, state)
            y = stage_fn(params_stage, state)
            # Last stage writes its finished microbatch t - (n_stages - 1).
            out_idx = t - (n_stages - 1)
            write = (stage == n_stages - 1) & (out_idx >= 0)
            outputs = lax.cond(
                write,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, y, jnp.maximum(out_idx, 0), axis=0
                ),
                lambda o: o,
                outputs,
            )
            # Rotate activations forward around the ring.
            state = lax.ppermute(y, axis_name, fwd_perm)
            return (state, outputs), None

        (state, outputs), _ = lax.scan(step, (state, outputs), jnp.arange(T))
        # Only the last stage holds real outputs; broadcast to all stages so
        # the result is replicated over pp.
        outputs = lax.psum(
            jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs)),
            axis_name,
        )
        return outputs

    fn = _shard_map()(
        local_fn,
        mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
        check_vma=False,
    )
    y_mbs = fn(stacked_params, x_mbs)
    return y_mbs.reshape(B, *y_mbs.shape[2:])
