"""MPMD pipeline parallelism over compiled graphs.

``parallel/pipeline.py`` is the single-controller SPMD GPipe program: one
jitted graph, one mesh, every stage lock-stepped inside one ``lax.scan`` —
bubbles paid in full, and every device marches to one program counter. This
module is the MPMD counterpart (PAPERS.md, "Scaling Deep Learning Training
with MPMD Pipeline Parallelism"): each stage is its OWN program — a
``@remote(tensor_transport="collective")`` actor owning its own mesh and
its own jitted stage fn — and the stages are stitched into a
``CompiledDAG`` (PR 7: shm channel rings + resident worker loops, zero
raylet RPCs per iteration) whose inter-stage edges carry device-object
DESCRIPTORS (PR 12, experimental/channel/device_envelope.py) while the
activations stream out of band over the ``util/collective`` p2p seam — no
tensor crosses the host object store between stages.

The schedule is interleaved 1F1B-style streaming: the driver pumps
microbatch ``m`` into stage 0 while stage ``k`` runs microbatch ``m-k`` —
each resident loop starts its next microbatch the moment the descriptor
slot for it lands, so stage k at microbatch m overlaps stage k+1 at m-1 and
the steady-state bubble fraction approaches ``(S-1)/(M+S-1)``. Per-stage
stall/busy counters (``channel_loop_stats``) make the bubble measurable
rather than theoretical (``microbench.py --pipeline``, PIPEBENCH
artifact).

Outputs are bit-exact vs ``pipeline_apply`` on the same stacked params:
each stage computes the identical ``stage_fn(params_k, x_mb)`` dot, and
activations cross process boundaries through ``_private/serialization``'s
exact-bytes jax.Array reducer.
"""

from __future__ import annotations

import logging

import ray_tpu
from ray_tpu.dag import InputNode

logger = logging.getLogger(__name__)


@ray_tpu.remote(tensor_transport="collective")
class PipelineStageActor:
    """One pipeline stage: owns its params (on its own mesh) and its jitted
    stage fn. ``run`` executes inside the resident channel loop — the
    tensor_transport opt-in makes its jax.Array result leave as a
    descriptor slot instead of ring bytes."""

    def __init__(self, stage_fn, params, stage_idx: int, n_stages: int,
                 mesh_axes: dict | None = None):
        import jax

        self.idx = stage_idx
        self.n_stages = n_stages
        self.mesh = None
        if mesh_axes:
            from ray_tpu.parallel.mesh import MeshConfig, create_mesh, replicate_pytree

            self.mesh = create_mesh(MeshConfig(**mesh_axes))
            self.params = replicate_pytree(params, self.mesh)
        else:
            self.params = jax.device_put(params)
        self._fn = jax.jit(stage_fn)

    def ready(self) -> int:
        return self.idx

    def warmup(self, x):
        """Trace + compile the stage fn before the clock starts."""
        self._fn(self.params, x).block_until_ready()
        return True

    def run(self, x):
        return self._fn(self.params, x)

    def pid(self) -> int:
        import os

        return os.getpid()

    def devobj_stats(self) -> dict:
        from ray_tpu.experimental.device_object import device_object_stats

        return device_object_stats()

    def init_collective(self, world_size, rank, backend, group_name):
        from ray_tpu.util import collective as col

        col.init_collective_group(world_size, rank, backend=backend, group_name=group_name)


class MPMDPipeline:
    """N stage actors + one compiled DAG; ``apply`` streams microbatches.

    ``stage_fn(params_k, x_mb) -> y_mb`` with activations keeping one
    shape; ``stacked_params`` is a pytree with leading dim ``n_stages``
    (the same contract as ``pipeline_apply``, so the two are drop-in
    comparable on identical params/inputs)."""

    def __init__(
        self,
        stage_fn,
        stacked_params,
        *,
        n_stages: int | None = None,
        num_microbatches: int | None = None,
        max_in_flight: int = 16,
        stage_mesh_axes: dict | None = None,
        warmup_x=None,
    ):
        import jax

        leaves = jax.tree_util.tree_leaves(stacked_params)
        if not leaves:
            raise ValueError("stacked_params has no leaves")
        inferred = int(leaves[0].shape[0])
        self.n_stages = n_stages or inferred
        if self.n_stages != inferred:
            raise ValueError(
                f"n_stages={self.n_stages} but stacked_params lead dim is {inferred}"
            )
        self.num_microbatches = num_microbatches or self.n_stages
        self._max_in_flight = max(2, int(max_in_flight))
        # DAG class nodes (compiled graphs bind ClassNodes, not live
        # handles); resolve_actor_handle() gives the live gang for classic
        # calls (warmup, stats) — the same actors the compiled DAG uses,
        # via the shared per-DAG actor cache.
        self._stage_nodes = [
            PipelineStageActor.bind(
                stage_fn,
                jax.tree.map(lambda p, k=k: p[k], stacked_params),
                k,
                self.n_stages,
                stage_mesh_axes,
            )
            for k in range(self.n_stages)
        ]
        self.stages = [n.resolve_actor_handle() for n in self._stage_nodes]
        ray_tpu.get([s.ready.remote() for s in self.stages], timeout=300)
        if warmup_x is not None:
            ray_tpu.get(
                [s.warmup.remote(warmup_x) for s in self.stages], timeout=300
            )
        with InputNode() as inp:
            d = inp
            for node in self._stage_nodes:
                d = node.run.bind(d)
        self.compiled = d.experimental_compile(
            max_buffered_results=self._max_in_flight
        )
        self._torn_down = False

    # -- execution ------------------------------------------------------

    def apply(self, x, num_microbatches: int | None = None):
        """Run the full batch through the pipeline; returns y with x's
        batch shape. Microbatches are pumped ``max_in_flight`` deep so the
        stages overlap (1F1B streaming); outputs gather in order."""
        import jax.numpy as jnp

        M = num_microbatches or self.num_microbatches
        B = x.shape[0]
        assert B % M == 0, f"batch {B} not divisible by microbatches {M}"
        mb = B // M
        x_mbs = x.reshape(M, mb, *x.shape[1:])
        window = self._max_in_flight - 1
        refs: list = []
        outs: list = []
        for m in range(M):
            refs.append(self.compiled.execute(x_mbs[m]))
            if len(refs) > window:
                outs.append(refs.pop(0).get())
        while refs:
            outs.append(refs.pop(0).get())
        return jnp.concatenate(outs, axis=0)

    # -- measurement ----------------------------------------------------

    def reset_stage_stats(self):
        self._each_loop_stats(reset=True)

    def stage_stats(self) -> list:
        """Per-stage stall/busy/resolve split (ns), ordered by stage index —
        read from each resident loop. The basis of the measured bubble
        fraction."""
        rows = [r for stats in self._each_loop_stats() for r in stats]
        return sorted(rows, key=lambda r: int(r["label"].split(":", 1)[0]))

    def bubble_fraction(self) -> float:
        """stall / (stall + busy) summed over stages since the last reset —
        the measured counterpart of (S-1)/(M+S-1)."""
        rows = self.stage_stats()
        stall = sum(r["stall_ns"] for r in rows)
        busy = sum(r["busy_ns"] for r in rows)
        total = stall + busy
        return stall / total if total else 0.0

    def _each_loop_stats(self, reset: bool = False) -> list:
        from ray_tpu._private import worker_context

        cw = worker_context.get_core_worker()
        out = []
        for addr in self.compiled._actor_addrs.values():
            resp = cw._owner_client(tuple(addr)).call(
                "channel_loop_stats",
                {"loop_id": self.compiled._dag_id, "reset": reset},
                timeout=10,
            )
            out.append(resp.get("stages") or [])
        return out

    def stage_devobj_stats(self) -> list:
        """Each stage process's device-object counters (the zero-host-copy
        evidence: transfers_host stays flat across a steady-state run)."""
        return ray_tpu.get(
            [s.devobj_stats.remote() for s in self.stages], timeout=60
        )

    # -- lifecycle ------------------------------------------------------

    def teardown(self, kill_actors: bool = True):
        if self._torn_down:
            return
        self._torn_down = True
        self.compiled.teardown()
        if kill_actors:
            for s in self.stages:
                try:
                    ray_tpu.kill(s)
                except Exception:
                    pass

    def __del__(self):
        try:
            if not self._torn_down:
                self.teardown(kill_actors=False)
        except Exception:
            pass


def mpmd_pipeline(stage_fn, stacked_params, **kwargs) -> MPMDPipeline:
    """Build an :class:`MPMDPipeline`; see the class docstring. Drop-in
    MPMD counterpart of ``pipeline_apply``::

        pipe = mpmd_pipeline(stage_fn, ws, num_microbatches=8)
        y = pipe.apply(x)         # bit-exact vs pipeline_apply(...)
        pipe.teardown()
    """
    return MPMDPipeline(stage_fn, stacked_params, **kwargs)
