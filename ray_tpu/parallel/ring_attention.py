"""Ring attention — context parallelism over the ``sp`` mesh axis.

Absent from the reference (SURVEY.md §5.7 confirms no SP/CP/ring attention
in-tree); built natively here the TPU way: Q/K/V are sharded over the sequence
dimension across the ``sp`` axis; each device computes blockwise attention of
its local Q chunk against a K/V chunk that rotates around the ICI ring via
``lax.ppermute``, maintaining flash-style online-softmax statistics so the
result is exact. n_sp steps, each overlapping an MXU-bound block attention
with a neighbour-to-neighbour ICI transfer — the classic ring schedule
(Liu et al., Ring Attention; see PAPERS.md).

Causal masking uses global positions derived from each chunk's ring offset;
fully-masked chunk pairs contribute nothing but still rotate (static schedule,
no data-dependent control flow — XLA-friendly).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def _shard_map():
    if hasattr(jax, "shard_map"):
        return jax.shard_map
    from jax.experimental.shard_map import shard_map

    return shard_map


def _block_attend(q, k, v, q_start, k_start, causal, sm_scale, m, l, acc):
    """One Q-chunk x K-chunk blockwise attention step with online softmax.

    q: [B, Tq, H, D] local; k/v: [B, Tc, H, D] rotating chunk.
    m, l: [B, H, Tq] running max / denominator; acc: [B, Tq, H, D].
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * sm_scale
    if causal:
        Tq, Tc = q.shape[1], k.shape[1]
        q_pos = q_start + lax.broadcasted_iota(jnp.int32, (Tq, Tc), 0)
        k_pos = k_start + lax.broadcasted_iota(jnp.int32, (Tq, Tc), 1)
        s = jnp.where((q_pos >= k_pos)[None, None], s, -jnp.inf)
    m_cur = jnp.maximum(m, s.max(axis=-1))
    # Guard fully-masked rows: exp(-inf - -inf) -> use safe max.
    safe_m = jnp.where(jnp.isneginf(m_cur), 0.0, m_cur)
    p = jnp.exp(s - safe_m[..., None])
    p = jnp.where(jnp.isneginf(s), 0.0, p)
    correction = jnp.exp(jnp.where(jnp.isneginf(m), 0.0, m) - safe_m)
    correction = jnp.where(jnp.isneginf(m), 0.0, correction)
    l_cur = l * correction + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    acc_cur = acc * correction.transpose(0, 2, 1)[..., None] + pv
    return m_cur, l_cur, acc_cur


def ring_attention(
    q,
    k,
    v,
    mesh,
    *,
    axis_name: str = "sp",
    causal: bool = False,
    sm_scale: float | None = None,
):
    """Exact attention over sequence-sharded Q/K/V.

    Inputs are global arrays [B, T, H, D] sharded over axis_name on dim 1 (or
    plain arrays, which shard_map will split). Returns output with the same
    sharding.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    n = mesh.shape[axis_name]
    shard_map = _shard_map()

    def local_fn(q_loc, k_loc, v_loc):
        # q_loc: [B, T/n, H, D] — this device's chunk.
        B, Tq, H, D = q_loc.shape
        idx = lax.axis_index(axis_name)
        q_start = idx * Tq

        m0 = jnp.full((B, H, Tq), -jnp.inf, dtype=jnp.float32)
        l0 = jnp.zeros((B, H, Tq), dtype=jnp.float32)
        acc0 = jnp.zeros((B, Tq, H, D), dtype=jnp.float32)
        perm = [(i, (i + 1) % n) for i in range(n)]

        def step(i, carry):
            kc, vc, m, l, acc = carry
            # Chunk currently held arrived from rank (idx - i) mod n.
            k_start = ((idx - i) % n) * Tq
            m, l, acc = _block_attend(
                q_loc, kc, vc, q_start, k_start, causal, sm_scale, m, l, acc
            )
            kc = lax.ppermute(kc, axis_name, perm)
            vc = lax.ppermute(vc, axis_name, perm)
            return kc, vc, m, l, acc

        _, _, m, l, acc = lax.fori_loop(0, n, step, (k_loc, v_loc, m0, l0, acc0))
        l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows (shouldn't occur)
        out = acc / l.transpose(0, 2, 1)[..., None]
        return out.astype(q_loc.dtype)

    spec = P(None, axis_name, None, None)
    fn = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
