"""Ring attention — context parallelism over the ``sp`` mesh axis.

Absent from the reference (SURVEY.md §5.7 confirms no SP/CP/ring attention
in-tree); built natively here the TPU way: Q/K/V are sharded over the sequence
dimension across the ``sp`` axis; each device computes blockwise attention of
its local Q chunk against a K/V chunk that rotates around the ICI ring via
``lax.ppermute``, combining per-chunk flash outputs through their log-sum-exp
so the result is exact (Liu et al., Ring Attention; see PAPERS.md).

Two TPU-specific optimizations over the naive schedule:

- **Compute/ICI overlap (double buffering)**: the ppermute moving chunk i+1
  is issued BEFORE the attention on chunk i, so XLA's latency-hiding
  scheduler can run the neighbour transfer concurrently with the MXU work
  (round-1 issued the permute after the attention, serializing them).
- **Pallas local math**: each Q-chunk x K-chunk block runs the flash
  attention kernel (ops/attention.py) when the chunk shapes are Mosaic
  tileable, so the [Tq, Tc] logits tile never round-trips HBM; per-chunk
  (out, lse) pairs combine exactly via logaddexp weighting.

Training works through a ring-level custom VJP: the backward makes a second
ring pass (standard flash backward per chunk, XLA einsums), rotating dK/dV
accumulators along with K/V so each chunk's gradients land back on its home
device after the full cycle.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def _shard_map():
    from ray_tpu.util.jax_compat import shard_map

    return shard_map()


_NEG_INF = -1e30  # finite stand-in for -inf: keeps exp/where math NaN-free


def _xla_chunk(q, k, v, q_start, k_start, causal: bool, sm_scale: float):
    """One Q-chunk x K-chunk flash block in plain XLA: returns the chunk's
    normalized output [B,Tq,H,D] (f32) and log-sum-exp [B,H,Tq] (f32)."""
    qf = q.astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, k.astype(jnp.float32)) * sm_scale
    if causal:
        Tq, Tc = q.shape[1], k.shape[1]
        q_pos = q_start + lax.broadcasted_iota(jnp.int32, (Tq, Tc), 0)
        k_pos = k_start + lax.broadcasted_iota(jnp.int32, (Tq, Tc), 1)
        s = jnp.where((q_pos >= k_pos)[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1)                      # [B,H,Tq]
    p = jnp.exp(s - m[..., None])
    p = jnp.where(s <= _NEG_INF, 0.0, p)
    l = p.sum(axis=-1)                           # [B,H,Tq]
    masked = l == 0.0
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    out = out / jnp.where(masked, 1.0, l).transpose(0, 2, 1)[..., None]
    lse = jnp.where(masked, _NEG_INF, m + jnp.log(jnp.where(masked, 1.0, l)))
    return out, lse


def _pallas_chunk(q, k, v, q_start, k_start, sm_scale: float, interpret: bool):
    """Pallas flash kernel for one chunk pair. Ring chunks are size-aligned
    (Tq == Tc, offsets multiples of Tq), so the causal relation collapses to
    three cases decided per device at runtime: k-chunk strictly after the
    q-chunk (fully masked), the diagonal (causal within the chunk), or
    strictly before (no mask)."""
    from ray_tpu.ops.attention import _pallas_flash_with_lse

    B, Tq, H, D = q.shape

    def masked_case(_q, _k, _v):
        return (
            jnp.zeros((B, Tq, H, D), jnp.float32),
            jnp.full((B, H, Tq), _NEG_INF, jnp.float32),
        )

    def diag_case(q, k, v):
        out, lse = _pallas_flash_with_lse(q, k, v, True, sm_scale, min(128, Tq), min(128, k.shape[1]), interpret)
        return out.astype(jnp.float32), lse

    def full_case(q, k, v):
        out, lse = _pallas_flash_with_lse(q, k, v, False, sm_scale, min(128, Tq), min(128, k.shape[1]), interpret)
        return out.astype(jnp.float32), lse

    if q_start is None:  # non-causal: every chunk is a plain full block
        return full_case(q, k, v)
    return lax.cond(
        k_start > q_start,
        masked_case,
        lambda a, b, c: lax.cond(k_start == q_start, diag_case, full_case, a, b, c),
        q, k, v,
    )


def _combine(acc, lse_run, out_c, lse_c):
    """Merge one chunk's flash output into the running result via LSE
    weighting: out = sum_c out_c * exp(lse_c - lse_global), exactly."""
    new_lse = jnp.logaddexp(lse_run, lse_c)
    safe = jnp.where(new_lse <= _NEG_INF, 0.0, new_lse)
    w_old = jnp.where(lse_run <= _NEG_INF, 0.0, jnp.exp(lse_run - safe))
    w_new = jnp.where(lse_c <= _NEG_INF, 0.0, jnp.exp(lse_c - safe))
    acc = acc * w_old.transpose(0, 2, 1)[..., None] + out_c * w_new.transpose(0, 2, 1)[..., None]
    return acc, new_lse


def _ring_forward(q_loc, k_loc, v_loc, axis_name, n, causal, sm_scale, impl, interpret):
    B, Tq, H, D = q_loc.shape
    idx = lax.axis_index(axis_name)
    q_start = idx * Tq
    perm = [(i, (i + 1) % n) for i in range(n)]

    def chunk(kc, vc, i):
        k_start = ((idx - i) % n) * Tq
        if impl == "pallas":
            return _pallas_chunk(
                q_loc, kc, vc, q_start if causal else None, k_start, sm_scale, interpret
            )
        return _xla_chunk(q_loc, kc, vc, q_start, k_start, causal, sm_scale)

    acc0 = jnp.zeros((B, Tq, H, D), jnp.float32)
    lse0 = jnp.full((B, H, Tq), _NEG_INF, jnp.float32)

    def step(i, carry):
        kc, vc, acc, lse_run = carry
        # Double buffering: launch the neighbour transfer of the NEXT chunk
        # before attending the current one — the attention consumes kc/vc,
        # not kn/vn, so the ICI hop and the MXU block run concurrently.
        kn = lax.ppermute(kc, axis_name, perm)
        vn = lax.ppermute(vc, axis_name, perm)
        out_c, lse_c = chunk(kc, vc, i)
        acc, lse_run = _combine(acc, lse_run, out_c, lse_c)
        return kn, vn, acc, lse_run

    kc, vc, acc, lse_run = lax.fori_loop(0, n - 1, step, (k_loc, v_loc, acc0, lse0))
    # Final chunk: no further transfer needed.
    out_c, lse_c = chunk(kc, vc, n - 1)
    acc, lse_run = _combine(acc, lse_run, out_c, lse_c)
    return acc.astype(q_loc.dtype), lse_run


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring_attn_local(q_loc, k_loc, v_loc, axis_name, n, causal, sm_scale, impl, interpret):
    out, _ = _ring_forward(q_loc, k_loc, v_loc, axis_name, n, causal, sm_scale, impl, interpret)
    return out


def _ring_attn_fwd(q_loc, k_loc, v_loc, axis_name, n, causal, sm_scale, impl, interpret):
    out, lse = _ring_forward(q_loc, k_loc, v_loc, axis_name, n, causal, sm_scale, impl, interpret)
    return out, (q_loc, k_loc, v_loc, out, lse)


def _ring_attn_bwd(axis_name, n, causal, sm_scale, impl, interpret, res, dout):
    """Second ring pass (standard flash backward per chunk): dK/dV
    accumulators rotate WITH their K/V chunks, so after the full cycle each
    chunk's gradients are back on its home device."""
    q_loc, k_loc, v_loc, out, lse = res
    B, Tq, H, D = q_loc.shape
    idx = lax.axis_index(axis_name)
    q_start = idx * Tq
    perm = [(i, (i + 1) % n) for i in range(n)]

    qf = q_loc.astype(jnp.float32)
    doutf = dout.astype(jnp.float32)
    outf = out.astype(jnp.float32)
    delta = jnp.sum(doutf * outf, axis=-1).transpose(0, 2, 1)  # [B,H,Tq]
    lse_safe = jnp.where(lse <= _NEG_INF, 0.0, lse)
    row_live = (lse > _NEG_INF)[..., None]  # fully-masked rows contribute nothing

    def step(i, carry):
        kc, vc, dk, dv, dq = carry
        # Same double buffering as the forward: K/V for the next chunk leave
        # NOW so the ICI hop overlaps the einsums below; only dK/dV must wait
        # for this step's accumulation (alignment with their chunk is kept —
        # every buffer is permuted exactly once per step).
        kn = lax.ppermute(kc, axis_name, perm)
        vn = lax.ppermute(vc, axis_name, perm)
        k_start = ((idx - i) % n) * Tq
        kf = kc.astype(jnp.float32)
        vf = vc.astype(jnp.float32)
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * sm_scale
        if causal:
            q_pos = q_start + lax.broadcasted_iota(jnp.int32, (Tq, Tq), 0)
            k_pos = k_start + lax.broadcasted_iota(jnp.int32, (Tq, Tq), 1)
            s = jnp.where((q_pos >= k_pos)[None, None], s, _NEG_INF)
        p = jnp.where((s <= _NEG_INF) | ~row_live, 0.0, jnp.exp(s - lse_safe[..., None]))
        dp = jnp.einsum("bqhd,bkhd->bhqk", doutf, vf)
        ds = p * (dp - delta[..., None]) * sm_scale
        dq = dq + jnp.einsum("bhqk,bkhd->bqhd", ds, kf)
        dk = dk + jnp.einsum("bhqk,bqhd->bkhd", ds, qf)
        dv = dv + jnp.einsum("bhqk,bqhd->bkhd", p, doutf)
        # Rotate grads together with their chunks; after n steps they're home.
        return (
            kn,
            vn,
            lax.ppermute(dk, axis_name, perm),
            lax.ppermute(dv, axis_name, perm),
            dq,
        )

    dk0 = jnp.zeros((B, Tq, H, D), jnp.float32)
    dv0 = jnp.zeros_like(dk0)
    dq0 = jnp.zeros_like(dk0)
    _, _, dk, dv, dq = lax.fori_loop(0, n, step, (k_loc, v_loc, dk0, dv0, dq0))
    return dq.astype(q_loc.dtype), dk.astype(k_loc.dtype), dv.astype(v_loc.dtype)


_ring_attn_local.defvjp(_ring_attn_fwd, _ring_attn_bwd)


def ring_attention(
    q,
    k,
    v,
    mesh,
    *,
    axis_name: str = "sp",
    causal: bool = False,
    sm_scale: float | None = None,
    impl: str = "auto",
    interpret: bool = False,
):
    """Exact attention over sequence-sharded Q/K/V.

    Inputs are global arrays [B, T, H, D] sharded over axis_name on dim 1 (or
    plain arrays, which shard_map will split). Returns output with the same
    sharding. Differentiable (ring-level custom VJP; see module docstring).

    impl: "pallas" (flash kernel per chunk), "xla", or "auto" (pallas on TPU
    when the local chunk is Mosaic-tileable, else xla).
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    n = mesh.shape[axis_name]
    chunk_len = q.shape[1] // n
    # Same tiling requirement as flash_attention (ops/attention.py): blocks
    # must divide the chunk exactly — a clamped tail slice would read
    # overlapping rows and the backward would double-count them. Head dim is
    # unconstrained (the kernel's block spans all of D).
    tileable = chunk_len % min(128, chunk_len) == 0
    if impl == "auto":
        on_tpu = jax.default_backend() in ("tpu", "axon")
        impl = "pallas" if (on_tpu or interpret) and tileable else "xla"
    elif impl == "pallas" and not tileable:
        raise ValueError(
            f"impl='pallas' requires the per-device chunk length ({chunk_len}) "
            "to be a multiple of the 128 block size (or < 128 exactly); "
            "use impl='xla' or pad the sequence"
        )
    shard_map = _shard_map()

    spec = P(None, axis_name, None, None)
    fn = shard_map(
        # custom_vjp takes nondiff args positionally (kwargs unsupported).
        lambda a, b, c: _ring_attn_local(
            a, b, c, axis_name, n, causal, sm_scale, impl, interpret
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
