"""Public exception types.

Analog of the reference's python/ray/exceptions.py: typed errors surfaced by
``get``/task execution so user code can distinguish application errors from
system failures.
"""

from __future__ import annotations

import traceback


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception; re-raised at ray_tpu.get() on the caller.

    Analog of the reference's RayTaskError (python/ray/exceptions.py): wraps
    the remote exception plus its remote traceback.
    """

    def __init__(self, cause: BaseException | None = None, remote_traceback: str = "", task_name: str = ""):
        self.cause = cause
        self.remote_traceback = remote_traceback
        self.task_name = task_name
        super().__init__(str(cause) if cause else remote_traceback)

    @classmethod
    def from_exception(cls, exc: BaseException, task_name: str = "") -> "TaskError":
        return cls(cause=exc, remote_traceback=traceback.format_exc(), task_name=task_name)

    def __str__(self):
        return (
            f"Task {self.task_name or '<unknown>'} failed:\n{self.remote_traceback}"
        )


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class ActorError(RayTpuError):
    pass


class ActorDiedError(ActorError):
    """The actor is dead: it crashed, was killed, or exhausted restarts."""

    def __init__(self, msg: str = "The actor died.", actor_id=None):
        super().__init__(msg)
        self.actor_id = actor_id


class ActorUnavailableError(ActorError):
    """The actor is temporarily unreachable (e.g. restarting)."""


class ReplicaDrainingError(ActorError):
    """A Serve replica in drain mode (deliberate retirement: downscale or
    rolling update) refused a NEW request. In-flight work still completes
    there; callers should reassign to another replica. The HTTP proxy does
    so transparently (it owns the request until the response lands); a
    DeploymentHandle caller sees this at ``ray_tpu.get()`` — after the
    handle already returned its ref — and resubmits itself (the handle's
    transparent reassign covers only the died-before-accepting race, which
    is detectable at submission time)."""

    def __init__(self, msg: str = "", deployment: str = "", replica_id: str = ""):
        self.deployment = deployment
        self.replica_id = replica_id
        super().__init__(
            msg
            or (
                f"replica {replica_id or '<unknown>'} of deployment "
                f"{deployment or '<unknown>'} is draining and refuses new "
                "requests"
            )
        )


class ObjectLostError(RayTpuError):
    """Object was lost (all copies gone) and could not be reconstructed."""

    def __init__(self, object_id_hex: str = "", msg: str = ""):
        super().__init__(msg or f"Object {object_id_hex} was lost and could not be recovered.")
        self.object_id_hex = object_id_hex


class OwnerDiedError(ObjectLostError):
    """The object's owner process died; the object's lineage is gone."""


class DeviceObjectLostError(ObjectLostError):
    """A device-resident object (experimental/device_object/) is gone: the
    holder process that kept the ``jax.Array`` on its devices is dead or
    unreachable AND no spilled/host copy exists. Names the holder so the
    postmortem starts at the right process."""

    def __init__(self, object_id_hex: str = "", holder: str = "", msg: str = ""):
        self.holder = holder
        super().__init__(
            object_id_hex,
            msg
            or (
                f"device object {object_id_hex[:16]} was lost: holder "
                f"{holder or '<unknown>'} is dead or unreachable and no "
                "spilled/host copy exists"
            ),
        )


class CollectiveError(RayTpuError):
    """A collective-plane operation (util/collective) failed."""


class CollectiveTimeoutError(CollectiveError):
    """A collective op or p2p recv timed out waiting for peers. Names the
    group, the ranks still missing, and (for p2p) the transfer tag, so the
    postmortem starts at the right member. Deliberately NOT a TimeoutError
    subclass: the chaos-matrix contract treats bare timeouts as untyped
    failures, and this class exists to carry the blame."""

    def __init__(self, msg: str = "", *, group: str = "", ranks=None, tag: str = ""):
        self.group = group
        self.ranks = sorted(ranks) if ranks else []
        self.tag = tag
        super().__init__(
            msg
            or (
                f"collective op on group {group or '<unknown>'} timed out "
                f"waiting for ranks {self.ranks}"
                + (f" (tag {tag!r})" if tag else "")
            )
        )


class CollectiveBroadcastError(CollectiveError):
    """A device-object group broadcast could not deliver to every rank.
    Surviving ranks HAVE the payload (their resolves stay local); ``failed``
    maps each undelivered rank to the reason, so callers can name the dead
    member and decide whether to respawn it. Failed ranks were already
    EVICTED from the group roster (epoch bump), so the next broadcast
    addresses survivors only; a respawned replacement re-registers via
    roster_join and is back on the broadcast plane from its first
    post-rejoin sync."""

    def __init__(self, msg: str = "", *, group: str = "", failed: dict | None = None, info: dict | None = None):
        self.group = group
        self.failed = dict(failed or {})
        self.info = dict(info or {})
        super().__init__(
            msg
            or (
                f"group broadcast on {group or '<unknown>'} failed for ranks "
                f"{sorted(self.failed)}: {self.failed}"
            )
        )


class CollectiveReduceError(CollectiveError):
    """A device-object group reduce/allreduce could not complete on every
    holder. Unlike a failed broadcast (survivors keep their payload), a
    PARTIAL reduce is poison — some holders may already hold the combined
    value while others kept their contribution — so ``failed`` names every
    holder that did not finish and the caller must treat the gang as
    divergent (re-run or rebuild)."""

    def __init__(self, msg: str = "", *, group: str = "", failed: dict | None = None, info: dict | None = None):
        self.group = group
        self.failed = dict(failed or {})
        self.info = dict(info or {})
        super().__init__(
            msg
            or (
                f"group reduce on {group or '<unknown>'} failed for holders "
                f"{sorted(self.failed)}: {self.failed}"
            )
        )


class OutOfMemoryError(RayTpuError):
    """A task's worker was killed by the node memory monitor (reference:
    ray.exceptions.OutOfMemoryError + worker_killing_policy)."""


class ObjectStoreFullError(RayTpuError):
    """The node's shared-memory arena is full even after spilling/eviction."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """ray_tpu.get() timed out."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled before/while running."""


class RuntimeEnvSetupError(RayTpuError):
    """Preparing the task/actor runtime environment failed."""


class NodeDiedError(RayTpuError):
    """The node hosting the computation died."""


class PlacementGroupUnavailableError(RayTpuError):
    """Placement group cannot be scheduled (infeasible or removed)."""
