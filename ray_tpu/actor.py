"""ActorClass / ActorHandle / ActorMethod.

Analog of the reference's actor machinery (python/ray/actor.py:383 ActorClass,
:1024 ActorHandle, :98 ActorMethod): ``@ray_tpu.remote`` on a class yields an
ActorClass; ``.remote()`` registers the actor with the GCS which gang-schedules
its creation; method calls go direct to the actor process (the raylet is not
involved after creation — reference: direct actor task transport).
"""

from __future__ import annotations

import functools

from ray_tpu.remote_function import _build_resources, _scheduling_opts

_ACTOR_OPTION_KEYS = {
    "num_cpus",
    "num_tpus",
    "resources",
    "name",
    "namespace",
    "get_if_exists",
    "lifetime",
    "max_restarts",
    "max_task_retries",
    "max_concurrency",
    "scheduling_strategy",
    "placement_group",
    "placement_group_bundle_index",
    "runtime_env",
    # Device object plane: jax.Array returns stay resident on this actor's
    # devices (experimental/device_object/).
    "tensor_transport",
}


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str, num_returns: int = 1):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns

    def remote(self, *args, **kwargs):
        return self._handle._invoke(self._method_name, args, kwargs, self._num_returns)

    def options(self, num_returns: int = 1):
        return ActorMethod(self._handle, self._method_name, num_returns)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method '{self._method_name}' cannot be called directly; "
            f"use '.{self._method_name}.remote()'."
        )


class ActorHandle:
    def __init__(self, actor_id: str, max_task_retries: int = 0, name: str = "", method_num_returns: dict | None = None):
        self._actor_id = actor_id
        self._max_task_retries = max_task_retries
        self._name = name
        self._method_num_returns = method_num_returns or {}

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return ActorMethod(self, item, self._method_num_returns.get(item, 1))

    def _invoke(self, method_name, args, kwargs, num_returns):
        from ray_tpu._private import worker_context

        cw = worker_context.get_core_worker()
        refs = cw.submit_actor_task(
            self._actor_id,
            method_name,
            args,
            kwargs,
            num_returns=num_returns,
            max_task_retries=self._max_task_retries,
        )
        if num_returns == 1:
            return refs[0]
        return refs

    @property
    def actor_id(self) -> str:
        return self._actor_id

    def __reduce__(self):
        return (
            ActorHandle,
            (self._actor_id, self._max_task_retries, self._name, self._method_num_returns),
        )

    def __repr__(self):
        return f"ActorHandle({self._actor_id[:16]}, name={self._name!r})"


class ActorClass:
    def __init__(self, cls, **default_opts):
        self._cls = cls
        self._opts = default_opts
        functools.update_wrapper(self, cls, updated=[])

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class '{self._cls.__name__}' cannot be instantiated directly; "
            f"use '{self._cls.__name__}.remote()'."
        )

    def options(self, **opts):
        bad = set(opts) - _ACTOR_OPTION_KEYS
        if bad:
            raise ValueError(f"invalid actor .options() keys: {sorted(bad)}")
        return ActorClass(self._cls, **{**self._opts, **opts})

    def remote(self, *args, **kwargs) -> ActorHandle:
        from ray_tpu._private import worker_context

        cw = worker_context.get_core_worker()
        opts = self._opts
        resources = _build_resources({**opts, "resources": opts.get("resources")})
        # Actors only reserve explicitly requested resources for their lifetime.
        if "num_cpus" not in opts and "CPU" in resources:
            resources.pop("CPU")
        info = cw.create_actor(
            self._cls,
            args,
            kwargs,
            resources=resources,
            name=opts.get("name"),
            namespace=opts.get("namespace"),
            get_if_exists=opts.get("get_if_exists", False),
            max_restarts=opts.get("max_restarts", 0),
            max_task_retries=opts.get("max_task_retries", 0),
            max_concurrency=opts.get("max_concurrency", 1),
            runtime_env=opts.get("runtime_env"),
            tensor_transport=opts.get("tensor_transport"),
            **_scheduling_opts(opts),
        )
        return ActorHandle(
            info["actor_id"],
            max_task_retries=info["max_task_retries"],
            name=info["name"],
            method_num_returns=self._method_num_returns(),
        )

    def bind(self, *args, **kwargs):
        """Build a lazy actor-construction DAG node (reference: ray.dag
        class_node); method ``.bind`` on the result builds method nodes."""
        from ray_tpu.dag.dag_node import ClassNode

        return ClassNode(self, args, kwargs)

    def _method_num_returns(self) -> dict:
        out = {}
        for name in dir(self._cls):
            method = getattr(self._cls, name, None)
            n = getattr(method, "__ray_tpu_num_returns__", None)
            if n is not None:
                out[name] = n
        return out


def method(num_returns: int = 1):
    """Per-method options decorator (analog of ray.method)."""

    def decorator(fn):
        fn.__ray_tpu_num_returns__ = num_returns
        return fn

    return decorator
