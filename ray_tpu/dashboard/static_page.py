"""Dashboard single-page UI.

Analog of the reference's dashboard client (dashboard/client/ — a built
React app): this image has no node/npm toolchain, so the UI is a
dependency-free vanilla-JS SPA served inline. It consumes the same REST
surface (head.py): live-polling stat tiles, sortable/filterable tables for
nodes/actors/tasks/placement groups/objects/workers, a task summary, job
submission + per-job logs, a log-file browser with tailing, and the raw
Prometheus exposition.
"""

INDEX_HTML = r"""<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>ray_tpu dashboard</title>
<style>
  :root {
    --bg: #0f1419; --panel: #171d24; --panel2: #1e2630; --text: #d6dde6;
    --dim: #8494a6; --accent: #4fa3ff; --ok: #3fb97f; --warn: #e0a63d;
    --err: #e06c5b; --border: #2a3442;
  }
  * { box-sizing: border-box; }
  body { margin: 0; background: var(--bg); color: var(--text);
         font: 13px/1.5 system-ui, -apple-system, "Segoe UI", sans-serif; }
  header { display: flex; align-items: center; gap: 16px; padding: 10px 18px;
           background: var(--panel); border-bottom: 1px solid var(--border); }
  header h1 { font-size: 15px; margin: 0; font-weight: 600; }
  header .addr { color: var(--dim); font-size: 12px; }
  header .right { margin-left: auto; display: flex; gap: 8px; align-items: center; }
  select, input, button, textarea {
    background: var(--panel2); color: var(--text); border: 1px solid var(--border);
    border-radius: 4px; padding: 4px 8px; font: inherit; }
  button { cursor: pointer; }
  button:hover { border-color: var(--accent); }
  nav { display: flex; gap: 2px; padding: 0 12px; background: var(--panel);
        border-bottom: 1px solid var(--border); }
  nav a { padding: 8px 14px; color: var(--dim); text-decoration: none;
          border-bottom: 2px solid transparent; }
  nav a.active { color: var(--text); border-bottom-color: var(--accent); }
  main { padding: 16px 18px; }
  .tiles { display: flex; flex-wrap: wrap; gap: 12px; margin-bottom: 16px; }
  .tile { background: var(--panel); border: 1px solid var(--border);
          border-radius: 6px; padding: 10px 16px; min-width: 130px; }
  .tile .label { color: var(--dim); font-size: 11px; text-transform: uppercase;
                 letter-spacing: .04em; }
  .tile .value { font-size: 20px; font-weight: 600; margin-top: 2px; }
  .tile .sub { color: var(--dim); font-size: 11px; }
  .bar { height: 4px; background: var(--panel2); border-radius: 2px;
         margin-top: 6px; overflow: hidden; }
  .bar i { display: block; height: 100%; background: var(--accent); }
  .toolbar { display: flex; gap: 8px; margin-bottom: 10px; align-items: center; }
  table { border-collapse: collapse; width: 100%; background: var(--panel);
          border: 1px solid var(--border); border-radius: 6px; overflow: hidden; }
  th, td { text-align: left; padding: 6px 10px; border-bottom: 1px solid var(--border);
           font-size: 12px; max-width: 420px; overflow: hidden;
           text-overflow: ellipsis; white-space: nowrap; }
  th { background: var(--panel2); color: var(--dim); cursor: pointer;
       user-select: none; position: sticky; top: 0; }
  th .dir { color: var(--accent); }
  tr:hover td { background: var(--panel2); }
  .pill { display: inline-block; padding: 1px 8px; border-radius: 8px;
          font-size: 11px; }
  .pill.ok { background: rgba(63,185,127,.15); color: var(--ok); }
  .pill.warn { background: rgba(224,166,61,.15); color: var(--warn); }
  .pill.err { background: rgba(224,108,91,.15); color: var(--err); }
  .pill.dim { background: rgba(132,148,166,.15); color: var(--dim); }
  pre.logbox { background: var(--panel); border: 1px solid var(--border);
               border-radius: 6px; padding: 12px; max-height: 480px;
               overflow: auto; font-size: 12px; white-space: pre-wrap; }
  .split { display: flex; gap: 16px; align-items: flex-start; }
  .split > div { flex: 1; min-width: 0; }
  .muted { color: var(--dim); }
  .error-banner { background: rgba(224,108,91,.12); color: var(--err);
                  border: 1px solid var(--err); border-radius: 4px;
                  padding: 6px 12px; margin-bottom: 10px; display: none; }
  form.jobform { display: flex; gap: 8px; margin-bottom: 12px; }
  form.jobform input[name=entrypoint] { flex: 1; }
  h3 { margin: 14px 0 8px; font-size: 13px; color: var(--dim);
       text-transform: uppercase; letter-spacing: .04em; }
</style>
</head>
<body>
<header>
  <h1>ray_tpu</h1>
  <span class="addr" id="addr"></span>
  <div class="right">
    <span class="muted" id="updated"></span>
    <label class="muted">refresh
      <select id="interval">
        <option value="2000">2s</option>
        <option value="5000" selected>5s</option>
        <option value="15000">15s</option>
        <option value="0">off</option>
      </select>
    </label>
    <button onclick="refresh()">refresh now</button>
  </div>
</header>
<nav id="nav"></nav>
<main>
  <div class="error-banner" id="errbox"></div>
  <div class="tiles" id="tiles"></div>
  <div id="content"></div>
</main>
<script>
"use strict";
const TABS = ["overview","actors","tasks","placement_groups","objects","workers","jobs","logs","metrics"];
let tab = location.hash.replace("#","") || "overview";
if (!TABS.includes(tab)) tab = "overview";
let sortKey = null, sortDir = 1, filterText = "";
let timer = null;

const $ = (id) => document.getElementById(id);
const esc = (s) => String(s).replace(/[&<>"']/g, c =>
  ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));

async function jget(url) {
  const r = await fetch(url);
  if (!r.ok) throw new Error(url + " -> " + r.status);
  return r.json();
}

function setError(msg) {
  const box = $("errbox");
  if (!msg) { box.style.display = "none"; return; }
  box.textContent = msg; box.style.display = "block";
}

function drawNav() {
  $("nav").innerHTML = TABS.map(t =>
    `<a href="#${t}" class="${t===tab?"active":""}" onclick="switchTab('${t}')">${t.replace("_"," ")}</a>`
  ).join("");
}
function switchTab(t) { tab = t; sortKey = null; filterText = ""; drawNav(); refresh(); }

function pill(v) {
  const s = String(v).toUpperCase();
  if (["ALIVE","RUNNING","FINISHED","SUCCEEDED","CREATED","OK","TRUE"].includes(s)) return `<span class="pill ok">${esc(v)}</span>`;
  if (["PENDING","PENDING_CREATION","RESTARTING","STARTING","QUEUED"].includes(s)) return `<span class="pill warn">${esc(v)}</span>`;
  if (["DEAD","FAILED","STOPPED","ERROR"].includes(s)) return `<span class="pill err">${esc(v)}</span>`;
  return `<span class="pill dim">${esc(v)}</span>`;
}

function fmtBytes(n) {
  if (typeof n !== "number" || !isFinite(n)) return n;
  const u = ["B","KiB","MiB","GiB","TiB"]; let i = 0;
  while (n >= 1024 && i < u.length-1) { n /= 1024; i++; }
  return n.toFixed(i ? 1 : 0) + " " + u[i];
}

function cell(k, v) {
  if (v === null || v === undefined) return "<span class='muted'>—</span>";
  if (typeof v === "object") return `<code>${esc(JSON.stringify(v))}</code>`;
  if (k.includes("state") || k === "status") return pill(v);
  if ((k.includes("bytes") || k.includes("memory") || k === "size") && typeof v === "number") return fmtBytes(v);
  return esc(v);
}

// rawCols values are inserted as-is (pre-built button HTML).
function table(rows, rawCols) {
  rawCols = rawCols || [];
  if (!rows || !rows.length) return "<p class='muted'>none</p>";
  const cols = Object.keys(rows[0]);
  let data = rows;
  if (filterText) {
    const f = filterText.toLowerCase();
    data = data.filter(r => JSON.stringify(r).toLowerCase().includes(f));
  }
  if (sortKey) {
    data = [...data].sort((a, b) => {
      const x = a[sortKey], y = b[sortKey];
      if (x === y) return 0;
      if (x === null || x === undefined) return 1;
      if (y === null || y === undefined) return -1;
      return (x < y ? -1 : 1) * sortDir;
    });
  }
  const head = cols.map(c =>
    `<th data-sort="${esc(c)}">${esc(c)}${sortKey===c ? `<span class="dir"> ${sortDir>0?"▲":"▼"}</span>` : ""}</th>`
  ).join("");
  const body = data.slice(0, 500).map(r =>
    "<tr>" + cols.map(c =>
      rawCols.includes(c) ? `<td>${r[c]}</td>`
                          : `<td title="${esc(r[c] ?? "")}">${cell(c, r[c])}</td>`
    ).join("") + "</tr>"
  ).join("");
  const more = data.length > 500 ? `<p class="muted">showing 500 of ${data.length}</p>` : "";
  return `<table><thead><tr>${head}</tr></thead><tbody>${body}</tbody></table>${more}`;
}
function setSort(c) { if (sortKey === c) sortDir = -sortDir; else { sortKey = c; sortDir = 1; } refresh(); }
function toolbar() {
  return `<div class="toolbar">
    <input placeholder="filter…" value="${esc(filterText)}"
           oninput="filterText=this.value" onchange="this.blur(); refresh()">
  </div>`;
}

let statusPromise = null;

async function drawTiles() {
  try {
    const s = await statusPromise;
    const nodes = s.nodes || [];
    const alive = nodes.filter(n => (n.state||"").toUpperCase() === "ALIVE").length;
    const cr = s.cluster_resources || {}, ar = s.available_resources || {};
    const cpuT = cr.CPU || 0, cpuU = cpuT - (ar.CPU || 0);
    const tpuT = cr.TPU || 0, tpuU = tpuT - (ar.TPU || 0);
    let storeUsed = 0, storeCap = 0;
    nodes.forEach(n => { const su = n.store_usage || {}; storeUsed += su.used||0; storeCap += su.capacity||0; });
    const tiles = [
      {label: "nodes alive", value: `${alive} / ${nodes.length}`},
      {label: "CPUs in use", value: `${cpuU.toFixed(1)} / ${cpuT}`, frac: cpuT ? cpuU/cpuT : 0},
      ...(tpuT ? [{label: "TPUs in use", value: `${tpuU.toFixed(1)} / ${tpuT}`, frac: tpuU/tpuT}] : []),
      {label: "object store", value: fmtBytes(storeUsed), sub: "of " + fmtBytes(storeCap),
       frac: storeCap ? storeUsed/storeCap : 0},
    ];
    $("tiles").innerHTML = tiles.map(t => `
      <div class="tile"><div class="label">${t.label}</div>
        <div class="value">${t.value}</div>
        ${t.sub ? `<div class="sub">${t.sub}</div>` : ""}
        ${t.frac !== undefined ? `<div class="bar"><i style="width:${Math.min(100, t.frac*100).toFixed(0)}%"></i></div>` : ""}
      </div>`).join("");
  } catch (e) { setError("cluster status unavailable: " + e.message); }
}

const DRAW = {
  async overview() {
    const s = await statusPromise;
    return toolbar() + "<h3>Nodes</h3>" + table(s.nodes || []);
  },
  async actors()   { return toolbar() + table((await jget("/api/v0/actors")).result); },
  async tasks() {
    const [summary, tasks] = await Promise.all([
      jget("/api/v0/tasks/summarize").catch(() => null),
      jget("/api/v0/tasks"),
    ]);
    let out = "";
    if (summary && typeof summary === "object" && Object.keys(summary).length) {
      out += "<h3>Summary</h3>" + table(Object.entries(summary).map(
        ([name, info]) => Object.assign({func_or_class_name: name},
                                        typeof info === "object" ? info : {value: info})));
    }
    return toolbar() + out + "<h3>Tasks</h3>" + table(tasks.result);
  },
  async placement_groups() { return toolbar() + table((await jget("/api/v0/placement_groups")).result); },
  async objects()  { return toolbar() + table((await jget("/api/v0/objects")).result); },
  async workers()  { return toolbar() + table((await jget("/api/v0/workers")).result); },
  async jobs() {
    const jobs = await jget("/api/jobs");
    const rows = (Array.isArray(jobs) ? jobs : (jobs.result || jobs.jobs || [])).map(r => {
      const id = r.submission_id || r.job_id || "";
      return Object.assign({}, r, {
        actions: `<button data-act="joblogs" data-id="${esc(id)}">logs</button> ` +
                 `<button data-act="jobstop" data-id="${esc(id)}">stop</button>`,
      });
    });
    const logHtml = window._joblog
      ? `<h3>Logs: ${esc(window._joblog.id)}</h3><pre class="logbox">${esc(window._joblog.text)}</pre>`
      : "";
    return `
      <form class="jobform" onsubmit="submitJob(event)">
        <input name="entrypoint" placeholder='entrypoint, e.g. python -c "print(42)"' required>
        <button>submit job</button>
      </form>` + table(rows, ["actions"]) + logHtml;
  },
  async logs() {
    const files = (await jget("/api/v0/logs")).result || [];
    const body = files.map(f =>
      `<tr><td><button data-act="tail" data-file="${esc(f.file)}">${esc(f.file)}</button></td>` +
      `<td>${fmtBytes(f.size)}</td></tr>`).join("");
    const tbl = files.length
      ? `<table><thead><tr><th>file</th><th>size</th></tr></thead><tbody>${body}</tbody></table>`
      : "<p class='muted'>no log files</p>";
    const tail = window._logtail
      ? `<div><h3>${esc(window._logtail.file)}</h3><pre class="logbox">${esc(window._logtail.text)}</pre></div>`
      : "<div><p class='muted'>select a file to tail</p></div>";
    return `<div class="split"><div>${tbl}</div>${tail}</div>`;
  },
  async metrics() {
    const r = await fetch("/metrics");
    return `<pre class="logbox">${esc(await r.text())}</pre>`;
  },
};

async function showJobLogs(id) {
  try {
    const r = await jget("/api/jobs/" + encodeURIComponent(id) + "/logs");
    window._joblog = {id, text: r.logs || "(empty)"};
  } catch (e) { window._joblog = {id, text: "error: " + e.message}; }
  refresh();
}
async function stopJob(id) {
  try {
    const r = await fetch("/api/jobs/" + encodeURIComponent(id) + "/stop", {method: "POST"});
    if (!r.ok) setError("stop failed: " + ((await r.json()).error || r.status));
  } catch (e) { setError("stop failed: " + e.message); }
  refresh();
}
async function tailLog(file) {
  try {
    const r = await jget("/api/v0/logs/tail?file=" + encodeURIComponent(file) + "&lines=400");
    window._logtail = {file, text: (r.lines || []).join("\n") || "(empty)"};
  } catch (e) { window._logtail = {file, text: "error: " + e.message}; }
  refresh();
}
async function submitJob(ev) {
  ev.preventDefault();
  const entry = ev.target.entrypoint.value;
  try {
    const r = await fetch("/api/jobs", {method: "POST", headers: {"Content-Type": "application/json"},
                                        body: JSON.stringify({entrypoint: entry})});
    if (!r.ok) { setError("job submit failed: " + ((await r.json()).error || r.status)); }
    else ev.target.entrypoint.value = "";
  } catch (e) { setError("job submit failed: " + e.message); }
  refresh();
}

async function refresh() {
  statusPromise = jget("/api/cluster_status");
  drawTiles();
  // Never clobber in-progress typing: if an input inside the content area
  // has focus, skip this re-render (tiles still update).
  const ae = document.activeElement;
  if (ae && $("content").contains(ae) && ["INPUT","TEXTAREA"].includes(ae.tagName)) {
    $("updated").textContent = "paused (editing)";
    return;
  }
  try {
    $("content").innerHTML = await DRAW[tab]();
    setError(null);
  } catch (e) {
    $("content").innerHTML = "";
    setError(tab + " unavailable: " + e.message);
  }
  $("updated").textContent = "updated " + new Date().toLocaleTimeString();
}

// Delegated actions: ids/filenames are user- or job-influenced, so they
// ride data-* attributes (HTML-attr escaping is sufficient there) instead
// of being spliced into inline JS strings (where entity decoding would
// reopen script injection).
$("content").addEventListener("click", (ev) => {
  const el = ev.target.closest("[data-act],[data-sort]");
  if (!el) return;
  if (el.dataset.sort !== undefined) return setSort(el.dataset.sort);
  if (el.dataset.act === "joblogs") return showJobLogs(el.dataset.id);
  if (el.dataset.act === "jobstop") return stopJob(el.dataset.id);
  if (el.dataset.act === "tail") return tailLog(el.dataset.file);
});

function schedule() {
  if (timer) clearInterval(timer);
  const ms = parseInt($("interval").value, 10);
  if (ms > 0) timer = setInterval(refresh, ms);
}
$("interval").addEventListener("change", schedule);

jget("/api/version").then(v => {
  $("addr").textContent = "v" + v.version + " · " + v.ray_address;
}).catch(() => {});
drawNav();
refresh();
schedule();
</script>
</body>
</html>
"""
