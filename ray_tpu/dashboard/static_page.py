"""Embedded dashboard page.

Stand-in for the reference's React frontend (dashboard/client/): one
self-contained HTML page (no build step, no external assets) that polls the
head's REST API and renders nodes/resources, actors, jobs, and task summary.
"""

INDEX_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>ray_tpu dashboard</title>
<style>
  body { font-family: -apple-system, system-ui, sans-serif; margin: 2rem; color: #222; }
  h1 { font-size: 1.3rem; }  h2 { font-size: 1.05rem; margin-top: 1.6rem; }
  table { border-collapse: collapse; width: 100%; font-size: 0.85rem; }
  th, td { text-align: left; padding: 4px 10px; border-bottom: 1px solid #e5e5e5; }
  th { color: #666; font-weight: 600; }
  .pill { display: inline-block; padding: 1px 8px; border-radius: 10px; font-size: 0.75rem; }
  .ALIVE, .RUNNING, .SUCCEEDED, .FINISHED { background: #e6f4ea; color: #137333; }
  .DEAD, .FAILED { background: #fce8e6; color: #c5221f; }
  .PENDING, .PENDING_CREATION, .STOPPED { background: #fef7e0; color: #b06000; }
  .muted { color: #999; }
  #updated { font-size: 0.75rem; color: #999; }
</style>
</head>
<body>
<h1>ray_tpu dashboard <span id="updated"></span></h1>
<h2>Cluster</h2><div id="cluster"></div>
<h2>Nodes</h2><table id="nodes"></table>
<h2>Actors</h2><table id="actors"></table>
<h2>Placement groups</h2><table id="pgs"></table>
<h2>Jobs (submitted)</h2><table id="jobs"></table>
<h2>Tasks</h2><div id="tasks"></div>
<h2>Logs</h2>
<select id="logsel"><option value="">— pick a log file —</option></select>
<pre id="logview" style="background:#f7f7f7;padding:8px;max-height:320px;overflow:auto;font-size:0.75rem"></pre>
<script>
const esc = (v) => String(v).replace(/[&<>"']/g,
  (c) => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));
const fmt = (n) => typeof n === "number" ? (Number.isInteger(n) ? n : n.toFixed(2)) : n;
// User-controlled strings (actor names, job entrypoints) flow into these
// templates — escape everything; `pill` output is marked pre-escaped.
const pill = (s) => ({__html: `<span class="pill ${esc(s)}">${esc(s)}</span>`});
const cell = (c) => c === null || c === undefined ? '<span class=muted>—</span>'
  : (c && c.__html) ? c.__html : esc(c);
async function j(path) { const r = await fetch(path); return r.json(); }
function table(el, headers, rows) {
  el.innerHTML = "<tr>" + headers.map(h => `<th>${esc(h)}</th>`).join("") + "</tr>" +
    (rows.length ? rows.map(r => "<tr>" + r.map(c => `<td>${cell(c)}</td>`).join("") + "</tr>").join("")
                 : `<tr><td colspan=${headers.length} class=muted>none</td></tr>`);
}
async function refresh() {
  try {
    const status = await j("/api/cluster_status");
    const res = status.cluster_resources || {}, avail = status.available_resources || {};
    document.getElementById("cluster").innerHTML =
      Object.keys(res).sort().map(k =>
        `<b>${esc(k)}</b>: ${fmt(res[k] - (avail[k] ?? 0))}/${fmt(res[k])} used`).join(" &nbsp;·&nbsp; ");
    const gb = (n) => n == null ? null : (n / 1073741824).toFixed(1) + "G";
    table(document.getElementById("nodes"),
      ["node", "state", "address", "active workers", "cpu %", "mem", "workers rss"],
      (status.nodes || []).map(n => {
        const s = n.stats || {};
        const wrss = Object.values(s.workers || {}).reduce((a, w) => a + (w.rss || 0), 0);
        return [n.node_id.slice(0,12), pill(n.state),
          (n.address || []).join(":"), n.num_active_workers ?? 0,
          s.cpu_percent != null ? fmt(s.cpu_percent) : null,
          s.mem_total ? `${gb(s.mem_used)}/${gb(s.mem_total)}` : null,
          wrss ? gb(wrss) : null];
      }));
    const actors = (await j("/api/v0/actors")).result || [];
    table(document.getElementById("actors"),
      ["actor", "name", "state", "node", "restarts"],
      actors.map(a => [a.actor_id.slice(0,12), a.name, pill(a.state),
        (a.node_id || "").slice(0,8), a.num_restarts ?? 0]));
    const pgs = (await j("/api/v0/placement_groups")).result || [];
    table(document.getElementById("pgs"),
      ["id", "state", "strategy", "bundles"],
      pgs.map(p => [String(p.placement_group_id || p.id || "").slice(0,12), pill(p.state || "?"),
        p.strategy, JSON.stringify(p.bundles || []).slice(0, 80)]));
    const jobs = await j("/api/jobs/");
    table(document.getElementById("jobs"),
      ["id", "status", "entrypoint"],
      (jobs || []).map(x => [x.submission_id, pill(x.status), x.entrypoint]));
    const summary = await j("/api/v0/tasks/summarize");
    document.getElementById("tasks").innerHTML =
      "<table>" + "<tr><th>task</th><th>total</th><th>states</th></tr>" +
      Object.entries(summary).map(([name, e]) =>
        `<tr><td>${esc(name)}</td><td>${esc(e.total)}</td><td>` +
        Object.entries(e.states || {}).map(([s, c]) => `${pill(s).__html} ${esc(c)}`).join(" ") +
        `</td></tr>`).join("") + "</table>";
    document.getElementById("updated").textContent =
      "updated " + new Date().toLocaleTimeString();
  } catch (e) {
    document.getElementById("updated").textContent = "refresh failed: " + e;
  }
}
async function refreshLogs() {
  try {
    const files = (await j("/api/v0/logs")).result || [];
    const sel = document.getElementById("logsel");
    const cur = sel.value;
    sel.innerHTML = '<option value="">— pick a log file —</option>' +
      files.map(f => `<option value="${esc(f.file)}">${esc(f.file)} (${f.size}b)</option>`).join("");
    sel.value = cur;
  } catch (e) {}
}
document.getElementById("logsel").addEventListener("change", async (ev) => {
  const f = ev.target.value;
  if (!f) return;
  const r = await j("/api/v0/logs/tail?file=" + encodeURIComponent(f) + "&lines=200");
  document.getElementById("logview").textContent = (r.lines || []).join("\n");
});
refresh(); refreshLogs(); setInterval(refresh, 3000); setInterval(refreshLogs, 10000);
</script>
</body>
</html>
"""
