"""Dashboard head HTTP server.

Routes (subset of the reference's dashboard REST surface, dashboard/head.py +
dashboard/modules/{job/job_head.py,metrics}):

- ``GET  /api/version``                 — version + ray address
- ``GET  /api/cluster_status``          — nodes, resources, autoscaler summary
- ``GET  /api/v0/<resource>``           — state API (tasks/actors/nodes/jobs/
                                          placement_groups/workers/objects)
- ``GET  /api/v0/tasks/summarize``      — task summary
- ``GET  /metrics``                     — Prometheus text exposition
- ``POST /api/jobs/``                   — submit job {entrypoint, ...}
- ``GET  /api/jobs/``                   — list submitted jobs
- ``GET  /api/jobs/<id>``               — job info
- ``GET  /api/jobs/<id>/logs``          — job logs {"logs": "..."}
- ``POST /api/jobs/<id>/stop``          — stop job
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import ray_tpu
from ray_tpu._private.rpc import RpcClient
from ray_tpu.dashboard.job_manager import JobManager

logger = logging.getLogger(__name__)


class DashboardHead:
    def __init__(self, gcs_address, session_dir: str, host: str = "127.0.0.1", port: int = 0):
        self._gcs_address = tuple(gcs_address)
        self._session_dir = session_dir
        self.job_manager = JobManager(gcs_address, session_dir)
        # One cached GCS client shared by request handlers (guarded: the
        # ThreadingHTTPServer serves concurrent requests). Building a fresh
        # RpcClient per request costs a TCP connect on every poll of a hot
        # endpoint and leaks sockets under load when handlers die mid-write.
        self._gcs_client = None
        self._gcs_client_lock = threading.Lock()
        head = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                logger.debug("dashboard: " + fmt, *args)

            def _send(self, code: int, payload, content_type="application/json"):
                body = (
                    payload
                    if isinstance(payload, bytes)
                    else json.dumps(payload).encode()
                    if content_type == "application/json"
                    else str(payload).encode()
                )
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    head._handle_get(self)
                except BrokenPipeError:
                    pass
                except Exception as e:
                    logger.exception("dashboard GET %s failed", self.path)
                    try:
                        self._send(500, {"error": str(e)})
                    except Exception:
                        pass

            def do_POST(self):
                try:
                    head._handle_post(self)
                except BrokenPipeError:
                    pass
                except Exception as e:
                    logger.exception("dashboard POST %s failed", self.path)
                    try:
                        self._send(500, {"error": str(e)})
                    except Exception:
                        pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.address = (host, self._server.server_address[1])
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="dashboard-head", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------------
    def _gcs(self) -> RpcClient:
        """Cached GCS client (RpcClient is safe to call from any thread and
        reconnects internally; only creation needs the guard)."""
        with self._gcs_client_lock:
            if self._gcs_client is None:
                self._gcs_client = RpcClient(self._gcs_address, label="dashboard-gcs")
            return self._gcs_client

    def _state(self):
        from ray_tpu._private.state import GlobalState

        return GlobalState(gcs_address=self._gcs_address)

    def _handle_get(self, req):
        path = req.path.split("?", 1)[0].rstrip("/") or "/"
        if path == "/":
            from ray_tpu.dashboard.static_page import INDEX_HTML

            req._send(200, INDEX_HTML, content_type="text/html; charset=utf-8")
            return
        if path == "/api/version":
            req._send(200, {"version": ray_tpu.__version__, "ray_address": "%s:%d" % self._gcs_address})
            return
        if path == "/api/cluster_status":
            state = self._state()
            try:
                req._send(
                    200,
                    {
                        "nodes": state.nodes(),
                        "cluster_resources": state.cluster_resources(),
                        "available_resources": state.available_resources(),
                    },
                )
            finally:
                state.close()
            return
        if path == "/metrics":
            from ray_tpu.util.metrics import prometheus_text

            req._send(200, prometheus_text(self._gcs()), content_type="text/plain; version=0.0.4")
            return
        if path == "/api/v0/debug/flight_recorder":
            # Cluster-wide flight-recorder dump (merged, stamp-ordered) —
            # the HTTP face of `ray_tpu debug dump`.
            state = self._state()
            try:
                req._send(200, {"result": state.flight_recorder_dump()})
            finally:
                state.close()
            return
        if path == "/api/v0/tasks/summarize":
            from ray_tpu.util.state import summarize_tasks

            req._send(200, summarize_tasks(address="%s:%d" % self._gcs_address))
            return
        if path == "/api/v0/logs":
            # Log-file listing (reference: dashboard/modules/log/): on this
            # single-session-dir layout every node's worker logs land here.
            import os

            logdir = os.path.join(self._session_dir, "logs")
            files = []
            if os.path.isdir(logdir):
                for root, _dirs, names in os.walk(logdir):
                    for name in names:
                        full = os.path.join(root, name)
                        files.append({
                            "file": os.path.relpath(full, logdir),
                            "size": os.path.getsize(full),
                        })
            req._send(200, {"result": sorted(files, key=lambda f: f["file"])})
            return
        if path == "/api/v0/logs/tail":
            import os
            from urllib.parse import parse_qs, urlparse

            q = parse_qs(urlparse(req.path).query)
            rel = (q.get("file") or [""])[0]
            try:
                lines = max(1, min(int((q.get("lines") or ["200"])[0]), 10_000))
            except ValueError:
                req._send(400, {"error": "lines must be an integer"})
                return
            logdir = os.path.realpath(os.path.join(self._session_dir, "logs"))
            full = os.path.realpath(os.path.join(logdir, rel))
            # Path-traversal guard: the file must stay inside the log dir.
            if not full.startswith(logdir + os.sep) or not os.path.isfile(full):
                req._send(404, {"error": f"no such log file {rel!r}"})
                return
            with open(full, "rb") as f:
                f.seek(0, 2)
                size = f.tell()
                f.seek(max(0, size - 256 * 1024))
                data = f.read().decode("utf-8", "replace")
            req._send(200, {"lines": data.splitlines()[-lines:]})
            return
        if path.startswith("/api/v0/"):
            from ray_tpu.util.state import api as state_api

            resource = path[len("/api/v0/") :]
            fn = getattr(state_api, f"list_{resource}", None)
            if fn is None:
                req._send(404, {"error": f"unknown resource {resource!r}"})
                return
            req._send(200, {"result": fn(address="%s:%d" % self._gcs_address)})
            return
        if path.startswith("/api/workflows/events/"):
            # HTTP event provider (reference workflow/http_event_provider.py):
            # read back a delivered event.
            from ray_tpu.workflow.event_listener import EVENT_KV_PREFIX

            key = path[len("/api/workflows/events/") :]
            resp = self._gcs().call("kv_get", {"key": EVENT_KV_PREFIX + key})
            if not resp.get("found"):
                req._send(404, {"error": f"no event for key {key!r}"})
                return
            # The KV value may have been written by a non-JSON producer
            # (direct kv_put): surface a client error, not a 500. Strict
            # decode — UnicodeDecodeError is a ValueError — so invalid UTF-8
            # 422s instead of being mangled to U+FFFD and served as 200.
            try:
                event = json.loads(bytes(resp["value"]).decode("utf-8"))
            except (ValueError, TypeError):
                req._send(
                    422,
                    {"error": f"event value for key {key!r} is not valid JSON"},
                )
                return
            req._send(200, {"key": key, "event": event})
            return
        if path == "/api/jobs":
            req._send(200, self.job_manager.list_jobs())
            return
        if path.startswith("/api/jobs/"):
            rest = path[len("/api/jobs/") :]
            if rest.endswith("/logs"):
                sid = rest[: -len("/logs")]
                try:
                    req._send(200, {"logs": self.job_manager.get_job_logs(sid)})
                except KeyError:
                    req._send(404, {"error": f"no such job {sid}"})
                return
            info = self.job_manager.get_job_info(rest)
            if info is None:
                req._send(404, {"error": f"no such job {rest}"})
            else:
                req._send(200, info)
            return
        req._send(404, {"error": f"no route {path}"})

    def _handle_post(self, req):
        path = req.path.split("?", 1)[0].rstrip("/")
        length = int(req.headers.get("Content-Length") or 0)
        body = json.loads(req.rfile.read(length) or b"{}") if length else {}
        if path == "/api/jobs":
            try:
                sid = self.job_manager.submit_job(
                    entrypoint=body["entrypoint"],
                    submission_id=body.get("submission_id"),
                    runtime_env=body.get("runtime_env"),
                    metadata=body.get("metadata"),
                )
            except KeyError:
                req._send(400, {"error": "missing required field 'entrypoint'"})
                return
            except ValueError as e:
                req._send(400, {"error": str(e)})
                return
            req._send(200, {"submission_id": sid})
            return
        if path.startswith("/api/workflows/events/"):
            # HTTP event provider: deliver an event payload to workflows
            # polling KVEventListener(key) (reference http_event_provider.py
            # POST /event/send_event/{workflow_id}).
            from ray_tpu.workflow.event_listener import EVENT_KV_PREFIX

            key = path[len("/api/workflows/events/") :]
            self._gcs().call(
                "kv_put",
                {
                    "key": EVENT_KV_PREFIX + key,
                    "value": json.dumps(body).encode(),
                    "overwrite": True,
                },
            )
            req._send(200, {"delivered": key})
            return
        if path.startswith("/api/jobs/") and path.endswith("/stop"):
            sid = path[len("/api/jobs/") : -len("/stop")]
            try:
                stopped = self.job_manager.stop_job(sid)
            except KeyError:
                req._send(404, {"error": f"no such job {sid}"})
                return
            req._send(200, {"stopped": stopped})
            return
        req._send(404, {"error": f"no route {path}"})

    def stop(self):
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:
            pass
        with self._gcs_client_lock:
            client, self._gcs_client = self._gcs_client, None
        if client is not None:
            try:
                client.close()
            except Exception:
                pass
