"""Per-node dashboard agent.

Analog of the reference's dashboard/agent.py: a per-node stats reporter that
samples host-level metrics (CPU/memory/disk via psutil), per-worker process
stats (RSS, cpu%), and accelerator presence, and ships them to the GCS where
the dashboard head's REST API and UI read them (reference: reporter module
dashboard/modules/reporter/).

Runs in two modes:
- in-raylet asyncio task (default — the raylet spawns ``NodeStatsAgent.run``
  alongside its heartbeat loop; one fewer process per node on small hosts)
- standalone process: ``python -m ray_tpu.dashboard.agent --gcs host:port
  --node-id <id>`` (the reference's layout; useful when the raylet must stay
  minimal or stats sampling needs isolation).
"""

from __future__ import annotations

import asyncio
import logging
import os

logger = logging.getLogger(__name__)

REPORT_INTERVAL_S = 5.0


def _sample_node_stats(session_dir: str, worker_pids: dict) -> dict:
    """One stats sample. worker_pids: {worker_id: pid}."""
    try:
        import psutil
    except ImportError:
        return {}
    stats: dict = {}
    try:
        stats["cpu_percent"] = psutil.cpu_percent(interval=None)
        vm = psutil.virtual_memory()
        stats["mem_used"] = int(vm.used)
        stats["mem_total"] = int(vm.total)
        try:
            du = psutil.disk_usage(session_dir or "/")
            stats["disk_used"] = int(du.used)
            stats["disk_total"] = int(du.total)
        except OSError:
            pass
        workers = {}
        for wid, pid in worker_pids.items():
            try:
                p = psutil.Process(pid)
                with p.oneshot():
                    workers[wid] = {
                        "pid": pid,
                        "rss": int(p.memory_info().rss),
                        "cpu_percent": p.cpu_percent(interval=None),
                        "status": p.status(),
                    }
            except psutil.Error:
                continue
        stats["workers"] = workers
        # Accelerator presence: chip count advertised by the node's resource
        # set is authoritative; /dev/accel* confirms local hardware.
        stats["tpu_devices"] = len(
            [d for d in os.listdir("/dev") if d.startswith("accel")]
        ) if os.path.isdir("/dev") else 0
    except Exception:
        logger.debug("stats sample failed", exc_info=True)
    return stats


class NodeStatsAgent:
    """In-raylet agent: samples and reports to the GCS on an interval."""

    def __init__(self, raylet):
        self.raylet = raylet

    async def run(self):
        # First cpu_percent call primes psutil's delta bookkeeping.
        _sample_node_stats(self.raylet.session_dir, {})
        while True:
            try:
                pids = {
                    wid: w.pid
                    for wid, w in self.raylet.workers.items()
                    if w.state != "dead"
                }
                stats = _sample_node_stats(self.raylet.session_dir, pids)
                if stats:
                    await self.raylet.gcs.acall(
                        "report_node_stats",
                        {"node_id": self.raylet.node_id, "stats": stats},
                    )
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.debug("node stats report failed", exc_info=True)
            await asyncio.sleep(REPORT_INTERVAL_S)


def main(argv=None):
    """Standalone agent process (reference: dashboard/agent.py entry)."""
    import argparse
    import time

    from ray_tpu._private.rpc import RpcClient

    ap = argparse.ArgumentParser(prog="ray_tpu-dashboard-agent")
    ap.add_argument("--gcs", required=True, help="GCS address host:port")
    ap.add_argument("--node-id", required=True)
    ap.add_argument("--session-dir", default="/tmp/ray_tpu")
    args = ap.parse_args(argv)
    host, port = args.gcs.rsplit(":", 1)
    gcs = RpcClient((host, int(port)), label="dashboard-agent")
    _sample_node_stats(args.session_dir, {})
    while True:
        # Standalone mode discovers worker processes on this host by their
        # command line (the GCS node record carries only worker counts).
        pids = {}
        try:
            import psutil

            for p in psutil.process_iter(["pid", "cmdline"]):
                cmd = " ".join(p.info.get("cmdline") or [])
                if "ray_tpu._private.worker_main" in cmd:
                    pids[f"pid-{p.info['pid']}"] = p.info["pid"]
        except Exception:
            pass
        try:
            stats = _sample_node_stats(args.session_dir, pids)
            if stats:
                gcs.call("report_node_stats", {"node_id": args.node_id, "stats": stats})
        except Exception:
            logger.debug("standalone stats report failed", exc_info=True)
        time.sleep(REPORT_INTERVAL_S)


if __name__ == "__main__":
    main()
