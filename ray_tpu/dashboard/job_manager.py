"""Job manager — drives submitted jobs as driver subprocesses.

Analog of the reference's dashboard/modules/job/job_manager.py: each submitted
job runs its shell entrypoint in a subprocess whose environment points at the
cluster (RAY_TPU_ADDRESS), with stdout/stderr captured to a per-job log file;
job metadata and status live in the GCS KV under ``job_submission:<id>`` so
any process (dashboard, CLI, SDK) can read them.
"""

from __future__ import annotations

import json
import os
import subprocess
import threading
import time
import uuid

from ray_tpu._private.rpc import RpcClient

# Terminal states mirror the reference's JobStatus (dashboard/modules/job/common.py).
JOB_STATUSES = ("PENDING", "RUNNING", "SUCCEEDED", "FAILED", "STOPPED")


def _kv_key(submission_id: str) -> str:
    return f"job_submission:{submission_id}"


class JobManager:
    def __init__(self, gcs_address, session_dir: str):
        self._gcs_address = tuple(gcs_address)
        self._session_dir = session_dir
        self._log_dir = os.path.join(session_dir, "job_logs")
        os.makedirs(self._log_dir, exist_ok=True)
        self._procs: dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()

    def _gcs(self) -> RpcClient:
        return RpcClient(self._gcs_address, label="job-manager")

    def _write_info(self, info: dict):
        gcs = self._gcs()
        try:
            gcs.call(
                "kv_put",
                {
                    "key": _kv_key(info["submission_id"]),
                    "value": json.dumps(info).encode(),
                    "overwrite": True,
                },
            )
        finally:
            gcs.close()

    def _read_info(self, submission_id: str) -> dict | None:
        gcs = self._gcs()
        try:
            resp = gcs.call("kv_get", {"key": _kv_key(submission_id)})
        finally:
            gcs.close()
        if not resp.get("found"):
            return None
        return json.loads(resp["value"])

    # ------------------------------------------------------------------
    # Public API (mirrors the reference's JobManager surface)
    # ------------------------------------------------------------------
    def submit_job(
        self,
        entrypoint: str,
        submission_id: str | None = None,
        runtime_env: dict | None = None,
        metadata: dict | None = None,
        entrypoint_num_cpus: float | None = None,
    ) -> str:
        submission_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:16]}"
        if self._read_info(submission_id) is not None:
            raise ValueError(f"job {submission_id} already exists")
        log_path = os.path.join(self._log_dir, f"{submission_id}.log")
        info = {
            "submission_id": submission_id,
            "entrypoint": entrypoint,
            "status": "PENDING",
            "message": "Job is queued.",
            "runtime_env": runtime_env or {},
            "metadata": metadata or {},
            "start_time": time.time(),
            "end_time": None,
            "log_path": log_path,
        }
        self._write_info(info)
        threading.Thread(
            target=self._run_job, args=(info,), name=f"job-{submission_id}", daemon=True
        ).start()
        return submission_id

    def _run_job(self, info: dict):
        submission_id = info["submission_id"]
        # stop_job may have raced submit: honor a STOPPED written before
        # the entrypoint launched.
        latest = self._read_info(submission_id)
        if latest is not None and latest.get("status") == "STOPPED":
            return
        env = dict(os.environ)
        host, port = self._gcs_address
        env["RAY_TPU_ADDRESS"] = f"{host}:{port}"
        env["RAY_TPU_JOB_SUBMISSION_ID"] = submission_id
        renv = info.get("runtime_env") or {}
        env.update({str(k): str(v) for k, v in (renv.get("env_vars") or {}).items()})
        cwd = renv.get("working_dir") or None
        log_f = open(info["log_path"], "wb")
        try:
            proc = subprocess.Popen(
                info["entrypoint"],
                shell=True,
                stdout=log_f,
                stderr=subprocess.STDOUT,
                env=env,
                cwd=cwd,
                start_new_session=True,
            )
        except Exception as e:
            log_f.close()
            info.update(status="FAILED", message=f"failed to start: {e}", end_time=time.time())
            self._write_info(info)
            return
        with self._lock:
            self._procs[submission_id] = proc
        # Re-check after launch: a stop that landed between the PENDING check
        # and Popen must win, not leak a running entrypoint.
        latest = self._read_info(submission_id)
        if latest is not None and latest.get("status") == "STOPPED":
            try:
                os.killpg(os.getpgid(proc.pid), 15)
            except Exception:
                proc.terminate()
            proc.wait()
            with self._lock:
                self._procs.pop(submission_id, None)
            return
        info.update(status="RUNNING", message="Job is running.")
        self._write_info(info)
        code = proc.wait()
        log_f.close()
        with self._lock:
            self._procs.pop(submission_id, None)
        # A stop_job SIGTERM surfaces as negative returncode; keep STOPPED if set.
        latest = self._read_info(submission_id) or info
        if latest.get("status") == "STOPPED":
            return
        if code == 0:
            latest.update(status="SUCCEEDED", message="Job finished successfully.")
        else:
            latest.update(status="FAILED", message=f"Job exited with code {code}.")
        latest["end_time"] = time.time()
        self._write_info(latest)

    def stop_job(self, submission_id: str) -> bool:
        info = self._read_info(submission_id)
        if info is None:
            raise KeyError(f"no such job {submission_id}")
        if info.get("status") in ("SUCCEEDED", "FAILED", "STOPPED"):
            return False
        info.update(status="STOPPED", message="Job was stopped.", end_time=time.time())
        self._write_info(info)
        with self._lock:
            proc = self._procs.get(submission_id)
        if proc is not None and proc.poll() is None:
            try:
                # Entrypoint ran with start_new_session — signal the whole group.
                os.killpg(os.getpgid(proc.pid), 15)
            except Exception:
                proc.terminate()
        # PENDING jobs (no proc yet) are stopped by the STOPPED status alone:
        # _run_job re-checks it before and after launching the entrypoint.
        return True

    def get_job_info(self, submission_id: str) -> dict | None:
        info = self._read_info(submission_id)
        if info is not None:
            # Internal head-node filesystem path; not part of the API surface.
            info.pop("log_path", None)
        return info

    def list_jobs(self) -> list[dict]:
        gcs = self._gcs()
        try:
            keys = gcs.call("kv_keys", {"prefix": "job_submission:"}).get("keys", [])
            out = []
            for key in keys:
                resp = gcs.call("kv_get", {"key": key})
                if resp.get("found"):
                    info = json.loads(resp["value"])
                    info.pop("log_path", None)
                    out.append(info)
            return sorted(out, key=lambda j: j.get("start_time") or 0)
        finally:
            gcs.close()

    def get_job_logs(self, submission_id: str) -> str:
        info = self._read_info(submission_id)
        if info is None:
            raise KeyError(f"no such job {submission_id}")
        path = info.get("log_path")
        if not path or not os.path.exists(path):
            return ""
        with open(path, "r", errors="replace") as f:
            return f.read()
