"""Dashboard head — HTTP API over cluster state + job submission.

TPU-native analog of the reference's dashboard backend (dashboard/dashboard.py
head process, dashboard/state_aggregator.py, dashboard/modules/{job,metrics}):
a threaded HTTP server reading the GCS, serving the state API as REST, the
Prometheus metrics exposition, and the job-submission REST endpoints.
"""

from ray_tpu.dashboard.head import DashboardHead  # noqa: F401
