"""Job submission SDK.

Analog of the reference's ``ray.job_submission.JobSubmissionClient``
(dashboard/modules/job/sdk.py:40) — a thin REST client against the dashboard
head's ``/api/jobs/`` endpoints.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


class JobSubmissionClient:
    def __init__(self, address: str):
        """``address`` is the dashboard HTTP address, e.g. ``http://127.0.0.1:8265``."""
        if not address.startswith("http"):
            address = "http://" + address
        self._base = address.rstrip("/")

    def _request(self, method: str, path: str, payload: dict | None = None):
        data = json.dumps(payload).encode() if payload is not None else None
        req = urllib.request.Request(self._base + path, data=data, method=method)
        if data is not None:
            req.add_header("Content-Type", "application/json")
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return json.loads(resp.read() or b"null")
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")
            try:
                detail = json.loads(detail).get("error", detail)
            except Exception:
                pass
            raise RuntimeError(f"{method} {path} failed ({e.code}): {detail}") from None

    def submit_job(
        self,
        *,
        entrypoint: str,
        submission_id: str | None = None,
        runtime_env: dict | None = None,
        metadata: dict | None = None,
    ) -> str:
        resp = self._request(
            "POST",
            "/api/jobs/",
            {
                "entrypoint": entrypoint,
                "submission_id": submission_id,
                "runtime_env": runtime_env,
                "metadata": metadata,
            },
        )
        return resp["submission_id"]

    def list_jobs(self) -> list[dict]:
        return self._request("GET", "/api/jobs/")

    def get_job_info(self, submission_id: str) -> dict:
        return self._request("GET", f"/api/jobs/{submission_id}")

    def get_job_status(self, submission_id: str) -> str:
        return self.get_job_info(submission_id)["status"]

    def get_job_logs(self, submission_id: str) -> str:
        return self._request("GET", f"/api/jobs/{submission_id}/logs")["logs"]

    def stop_job(self, submission_id: str) -> bool:
        return self._request("POST", f"/api/jobs/{submission_id}/stop")["stopped"]

    def wait_until_finished(self, submission_id: str, timeout: float = 300.0, poll_s: float = 0.5) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            status = self.get_job_status(submission_id)
            if status in JobStatus.TERMINAL:
                return status
            time.sleep(poll_s)
        raise TimeoutError(f"job {submission_id} still {status} after {timeout}s")
