"""Model zoo: the flagship decoder-only transformer (training + KV-cache
inference), plus MLP / ResNet / ViT used by Train/Tune/RLlib tests.

The reference has no in-tree LLM zoo (its Train/RLlib models are torch
modules; SURVEY.md §5.7) — these are the TPU-native equivalents of what it
delegates to HF/DeepSpeed."""

from ray_tpu.models.transformer import (  # noqa: F401
    TransformerConfig,
    forward,
    forward_hidden,
    init_params,
    loss_fn,
    make_train_step,
    num_params,
    param_logical_axes,
)
from ray_tpu.models.generate import (  # noqa: F401
    decode_chunk,
    decode_step,
    generate,
    init_cache,
    prefill,
    prefill_chunked,
    speculative_generate,
)
