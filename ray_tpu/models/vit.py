"""ViT-B/16-class vision transformer (BASELINE #4: map_batches batch inference).

flax.linen; attention through ops/attention.flash_attention so the TPU path
uses the Pallas kernel.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from ray_tpu.ops.attention import flash_attention


class ViTAttention(nn.Module):
    num_heads: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        B, T, D = x.shape
        H = self.num_heads
        qkv = nn.Dense(3 * D, dtype=self.dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, D // H)
        k = k.reshape(B, T, H, D // H)
        v = v.reshape(B, T, H, D // H)
        o = flash_attention(q, k, v, causal=False)
        return nn.Dense(D, dtype=self.dtype, name="proj")(o.reshape(B, T, D))


class ViTBlock(nn.Module):
    num_heads: int
    mlp_dim: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        y = nn.LayerNorm(dtype=jnp.float32)(x)
        x = x + ViTAttention(self.num_heads, self.dtype)(y)
        y = nn.LayerNorm(dtype=jnp.float32)(x)
        y = nn.Dense(self.mlp_dim, dtype=self.dtype)(y)
        y = nn.gelu(y)
        return x + nn.Dense(x.shape[-1], dtype=self.dtype)(y)


class ViT(nn.Module):
    num_classes: int = 1000
    patch_size: int = 16
    hidden_dim: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, images):
        # images: [B, H, W, 3]
        x = nn.Conv(
            self.hidden_dim,
            (self.patch_size, self.patch_size),
            strides=(self.patch_size, self.patch_size),
            dtype=self.dtype,
            name="patch_embed",
        )(images)
        B, h, w, D = x.shape
        x = x.reshape(B, h * w, D)
        cls = self.param("cls", nn.initializers.zeros, (1, 1, D), jnp.float32)
        x = jnp.concatenate([jnp.broadcast_to(cls.astype(x.dtype), (B, 1, D)), x], axis=1)
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02), (1, h * w + 1, D), jnp.float32
        )
        x = x + pos.astype(x.dtype)
        for i in range(self.num_layers):
            x = ViTBlock(self.num_heads, self.mlp_dim, self.dtype, name=f"block_{i}")(x)
        x = nn.LayerNorm(dtype=jnp.float32)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x[:, 0])


def ViT_B16(num_classes: int = 1000, **kw):
    return ViT(num_classes=num_classes, **kw)


def ViT_Tiny(num_classes: int = 10, **kw):
    """Small variant for tests."""
    return ViT(
        num_classes=num_classes,
        hidden_dim=64,
        num_layers=2,
        num_heads=4,
        mlp_dim=128,
        patch_size=8,
        **kw,
    )
