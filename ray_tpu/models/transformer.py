"""Flagship model: decoder-only transformer (Llama-family architecture).

Pure-JAX with explicit parameter pytrees and per-leaf logical sharding axes —
the flagship for every parallelism strategy in parallel/ (dp/fsdp/tp/pp/sp/ep)
and the model behind __graft_entry__.py and bench.py.

TPU-first choices:
- layer parameters are *stacked* [L, ...] so the layer loop is a lax.scan
  (O(1) compile in depth) and pipeline parallelism is just sharding the stack
  over the ``pp`` axis (parallel/pipeline.py)
- attention runs the Pallas flash kernel on TPU (ops/attention.py), ring
  attention over the ``sp`` axis for long context (parallel/ring_attention.py)
- bf16 activations/params by default; f32 RMSNorm epsilon path and logits
- rotary embeddings, GQA (n_kv_heads <= n_heads), SwiGLU MLP, optional
  mixture-of-experts MLP (parallel/moe.py) sharded over ``ep``
- remat (jax.checkpoint) around each layer: trades FLOPs for HBM, the standard
  TPU fit knob.

(The reference has no in-tree model zoo for LLMs — its Train/RLlib models are
torch modules; SURVEY.md §2.3/§5.7. This module is the TPU-native equivalent
of what it delegates to HF/DeepSpeed.)
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 8
    d_ff: int = 1376  # ~8/3 * d_model rounded
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    # MoE: 0 = dense MLP; >0 = experts sharded over ep.
    num_experts: int = 0
    expert_capacity_factor: float = 1.25
    remat: bool = True
    tie_embeddings: bool = False
    # lax.scan unroll factor over the layer stack. 1 (default) compiles one
    # rolled loop body — smallest compile, required shape for pipeline
    # parallelism's per-stage scheduling. Full unroll (= n_layers) lets XLA
    # schedule ACROSS layer boundaries, overlapping one layer's epilogue
    # with the next's prologue: +12% train throughput on the single-chip
    # v5e bench (79.3k -> 88.7k tok/s). Unroll only without pp sharding.
    scan_unroll: int = 1
    # Mistral-style sliding-window causal attention (0 = full causal):
    # row i attends keys (i-sliding_window, i]. Rides the flash kernel's
    # k-block pruning in training and the decode position mask at
    # inference; not combinable with ring/Ulysses sequence parallelism.
    sliding_window: int = 0
    # Fuse the LM-head projection into a chunked cross-entropy
    # (ops/losses.fused_lm_loss) so the [B*T, V] f32 logits tensor never
    # hits HBM — loss_fn only; forward() still returns full logits for
    # generation/eval paths.
    fused_loss: bool = True

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def init_params(key, cfg: TransformerConfig) -> dict:
    ks = jax.random.split(key, 10)
    D, H, KV, Dh, F, L, V = (
        cfg.d_model,
        cfg.n_heads,
        cfg.n_kv_heads,
        cfg.head_dim,
        cfg.d_ff,
        cfg.n_layers,
        cfg.vocab_size,
    )
    dt = cfg.param_dtype
    s = D**-0.5

    def norm(k, shape, scale):
        return (jax.random.normal(k, shape) * scale).astype(dt)

    layers = {
        "attn_norm": jnp.ones((L, D), dt),
        "wq": norm(ks[0], (L, D, H * Dh), s),
        "wk": norm(ks[1], (L, D, KV * Dh), s),
        "wv": norm(ks[2], (L, D, KV * Dh), s),
        "wo": norm(ks[3], (L, H * Dh, D), s * (2 * L) ** -0.5),
        "mlp_norm": jnp.ones((L, D), dt),
    }
    if cfg.num_experts > 0:
        E = cfg.num_experts
        layers.update(
            {
                "gate": norm(ks[4], (L, D, E), s),
                "wi_e": norm(ks[5], (L, E, D, F), s),
                "wg_e": norm(ks[6], (L, E, D, F), s),
                "wo_e": norm(ks[7], (L, E, F, D), F**-0.5 * (2 * L) ** -0.5),
            }
        )
    else:
        layers.update(
            {
                "wi": norm(ks[5], (L, D, F), s),
                "wg": norm(ks[6], (L, D, F), s),
                "wo_mlp": norm(ks[7], (L, F, D), F**-0.5 * (2 * L) ** -0.5),
            }
        )
    params = {
        "embed": norm(ks[8], (V, D), 1.0),
        "layers": layers,
        "norm_f": jnp.ones((D,), dt),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = norm(ks[9], (D, V), s)
    return params


def param_logical_axes(cfg: TransformerConfig) -> dict:
    """Per-leaf logical axis names (mapped to mesh axes by
    parallel/mesh.logical_to_spec)."""
    layers = {
        "attn_norm": ("layers", None),
        "wq": ("layers", "embed", "heads"),
        "wk": ("layers", "embed", "kv"),
        "wv": ("layers", "embed", "kv"),
        "wo": ("layers", "heads", "embed"),
        "mlp_norm": ("layers", None),
    }
    if cfg.num_experts > 0:
        layers.update(
            {
                "gate": ("layers", "embed", None),
                "wi_e": ("layers", "expert", "embed", "mlp"),
                "wg_e": ("layers", "expert", "embed", "mlp"),
                "wo_e": ("layers", "expert", "mlp", "embed"),
            }
        )
    else:
        layers.update(
            {
                "wi": ("layers", "embed", "mlp"),
                "wg": ("layers", "embed", "mlp"),
                "wo_mlp": ("layers", "mlp", "embed"),
            }
        )
    axes = {
        "embed": ("vocab", "embed"),
        "layers": layers,
        "norm_f": (None,),
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def _rms_norm(x, weight, eps):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps)).astype(x.dtype) * weight.astype(x.dtype)


def _rope_tables(positions, Dh: int, theta):
    """cos/sin rotation tables [B, T, Dh/2] for the given positions. The
    training path computes these ONCE per step (forward_hidden) instead of
    per layer per projection — positions are layer-invariant, and 16 sin+cos
    sweeps per step over [B,T,Dh/2] is pure wasted VPU time."""
    freqs = theta ** (-jnp.arange(0, Dh // 2, dtype=jnp.float32) / (Dh // 2))
    angles = positions[:, :, None].astype(jnp.float32) * freqs[None, None, :]
    return jnp.cos(angles), jnp.sin(angles)


def _rope_apply(x, cos, sin):
    # x: [B, T, H, Dh]; cos/sin: [B, T, Dh/2]
    x1, x2 = jnp.split(x, 2, axis=-1)
    rx1 = x1 * cos[:, :, None, :] - x2 * sin[:, :, None, :]
    rx2 = x2 * cos[:, :, None, :] + x1 * sin[:, :, None, :]
    return jnp.concatenate([rx1, rx2], axis=-1).astype(x.dtype)


def _rope(x, positions, theta):
    # Convenience form (decode paths in models/generate.py use this).
    cos, sin = _rope_tables(positions, x.shape[-1], theta)
    return _rope_apply(x, cos, sin)


def _attention_block(lp, x, rope_cs, cfg: TransformerConfig, mesh, attn_impl: str):
    import os

    B, T, D = x.shape
    H, KV, Dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    h = _rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    if os.environ.get("RAY_TPU_FUSED_QKV", "0") == "1":
        # One [D, (H+2KV)·Dh] matmul instead of three: fewer MXU launches
        # at identical FLOPs (the weight concat is folded by XLA). A/B knob,
        # read at trace time.
        wqkv = jnp.concatenate(
            [lp["wq"], lp["wk"], lp["wv"]], axis=-1
        ).astype(h.dtype)
        qkv = h @ wqkv
        q = qkv[..., : H * Dh].reshape(B, T, H, Dh)
        k = qkv[..., H * Dh : (H + KV) * Dh].reshape(B, T, KV, Dh)
        v = qkv[..., (H + KV) * Dh :].reshape(B, T, KV, Dh)
    else:
        q = (h @ lp["wq"].astype(h.dtype)).reshape(B, T, H, Dh)
        k = (h @ lp["wk"].astype(h.dtype)).reshape(B, T, KV, Dh)
        v = (h @ lp["wv"].astype(h.dtype)).reshape(B, T, KV, Dh)
    if isinstance(rope_cs, tuple):
        cos, sin = rope_cs
    else:
        # A/B fallback (RAY_TPU_ROPE_PER_LAYER=1): rope_cs is the raw
        # positions array; recompute tables in-layer — measures whether
        # XLA's CSE already hoists them from the scan.
        cos, sin = _rope_tables(rope_cs, Dh, cfg.rope_theta)
    q = _rope_apply(q, cos, sin)
    k = _rope_apply(k, cos, sin)
    if KV != H:  # GQA: repeat kv heads
        rep = H // KV
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    from ray_tpu.ops.attention import flash_attention

    if attn_impl == "ring" and mesh is not None and mesh.shape.get("sp", 1) > 1:
        if cfg.sliding_window:
            raise NotImplementedError("sliding_window + ring attention not supported")
        from ray_tpu.parallel.ring_attention import ring_attention

        o = ring_attention(q, k, v, mesh, causal=True)
    elif attn_impl == "ulysses" and mesh is not None and mesh.shape.get("sp", 1) > 1:
        if cfg.sliding_window:
            raise NotImplementedError("sliding_window + Ulysses attention not supported")
        from ray_tpu.parallel.ulysses import ulysses_attention

        o = ulysses_attention(q, k, v, mesh, causal=True)
    else:
        o = flash_attention(q, k, v, causal=True, window=cfg.sliding_window)
    o = o.reshape(B, T, H * Dh)
    return x + o @ lp["wo"].astype(o.dtype)


def _moe_mlp(lp, h, capacity_factor: float):
    """The one MoE dispatch call both the training block and KV-cache decode
    share (they differ only in capacity: training drops over-capacity
    tokens as an efficiency trade, inference runs lossless)."""
    from ray_tpu.parallel.moe import moe_layer

    return moe_layer(
        {
            "gate": lp["gate"].astype(h.dtype),
            "wi": lp["wi_e"].astype(h.dtype),
            "wo": lp["wo_e"].astype(h.dtype),
        },
        h,
        capacity_factor=capacity_factor,
    )


def _mlp_block(lp, x, cfg: TransformerConfig):
    h = _rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    if cfg.num_experts > 0:
        out, aux = _moe_mlp(lp, h, cfg.expert_capacity_factor)
        # SwiGLU-ish gate path folded into experts (wg_e unused in moe path
        # to keep dispatch einsums lean; kept in params for parity).
        return x + out, aux
    gate = jax.nn.silu(h @ lp["wg"].astype(h.dtype))
    up = h @ lp["wi"].astype(h.dtype)
    return x + (gate * up) @ lp["wo_mlp"].astype(h.dtype), 0.0


def _layer(lp, x, rope_cs, cfg: TransformerConfig, mesh, attn_impl: str):
    x = _attention_block(lp, x, rope_cs, cfg, mesh, attn_impl)
    x, aux = _mlp_block(lp, x, cfg)
    return x, aux


def forward_hidden(
    params: dict,
    tokens,
    cfg: TransformerConfig,
    mesh=None,
    attn_impl: str = "auto",
):
    """tokens [B, T] int32 -> (final hidden [B, T, D], moe aux)."""
    B, T = tokens.shape
    x = params["embed"].astype(cfg.dtype)[tokens]
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))
    # Rope tables are layer-invariant: one sin+cos sweep per step, shared by
    # every layer's q and k (vs 2·n_layers recomputations inside the scan).
    import os

    if os.environ.get("RAY_TPU_ROPE_PER_LAYER", "0") == "1":
        rope_cs = positions  # recomputed per layer (A/B fallback)
    else:
        rope_cs = _rope_tables(positions, cfg.head_dim, cfg.rope_theta)

    layer_fn = partial(_layer, cfg=cfg, mesh=mesh, attn_impl=attn_impl)
    if cfg.remat:
        layer_fn = jax.checkpoint(layer_fn, static_argnums=())

    def scan_body(carry, lp):
        x, aux = carry
        x, a = layer_fn(lp, x, rope_cs)
        return (x, aux + a), None

    unroll = max(1, min(int(cfg.scan_unroll or 1), cfg.n_layers))
    (x, aux), _ = lax.scan(scan_body, (x, 0.0), params["layers"], unroll=unroll)
    return _rms_norm(x, params["norm_f"], cfg.norm_eps), aux


def _head(params):
    return params["lm_head"] if "lm_head" in params else params["embed"].T


def forward(
    params: dict,
    tokens,
    cfg: TransformerConfig,
    mesh=None,
    attn_impl: str = "auto",
):
    """tokens [B, T] int32 -> logits [B, T, V] (f32)."""
    x, aux = forward_hidden(params, tokens, cfg, mesh=mesh, attn_impl=attn_impl)
    logits = (x @ _head(params).astype(x.dtype)).astype(jnp.float32)
    return logits, aux


def loss_fn(params, batch, cfg: TransformerConfig, mesh=None, attn_impl: str = "auto"):
    """batch: {"tokens": [B, T+1]} next-token LM loss."""
    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    if cfg.fused_loss:
        from ray_tpu.ops.losses import fused_lm_loss

        x, aux = forward_hidden(params, inputs, cfg, mesh=mesh, attn_impl=attn_impl)
        return fused_lm_loss(x, _head(params), targets) + 0.01 * aux
    logits, aux = forward(params, inputs, cfg, mesh=mesh, attn_impl=attn_impl)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean() + 0.01 * aux


def make_train_step(cfg: TransformerConfig, optimizer, mesh=None, attn_impl: str = "auto", donate: bool = True):
    """Returns train_step(params, opt_state, batch) -> (params, opt_state, loss).

    Pure function — callers jit it with in/out shardings (see
    train/jax/ and __graft_entry__.py). Gradients are averaged over the batch;
    under a dp/fsdp-sharded batch pjit inserts the psum automatically.
    """

    def train_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch, cfg, mesh, attn_impl)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        import optax

        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step


def num_params(params) -> int:
    return sum(int(p.size) for p in jax.tree.leaves(params))
