"""KV-cache inference for the flagship transformer: prefill + decode + sample.

The serving-side counterpart of models/transformer.py's training path. The
reference delegates inference to external frameworks (its Serve examples
wrap HF pipelines; SURVEY.md §5.7) — this is the TPU-native equivalent:

- static shapes throughout: the cache is preallocated at ``max_len`` and
  masked by position, so one compiled prefill + one compiled decode step
  serve every request length (no per-length recompiles);
- the whole generation loop is a ``lax.scan`` under one jit — no
  host→device round trip per token (under a remote-TPU tunnel that RTT
  would dominate decode latency);
- prefill attends densely over the prompt rows only (MXU-bound, masked for
  causality + per-row padding; the unwritten generation region of the
  cache is never scored), decode attends one query row against the cache
  with a position mask (HBM-bandwidth-bound, as it should be) and GQA
  caches are read at KV width via grouped einsums — never repeated to H;
- bf16 cache, f32 logits/sampling; greedy, temperature, and top-k.

Layer math intentionally mirrors transformer._attention_block/_mlp_block on
the same param pytree — decode diverges (cache writes, single-row masking)
enough that sharing one function would tangle the training hot path. MoE
configs decode through the same parallel/moe.moe_layer dispatch the
training block uses (T=1: each row's token rides its top-1 expert's slot).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models.transformer import (
    TransformerConfig,
    _head,
    _rms_norm,
    _rope,
)


def init_cache(cfg: TransformerConfig, batch: int, max_len: int):
    """Preallocated KV cache: k/v of shape [L, B, max_len, KV, Dh] (bf16 on
    TPU — cache reads are the decode bandwidth bill)."""
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
    }


def _project_qkv(lp, x, positions, cfg):
    B, T, _ = x.shape
    H, KV, Dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    h = _rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = (h @ lp["wq"].astype(h.dtype)).reshape(B, T, H, Dh)
    k = (h @ lp["wk"].astype(h.dtype)).reshape(B, T, KV, Dh)
    v = (h @ lp["wv"].astype(h.dtype)).reshape(B, T, KV, Dh)
    return _rope(q, positions, cfg.rope_theta), _rope(k, positions, cfg.rope_theta), v


def _mlp(lp, x, cfg):
    h = _rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    if cfg.num_experts > 0:
        from ray_tpu.models.transformer import _moe_mlp

        # LOSSLESS dispatch at inference: capacity_factor=E gives every
        # token a slot (capacity == T), so routing is per-token and
        # independent of batch padding — ragged rows behave exactly like
        # solo rows, and prefill agrees with T=1 decode. Training's
        # capacity drops (expert_capacity_factor) are an efficiency
        # approximation that inference deliberately does not replicate.
        # Aux loss is meaningless at inference and discarded.
        out, _aux = _moe_mlp(lp, h, float(cfg.num_experts))
        return x + out
    gate = jax.nn.silu(h @ lp["wg"].astype(h.dtype))
    up = h @ lp["wi"].astype(h.dtype)
    return x + (gate * up) @ lp["wo_mlp"].astype(h.dtype)


def _cache_attention(q, ck, cv, pos_mask, cfg):
    """q: [B, T, H, Dh] against cache rows ck/cv: [B, S, KV, Dh], masked by
    pos_mask [B, T, S] (True = attend). GQA uses grouped einsums so K/V are
    READ at KV width — never physically repeated to H heads (the cache read
    is the decode bandwidth bill; repeating would multiply it by H/KV)."""
    B, T, H, Dh = q.shape
    KV = ck.shape[2]
    scale = cfg.head_dim ** -0.5
    if KV != H:
        rep = H // KV
        qg = q.reshape(B, T, KV, rep, Dh)
        s = jnp.einsum("bqgrd,bkgd->bgrqk", qg, ck, preferred_element_type=jnp.float32)
        s = jnp.where(pos_mask[:, None, None], s * scale, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bgrqk,bkgd->bqgrd", p.astype(cv.dtype), cv,
                       preferred_element_type=jnp.float32)
        return o.reshape(B, T, H, Dh).astype(q.dtype)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, ck, preferred_element_type=jnp.float32)
    s = jnp.where(pos_mask[:, None], s * scale, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(cv.dtype), cv,
                   preferred_element_type=jnp.float32)
    return o.astype(q.dtype)


def prefill(params, tokens, cache, cfg: TransformerConfig, prompt_lens=None):
    """Run the prompt through the model, filling cache[:, :, :T].

    tokens: [B, T] int32. ``prompt_lens`` [B] int32 enables RAGGED batches:
    each row's real prompt occupies tokens[b, :prompt_lens[b]] (padding at
    the end, any values) — padded key rows are masked out of attention and
    the returned logits come from each row's LAST REAL token. Shapes stay
    static, so one compile serves every length mix (the batched-serving
    shape). Returns (logits_last [B, V] f32, cache, next_pos [B] int32).
    """
    B, T = tokens.shape
    if prompt_lens is None:
        prompt_lens = jnp.full((B,), T, jnp.int32)
    else:
        # Empty rows are undefined (all-masked softmax -> NaN, gather at
        # -1); clamp to 1 so a stray len-0 row behaves as "prompt is
        # tokens[b, :1]" instead of silently poisoning the whole batch.
        prompt_lens = jnp.maximum(jnp.asarray(prompt_lens, jnp.int32), 1)
    x = params["embed"].astype(cfg.dtype)[tokens]
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))

    def body(x, layer):
        lp, ck_slot, cv_slot = layer
        q, k, v = _project_qkv(lp, x, positions, cfg)
        ck = lax.dynamic_update_slice_in_dim(ck_slot, k, 0, axis=1)  # [B,S,KV,Dh]
        cv = lax.dynamic_update_slice_in_dim(cv_slot, v, 0, axis=1)
        # Attend only over the prompt's T rows — the generation region of
        # the cache is not written yet; scoring it would waste S/T the
        # FLOPs/HBM. Causal within the prompt; per-row padding invisible.
        k_pos = jnp.arange(T, dtype=jnp.int32)
        mask = (
            (k_pos[None, None, :] <= positions[:, :, None])
            & (k_pos[None, None, :] < prompt_lens[:, None, None])
        )
        o = _cache_attention(q, ck[:, :T], cv[:, :T], mask, cfg)
        x = x + o.reshape(B, T, -1) @ lp["wo"].astype(o.dtype)
        x = _mlp(lp, x, cfg)
        return x, (ck, cv)

    x, (ks, vs) = lax.scan(body, x, (params["layers"], cache["k"], cache["v"]))
    x = _rms_norm(x, params["norm_f"], cfg.norm_eps)
    last = jnp.take_along_axis(x, (prompt_lens - 1)[:, None, None], axis=1)[:, 0]
    logits = (last @ _head(params).astype(x.dtype)).astype(jnp.float32)
    return logits, {"k": ks, "v": vs}, prompt_lens


def decode_step(params, token, cache, pos, cfg: TransformerConfig):
    """One token per row: token [B] int32 written at per-row position
    ``pos`` ([B] int32, or a scalar for aligned batches).

    Returns (logits [B, V] f32, updated cache)."""
    B = token.shape[0]
    pos = jnp.asarray(pos, jnp.int32)
    # Aligned batches (scalar pos) keep the single fused dynamic_update_slice
    # cache write; only genuinely ragged batches pay the per-row scatter.
    aligned = pos.ndim == 0
    pos_b = jnp.broadcast_to(pos, (B,))
    x = params["embed"].astype(cfg.dtype)[token][:, None, :]  # [B, 1, D]
    positions = pos_b[:, None]
    S = cache["k"].shape[2]

    def write_row(slot, kv, p):
        # slot [S, KV, Dh], kv [1, KV, Dh] at row position p
        return lax.dynamic_update_slice(slot, kv, (p, 0, 0))

    def body(x, layer):
        lp, ck_slot, cv_slot = layer
        q, k, v = _project_qkv(lp, x, positions, cfg)
        if aligned:
            ck = lax.dynamic_update_slice(ck_slot, k, (0, pos, 0, 0))
            cv = lax.dynamic_update_slice(cv_slot, v, (0, pos, 0, 0))
        else:
            ck = jax.vmap(write_row)(ck_slot, k, pos_b)
            cv = jax.vmap(write_row)(cv_slot, v, pos_b)
        k_pos = jnp.arange(S, dtype=jnp.int32)
        mask = k_pos[None, None, :] <= pos_b[:, None, None]
        o = _cache_attention(q, ck, cv, mask, cfg)
        x = x + o.reshape(B, 1, -1) @ lp["wo"].astype(o.dtype)
        x = _mlp(lp, x, cfg)
        return x, (ck, cv)

    x, (ks, vs) = lax.scan(body, x, (params["layers"], cache["k"], cache["v"]))
    x = _rms_norm(x, params["norm_f"], cfg.norm_eps)
    logits = (x[:, 0] @ _head(params).astype(x.dtype)).astype(jnp.float32)
    return logits, {"k": ks, "v": vs}


def _sample(logits, key, temperature: float, top_k: int):
    if temperature == 0.0:
        return logits.argmax(axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


@partial(jax.jit, static_argnames=("cfg", "max_new_tokens", "temperature", "top_k"))
def generate(
    params,
    prompt,
    cfg: TransformerConfig,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: int = 0,
    key=None,
    prompt_lens=None,
):
    """prompt [B, T] int32 -> generated [B, max_new_tokens] int32.

    One jit: prefill + a lax.scan of decode steps (no per-token host
    round trips). temperature=0 is greedy; top_k=0 disables truncation.
    ``prompt_lens`` [B] batches RAGGED prompts (rows padded at the end to
    T): row b continues from its real prompt tokens[b, :prompt_lens[b]].
    """
    if key is None:
        key = jax.random.PRNGKey(0)
    B, T = prompt.shape
    cache = init_cache(cfg, B, T + max_new_tokens)
    logits, cache, pos = prefill(params, prompt, cache, cfg, prompt_lens=prompt_lens)
    if prompt_lens is None:
        # Aligned batch: a SCALAR position keeps decode's cache write a
        # single fused dynamic_update_slice instead of a per-row scatter.
        pos = jnp.int32(T)

    def step(carry, k):
        logits, cache, pos = carry
        tok = _sample(logits, k, temperature, top_k)
        logits, cache = decode_step(params, tok, cache, pos, cfg)
        return (logits, cache, pos + 1), tok

    keys = jax.random.split(key, max_new_tokens)
    _, toks = lax.scan(step, (logits, cache, pos), keys)
    return toks.T  # [B, max_new_tokens]
