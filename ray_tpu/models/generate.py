"""KV-cache inference for the flagship transformer: prefill + decode + sample.

The serving-side counterpart of models/transformer.py's training path. The
reference delegates inference to external frameworks (its Serve examples
wrap HF pipelines; SURVEY.md §5.7) — this is the TPU-native equivalent:

- static shapes throughout: the cache is preallocated at ``max_len`` and
  masked by position, so one compiled prefill + one compiled decode step
  serve every request length (no per-length recompiles);
- the whole generation loop is a ``lax.scan`` under one jit — no
  host→device round trip per token (under a remote-TPU tunnel that RTT
  would dominate decode latency);
- prefill attends densely over the prompt rows only (MXU-bound, masked for
  causality + per-row padding; the unwritten generation region of the
  cache is never scored), decode attends one query row against the cache
  with a position mask (HBM-bandwidth-bound, as it should be) and GQA
  caches are read at KV width via grouped einsums — never repeated to H;
- bf16 cache, f32 logits/sampling; greedy, temperature, and top-k.

Layer math intentionally mirrors transformer._attention_block/_mlp_block on
the same param pytree — decode diverges (cache writes, single-row masking)
enough that sharing one function would tangle the training hot path. MoE
configs decode through the same parallel/moe.moe_layer dispatch the
training block uses (T=1: each row's token rides its top-1 expert's slot).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models.transformer import (
    TransformerConfig,
    _head,
    _rms_norm,
    _rope,
)


def init_cache(cfg: TransformerConfig, batch: int, max_len: int):
    """Preallocated KV cache: k/v of shape [L, B, max_len, KV, Dh] (bf16 on
    TPU — cache reads are the decode bandwidth bill)."""
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
    }


def _project_qkv(lp, x, positions, cfg):
    B, T, _ = x.shape
    H, KV, Dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    h = _rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = (h @ lp["wq"].astype(h.dtype)).reshape(B, T, H, Dh)
    k = (h @ lp["wk"].astype(h.dtype)).reshape(B, T, KV, Dh)
    v = (h @ lp["wv"].astype(h.dtype)).reshape(B, T, KV, Dh)
    return _rope(q, positions, cfg.rope_theta), _rope(k, positions, cfg.rope_theta), v


def _mlp(lp, x, cfg):
    h = _rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    if cfg.num_experts > 0:
        from ray_tpu.models.transformer import _moe_mlp

        # LOSSLESS dispatch at inference: capacity_factor=E gives every
        # token a slot (capacity == T), so routing is per-token and
        # independent of batch padding — ragged rows behave exactly like
        # solo rows, and prefill agrees with T=1 decode. Training's
        # capacity drops (expert_capacity_factor) are an efficiency
        # approximation that inference deliberately does not replicate.
        # Aux loss is meaningless at inference and discarded.
        out, _aux = _moe_mlp(lp, h, float(cfg.num_experts))
        return x + out
    gate = jax.nn.silu(h @ lp["wg"].astype(h.dtype))
    up = h @ lp["wi"].astype(h.dtype)
    return x + (gate * up) @ lp["wo_mlp"].astype(h.dtype)


def _cache_attention(q, ck, cv, pos_mask, cfg):
    """q: [B, T, H, Dh] against cache rows ck/cv: [B, S, KV, Dh], masked by
    pos_mask [B, T, S] (True = attend). GQA uses grouped einsums so K/V are
    READ at KV width — never physically repeated to H heads (the cache read
    is the decode bandwidth bill; repeating would multiply it by H/KV)."""
    B, T, H, Dh = q.shape
    KV = ck.shape[2]
    scale = cfg.head_dim ** -0.5
    if KV != H:
        rep = H // KV
        qg = q.reshape(B, T, KV, rep, Dh)
        s = jnp.einsum("bqgrd,bkgd->bgrqk", qg, ck, preferred_element_type=jnp.float32)
        s = jnp.where(pos_mask[:, None, None], s * scale, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bgrqk,bkgd->bqgrd", p.astype(cv.dtype), cv,
                       preferred_element_type=jnp.float32)
        return o.reshape(B, T, H, Dh).astype(q.dtype)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, ck, preferred_element_type=jnp.float32)
    s = jnp.where(pos_mask[:, None], s * scale, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(cv.dtype), cv,
                   preferred_element_type=jnp.float32)
    return o.astype(q.dtype)


def prefill(params, tokens, cache, cfg: TransformerConfig, prompt_lens=None):
    """Run the prompt through the model, filling cache[:, :, :T].

    tokens: [B, T] int32. ``prompt_lens`` [B] int32 enables RAGGED batches:
    each row's real prompt occupies tokens[b, :prompt_lens[b]] (padding at
    the end, any values) — padded key rows are masked out of attention and
    the returned logits come from each row's LAST REAL token. Shapes stay
    static, so one compile serves every length mix (the batched-serving
    shape). Returns (logits_last [B, V] f32, cache, next_pos [B] int32).
    """
    B, T = tokens.shape
    if prompt_lens is None:
        prompt_lens = jnp.full((B,), T, jnp.int32)
    else:
        # Empty rows are undefined (all-masked softmax -> NaN, gather at
        # -1); clamp to 1 so a stray len-0 row behaves as "prompt is
        # tokens[b, :1]" instead of silently poisoning the whole batch.
        prompt_lens = jnp.maximum(jnp.asarray(prompt_lens, jnp.int32), 1)
    x = params["embed"].astype(cfg.dtype)[tokens]
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))

    def body(x, layer):
        lp, ck_slot, cv_slot = layer
        q, k, v = _project_qkv(lp, x, positions, cfg)
        ck = lax.dynamic_update_slice_in_dim(ck_slot, k, 0, axis=1)  # [B,S,KV,Dh]
        cv = lax.dynamic_update_slice_in_dim(cv_slot, v, 0, axis=1)
        # Attend only over the prompt's T rows — the generation region of
        # the cache is not written yet; scoring it would waste S/T the
        # FLOPs/HBM. Causal within the prompt; per-row padding invisible.
        k_pos = jnp.arange(T, dtype=jnp.int32)
        mask = (
            (k_pos[None, None, :] <= positions[:, :, None])
            & (k_pos[None, None, :] < prompt_lens[:, None, None])
        )
        if cfg.sliding_window:
            mask &= positions[:, :, None] - k_pos[None, None, :] < cfg.sliding_window
        o = _cache_attention(q, ck[:, :T], cv[:, :T], mask, cfg)
        x = x + o.reshape(B, T, -1) @ lp["wo"].astype(o.dtype)
        x = _mlp(lp, x, cfg)
        return x, (ck, cv)

    x, (ks, vs) = lax.scan(body, x, (params["layers"], cache["k"], cache["v"]))
    x = _rms_norm(x, params["norm_f"], cfg.norm_eps)
    last = jnp.take_along_axis(x, (prompt_lens - 1)[:, None, None], axis=1)[:, 0]
    logits = (last @ _head(params).astype(x.dtype)).astype(jnp.float32)
    return logits, {"k": ks, "v": vs}, prompt_lens


def _decode_chunk_hidden(params, tokens, cache, pos, cfg: TransformerConfig):
    """decode_chunk without the head projection: returns the final normed
    hidden states [B, q, D] + cache. Callers that need logits for only a
    subset of rows (chunked prefill needs just the final one) project
    themselves instead of paying [B, q, V]."""
    B, q = tokens.shape
    pos = jnp.asarray(pos, jnp.int32)
    aligned = pos.ndim == 0
    pos_b = jnp.broadcast_to(pos, (B,))
    x = params["embed"].astype(cfg.dtype)[tokens]  # [B, q, D]
    offs = jnp.arange(q, dtype=jnp.int32)
    positions = pos_b[:, None] + offs[None, :]  # [B, q]
    S = cache["k"].shape[2]

    def write_rows(slot, kv, p):
        # slot [S, KV, Dh], kv [q, KV, Dh] at row position p
        return lax.dynamic_update_slice(slot, kv, (p, 0, 0))

    def body(x, layer):
        lp, ck_slot, cv_slot = layer
        qh, k, v = _project_qkv(lp, x, positions, cfg)
        if aligned:
            ck = lax.dynamic_update_slice(ck_slot, k, (0, pos, 0, 0))
            cv = lax.dynamic_update_slice(cv_slot, v, (0, pos, 0, 0))
        else:
            ck = jax.vmap(write_rows)(ck_slot, k, pos_b)
            cv = jax.vmap(write_rows)(cv_slot, v, pos_b)
        k_pos = jnp.arange(S, dtype=jnp.int32)
        # Causal against the cache: row j of the chunk sees positions
        # <= pos[b] + j (its own and everything before it).
        mask = k_pos[None, None, :] <= positions[:, :, None]
        if cfg.sliding_window:
            mask &= positions[:, :, None] - k_pos[None, None, :] < cfg.sliding_window
        o = _cache_attention(qh, ck, cv, mask, cfg)
        x = x + o.reshape(B, q, -1) @ lp["wo"].astype(o.dtype)
        x = _mlp(lp, x, cfg)
        return x, (ck, cv)

    x, (ks, vs) = lax.scan(body, x, (params["layers"], cache["k"], cache["v"]))
    return _rms_norm(x, params["norm_f"], cfg.norm_eps), {"k": ks, "v": vs}


def decode_chunk(params, tokens, cache, pos, cfg: TransformerConfig):
    """q tokens per row against the cache: tokens [B, q] int32 written at
    per-row positions pos[b]..pos[b]+q-1 (pos [B] int32 or scalar).

    Returns (logits [B, q, V] f32 — one next-token distribution per fed
    token — and the updated cache). The position mask makes any stale cache
    rows beyond pos invisible, so callers may freely re-write positions
    (speculative decoding rejects; chunked prefill) without a cache rewind.
    """
    x, cache = _decode_chunk_hidden(params, tokens, cache, pos, cfg)
    logits = (x @ _head(params).astype(x.dtype)).astype(jnp.float32)
    return logits, cache


def prefill_chunked(params, tokens, cache, cfg: TransformerConfig, chunk: int = 512):
    """Prefill long prompts in fixed-size chunks: peak attention-score
    memory is [B, H, chunk, S] instead of [B, H, T, T] — the bounded-memory
    path for long-context serving. Aligned (non-ragged) prompts only.

    Returns (logits_last [B, V], cache, next_pos [B]) like prefill().
    """
    B, T = tokens.shape
    if T % chunk:
        # Clean tiling keeps one compiled chunk shape; callers pad prompts
        # to a chunk multiple (the serving idiom) or use prefill().
        raise ValueError(f"prompt length {T} not divisible by chunk {chunk}")
    n = T // chunk
    tok_chunks = tokens.reshape(B, n, chunk).transpose(1, 0, 2)  # [n, B, chunk]

    def body(carry, tok):
        cache, pos = carry
        # Hidden states only: projecting every chunk row to [chunk, V]
        # logits would waste head FLOPs on a path whose point is bounding
        # memory — only the final row's logits are needed.
        x, cache = _decode_chunk_hidden(params, tok, cache, pos, cfg)
        return (cache, pos + chunk), x[:, -1]

    (cache, pos), last = lax.scan(body, (cache, jnp.int32(0)), tok_chunks)
    logits = (last[-1] @ _head(params).astype(last.dtype)).astype(jnp.float32)
    return logits, cache, jnp.full((B,), T, jnp.int32)


def decode_step(params, token, cache, pos, cfg: TransformerConfig):
    """One token per row: token [B] int32 written at per-row position
    ``pos`` ([B] int32, or a scalar for aligned batches). The q=1 case of
    decode_chunk. Returns (logits [B, V] f32, updated cache)."""
    logits, cache = decode_chunk(params, token[:, None], cache, pos, cfg)
    return logits[:, 0], cache


def init_paged_cache(cfg: TransformerConfig, num_blocks: int, block_size: int):
    """Block-pool KV cache for continuous-batching serving: k/v of shape
    [L, num_blocks, block_size, KV, Dh]. Physical block 0 is RESERVED as the
    null block — allocators must never hand it out. Inactive decode slots and
    write-masked prefill padding rows are routed there, so the compiled step
    never needs a dynamic shape or a conditional write."""
    shape = (cfg.n_layers, num_blocks, block_size, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
    }


def _paged_decode_chunk_hidden(
    params,
    tokens,
    cache,
    block_tables,
    pos,
    cfg: TransformerConfig,
    valid_to=None,
):
    """``paged_decode_chunk`` without the head projection: returns the final
    normed hidden states [B, q, D] + cache. Chunked prefill consumes logits
    for at most ONE row per prompt — callers project that row themselves
    instead of paying [B, q, V] (the `_decode_chunk_hidden` pattern)."""
    B, q = tokens.shape
    n_max = block_tables.shape[1]
    block_size = cache["k"].shape[2]
    S = n_max * block_size
    pos = jnp.asarray(pos, jnp.int32)
    pos_b = jnp.broadcast_to(pos, (B,))
    block_tables = jnp.asarray(block_tables, jnp.int32)
    x = params["embed"].astype(cfg.dtype)[tokens]  # [B, q, D]
    offs = jnp.arange(q, dtype=jnp.int32)
    positions = pos_b[:, None] + offs[None, :]  # [B, q]
    # Physical write coordinates for every fed row (computed once, reused
    # per layer). Out-of-table positions clamp to the last entry; engines
    # validate lengths so this only guards compiler-visible bounds.
    blk_idx = jnp.minimum(positions // block_size, n_max - 1)
    blk_phys = jnp.take_along_axis(block_tables, blk_idx, axis=1)  # [B, q]
    row_off = positions % block_size
    if valid_to is not None:
        writable = positions < jnp.asarray(valid_to, jnp.int32)[:, None]
        blk_phys = jnp.where(writable, blk_phys, 0)

    def body(x, layer):
        lp, ck_slot, cv_slot = layer  # [N, Bs, KV, Dh]
        qh, k, v = _project_qkv(lp, x, positions, cfg)
        ck = ck_slot.at[blk_phys, row_off].set(k)
        cv = cv_slot.at[blk_phys, row_off].set(v)
        # Gather each row's logical cache view through its block table,
        # then attend exactly like the dense path. Masked (p == 0) entries
        # contribute nothing, so null-block garbage stays invisible.
        ck_g = ck[block_tables].reshape(B, S, cfg.n_kv_heads, cfg.head_dim)
        cv_g = cv[block_tables].reshape(B, S, cfg.n_kv_heads, cfg.head_dim)
        k_pos = jnp.arange(S, dtype=jnp.int32)
        mask = k_pos[None, None, :] <= positions[:, :, None]
        if cfg.sliding_window:
            mask &= positions[:, :, None] - k_pos[None, None, :] < cfg.sliding_window
        o = _cache_attention(qh, ck_g, cv_g, mask, cfg)
        x = x + o.reshape(B, q, -1) @ lp["wo"].astype(o.dtype)
        x = _mlp(lp, x, cfg)
        return x, (ck, cv)

    x, (ks, vs) = lax.scan(body, x, (params["layers"], cache["k"], cache["v"]))
    return _rms_norm(x, params["norm_f"], cfg.norm_eps), {"k": ks, "v": vs}


def paged_decode_chunk(
    params,
    tokens,
    cache,
    block_tables,
    pos,
    cfg: TransformerConfig,
    valid_to=None,
):
    """``decode_chunk`` over a PAGED cache: tokens [B, q] written at per-row
    positions pos[b]..pos[b]+q-1, where logical position p of row b lives in
    physical block ``block_tables[b, p // block_size]`` at row offset
    ``p % block_size``.

    - ``block_tables`` [B, n_max] int32: per-sequence physical block ids in
      logical order; entries beyond the sequence's allocation are 0 (the
      null block) and stay invisible behind the position mask. Shapes are
      STATIC — one compile serves every schedule the engine can produce
      (any mix of sequences, fragmentation, or mid-stream admissions).
    - ``valid_to`` [B] int32 (optional): rows at positions >= valid_to[b]
      have their K/V writes routed to the null block (used by chunked
      prefill so a padded final chunk never touches unallocated blocks).
      Their logits are garbage and must be ignored by the caller.
    - An INACTIVE slot is (token 0, pos 0, all-zero block table): it writes
      and attends only null-block row 0 — finite garbage, never NaN (an
      all-masked softmax would poison MoE dispatch for the whole batch).

    Returns (logits [B, q, V] f32, updated cache). Attention math is the
    dense ``_cache_attention`` over the GATHERED logical view, so outputs
    match the dense-cache path row for row (the serving oracle).
    """
    x, cache = _paged_decode_chunk_hidden(
        params, tokens, cache, block_tables, pos, cfg, valid_to=valid_to
    )
    logits = (x @ _head(params).astype(x.dtype)).astype(jnp.float32)
    return logits, cache


def paged_decode_step(params, token, cache, block_tables, pos, cfg: TransformerConfig):
    """One token per slot against the paged cache: token [B] int32 at
    per-slot positions ``pos`` [B]. The q=1 case of ``paged_decode_chunk``
    — the continuous-batching decode hot loop. Returns (logits [B, V] f32,
    updated cache)."""
    logits, cache = paged_decode_chunk(
        params, token[:, None], cache, block_tables, pos, cfg
    )
    return logits[:, 0], cache


def _sample(logits, key, temperature: float, top_k: int):
    if temperature == 0.0:
        return logits.argmax(axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


@partial(jax.jit, static_argnames=("cfg", "max_new_tokens", "temperature", "top_k"))
def generate(
    params,
    prompt,
    cfg: TransformerConfig,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: int = 0,
    key=None,
    prompt_lens=None,
):
    """prompt [B, T] int32 -> generated [B, max_new_tokens] int32.

    One jit: prefill + a lax.scan of decode steps (no per-token host
    round trips). temperature=0 is greedy; top_k=0 disables truncation.
    ``prompt_lens`` [B] batches RAGGED prompts (rows padded at the end to
    T): row b continues from its real prompt tokens[b, :prompt_lens[b]].
    """
    if key is None:
        key = jax.random.PRNGKey(0)
    B, T = prompt.shape
    cache = init_cache(cfg, B, T + max_new_tokens)
    logits, cache, pos = prefill(params, prompt, cache, cfg, prompt_lens=prompt_lens)
    if prompt_lens is None:
        # Aligned batch: a SCALAR position keeps decode's cache write a
        # single fused dynamic_update_slice instead of a per-row scatter.
        pos = jnp.int32(T)

    def step(carry, k):
        logits, cache, pos = carry
        tok = _sample(logits, k, temperature, top_k)
        logits, cache = decode_step(params, tok, cache, pos, cfg)
        return (logits, cache, pos + 1), tok

    keys = jax.random.split(key, max_new_tokens)
    _, toks = lax.scan(step, (logits, cache, pos), keys)
    return toks.T  # [B, max_new_tokens]


def _processed_probs(logits, temperature: float, top_p: float):
    """Temperature + nucleus(top-p) processed distribution [..., V] (f32).
    Spec-decode exactness is defined W.R.T. this processed distribution —
    the same processing applies to target and draft."""
    logits = logits.astype(jnp.float32) / max(temperature, 1e-6)
    probs = jax.nn.softmax(logits, axis=-1)
    if top_p < 1.0:
        sorted_probs = jnp.flip(jnp.sort(probs, axis=-1), axis=-1)
        cum = jnp.cumsum(sorted_probs, axis=-1)
        # keep the smallest prefix with mass >= top_p (ties at the cutoff
        # prob all kept — standard nucleus caveat)
        n_keep = jnp.sum(cum - sorted_probs < top_p, axis=-1)
        cutoff = jnp.take_along_axis(
            sorted_probs, jnp.maximum(n_keep - 1, 0)[..., None], axis=-1
        )
        probs = jnp.where(probs >= cutoff, probs, 0.0)
        probs = probs / jnp.maximum(probs.sum(-1, keepdims=True), 1e-30)
    return probs


@partial(
    jax.jit,
    static_argnames=("cfg", "draft_cfg", "max_new_tokens", "k", "temperature", "top_p"),
)
def speculative_generate(
    params,
    draft_params,
    prompt,
    cfg: TransformerConfig,
    draft_cfg: TransformerConfig,
    max_new_tokens: int = 32,
    k: int = 4,
    temperature: float = 0.0,
    top_p: float = 1.0,
    key=None,
):
    """Speculative decoding: a small draft model proposes ``k`` tokens per
    round from its own cache; the target verifies all of them in ONE
    ``decode_chunk`` and commits the accepted prefix plus one more token
    (1..k+1 tokens per target pass).

    ``temperature == 0`` is greedy-exact: output is EXACTLY
    ``generate(params, prompt, cfg, temperature=0.0)`` — a draft token is
    accepted iff it equals the target argmax at that position.

    ``temperature > 0`` is sampling-exact IN DISTRIBUTION via the standard
    accept-reject scheme (Leviathan et al. 2023; Chen et al. 2023): the
    draft SAMPLES x_i ~ q_i, the target accepts with prob
    min(1, p_i(x_i)/q_i(x_i)), and the first rejection resamples from the
    leftover distribution norm(max(p_i - q_i, 0)); a fully-accepted round
    samples its bonus token from p_{k+1}. Each emitted token is marginally
    distributed exactly as temperature/top-p sampling from the target.
    Both models must share the vocab. No cache rewind on rejection: stale
    rows past the committed position are invisible to the position mask and
    simply overwritten next round.

    Returns (tokens [B, max_new_tokens] int32, rounds int32 — target
    passes spent; rounds << max_new_tokens when the draft agrees often).
    """
    sampling = temperature > 0.0
    if key is None:
        key = jax.random.PRNGKey(0)
    B, T = prompt.shape
    S = T + max_new_tokens + k + 1
    t_cache = init_cache(cfg, B, S)
    d_cache = init_cache(draft_cfg, B, S)
    t_logits, t_cache, pos = prefill(params, prompt, t_cache, cfg)
    _, d_cache, _ = prefill(draft_params, prompt, d_cache, draft_cfg)
    # The two caches are position-locked: one pos drives both (they commit
    # the identical token sequence every round).
    key, k0 = jax.random.split(key)
    if sampling:
        p0 = _processed_probs(t_logits, temperature, top_p)
        cur = jax.random.categorical(k0, jnp.log(p0 + 1e-30), axis=-1).astype(jnp.int32)
    else:
        cur = t_logits.argmax(axis=-1).astype(jnp.int32)  # first emitted token

    out = jnp.zeros((B, max_new_tokens), jnp.int32)
    out = out.at[:, 0].set(cur)
    n = jnp.ones((B,), jnp.int32)  # tokens emitted so far

    def draft_propose(d_cache, cur, d_pos, kd):
        # k+1 steps so the draft cache holds rows for cur AND all k
        # proposals (including d_k): a fully-accepted round advances by
        # k+1 rows, and every one of them must be written. The (k+1)-th
        # prediction is discarded.
        def body(carry, kk):
            cache, tok, pos = carry
            logits, cache = decode_step(draft_params, tok, cache, pos, draft_cfg)
            if sampling:
                q = _processed_probs(logits, temperature, top_p)
                nxt = jax.random.categorical(kk, jnp.log(q + 1e-30), axis=-1)
                nxt = nxt.astype(jnp.int32)
            else:
                q = jnp.zeros((B, logits.shape[-1]), jnp.float32)
                nxt = logits.argmax(axis=-1).astype(jnp.int32)
            return (cache, nxt, pos + 1), (nxt, q)

        (d_cache, _, d_pos), (drafts, qs) = lax.scan(
            body, (d_cache, cur, d_pos), jax.random.split(kd, k + 1)
        )
        # proposals [B, k]; their processed draft distributions [B, k, V]
        return d_cache, drafts.T[:, :k], qs.transpose(1, 0, 2)[:, :k], d_pos

    def round_body(state):
        out, n, cur, pos, t_cache, d_cache, rounds, key = state
        key, kd, ka, kb = jax.random.split(key, 4)
        d_cache, drafts, qs, _ = draft_propose(d_cache, cur, pos, kd)
        fed = jnp.concatenate([cur[:, None], drafts], axis=1)  # [B, k+1]
        logits, t_cache = decode_chunk(params, fed, t_cache, pos, cfg)
        if sampling:
            ps = _processed_probs(logits, temperature, top_p)  # [B, k+1, V]
            p_at = jnp.take_along_axis(ps[:, :k], drafts[..., None], axis=-1)[..., 0]
            q_at = jnp.take_along_axis(qs, drafts[..., None], axis=-1)[..., 0]
            u = jax.random.uniform(ka, (B, k))
            # accept x_i iff u < p(x_i)/q(x_i)  (u*q < p is div-by-zero safe)
            accept = u * q_at < p_at
            accepted = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)
            # Rejection at position r = accepted: resample from the leftover
            # norm(max(p_r - q_r, 0)); full acceptance: sample from p_k.
            p_r = jnp.take_along_axis(
                ps, accepted[:, None, None], axis=1
            )[:, 0]  # [B, V]
            q_r = jnp.take_along_axis(
                qs, jnp.minimum(accepted, k - 1)[:, None, None], axis=1
            )[:, 0]
            q_r = jnp.where((accepted < k)[:, None], q_r, 0.0)
            resid = jnp.maximum(p_r - q_r, 0.0)
            z = resid.sum(-1, keepdims=True)
            # Degenerate residual (p <= q everywhere, numerically) -> p_r.
            resid = jnp.where(z > 1e-30, resid / jnp.maximum(z, 1e-30), p_r)
            bonus = jax.random.categorical(
                kb, jnp.log(resid + 1e-30), axis=-1
            ).astype(jnp.int32)
        else:
            preds = logits.argmax(axis=-1).astype(jnp.int32)  # [B, k+1]
            # accepted[b] = longest prefix of drafts matching target argmax.
            match = drafts == preds[:, :k]  # [B, k]
            accepted = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
            bonus = jnp.take_along_axis(preds, accepted[:, None], axis=1)[:, 0]
        # Emit d1..d_accepted then the bonus token at the divergence (or
        # after all k when fully accepted): k+1 candidate slots.
        emit = jnp.where(
            jnp.arange(k + 1)[None, :] < accepted[:, None],
            jnp.concatenate([drafts, jnp.zeros((B, 1), jnp.int32)], axis=1),
            0,
        )
        emit = emit.at[jnp.arange(B), accepted].set(bonus)  # slot `accepted`
        n_emit_raw = accepted + 1
        room = jnp.maximum(max_new_tokens - n, 0)
        n_emit = jnp.minimum(n_emit_raw, room)
        # Scatter emit[:, :n_emit] into out at per-row offset n.
        for i in range(k + 1):  # static k: unrolled masked writes
            idx = jnp.clip(n + i, 0, max_new_tokens - 1)
            valid = i < n_emit
            prev = out[jnp.arange(B), idx]
            out = out.at[jnp.arange(B), idx].set(
                jnp.where(valid, emit[:, i], prev)
            )
        # Advance: committed rows are cur + accepted drafts. Rows already
        # at capacity advance nothing (their writes were masked anyway).
        adv = jnp.where(room > 0, accepted + 1, 0)
        new_cur = jnp.where(
            n_emit > 0,
            jnp.take_along_axis(emit, jnp.maximum(n_emit - 1, 0)[:, None], axis=1)[:, 0],
            cur,
        )
        return (out, n + n_emit, new_cur, pos + adv, t_cache, d_cache, rounds + 1, key)

    def round_cond(state):
        _, n, *_rest = state
        return jnp.any(n < max_new_tokens)

    state = (out, n, cur, pos, t_cache, d_cache, jnp.int32(0), key)
    out, n, *_r, rounds, _key = lax.while_loop(round_cond, round_body, state)
    return out, rounds
