"""KV-cache inference for the flagship transformer: prefill + decode + sample.

The serving-side counterpart of models/transformer.py's training path. The
reference delegates inference to external frameworks (its Serve examples
wrap HF pipelines; SURVEY.md §5.7) — this is the TPU-native equivalent:

- static shapes throughout: the cache is preallocated at ``max_len`` and
  masked by position, so one compiled prefill + one compiled decode step
  serve every request length (no per-length recompiles);
- the whole generation loop is a ``lax.scan`` under one jit — no
  host→device round trip per token (under a remote-TPU tunnel that RTT
  would dominate decode latency);
- prefill reuses the Pallas flash kernel over the prompt (MXU-bound),
  decode attends one query row against the cache with a position mask
  (HBM-bandwidth-bound, as it should be);
- bf16 cache, f32 logits/sampling; greedy, temperature, and top-k.

Layer math intentionally mirrors transformer._attention_block/_mlp_block on
the same param pytree — decode diverges (cache writes, single-row masking)
enough that sharing one function would tangle the training hot path.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models.transformer import (
    TransformerConfig,
    _head,
    _rms_norm,
    _rope,
)


def init_cache(cfg: TransformerConfig, batch: int, max_len: int):
    """Preallocated KV cache: k/v of shape [L, B, max_len, KV, Dh] (bf16 on
    TPU — cache reads are the decode bandwidth bill)."""
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
    }


def _project_qkv(lp, x, positions, cfg):
    B, T, _ = x.shape
    H, KV, Dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    h = _rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = (h @ lp["wq"].astype(h.dtype)).reshape(B, T, H, Dh)
    k = (h @ lp["wk"].astype(h.dtype)).reshape(B, T, KV, Dh)
    v = (h @ lp["wv"].astype(h.dtype)).reshape(B, T, KV, Dh)
    return _rope(q, positions, cfg.rope_theta), _rope(k, positions, cfg.rope_theta), v


def _mlp(lp, x, cfg):
    h = _rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    gate = jax.nn.silu(h @ lp["wg"].astype(h.dtype))
    up = h @ lp["wi"].astype(h.dtype)
    return x + (gate * up) @ lp["wo_mlp"].astype(h.dtype)


def _cache_attention(q, ck, cv, pos_mask, cfg):
    """q: [B, T, H, Dh] against the full cache ck/cv: [B, S, KV, Dh], rows
    masked by pos_mask [B, T, S] (True = attend)."""
    H, KV = cfg.n_heads, cfg.n_kv_heads
    if KV != H:
        rep = H // KV
        ck = jnp.repeat(ck, rep, axis=2)
        cv = jnp.repeat(cv, rep, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, ck, preferred_element_type=jnp.float32)
    s = s * (cfg.head_dim ** -0.5)
    s = jnp.where(pos_mask[:, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(cv.dtype), cv,
                   preferred_element_type=jnp.float32)
    return o.astype(q.dtype)


def prefill(params, tokens, cache, cfg: TransformerConfig):
    """Run the prompt through the model, filling cache[:, :, :T].

    tokens: [B, T] int32 (the full prompt; pad+mask externally for ragged
    batches). Returns (logits_last [B, V] f32, cache, next_pos=T).
    """
    B, T = tokens.shape
    S = cache["k"].shape[2]
    x = params["embed"].astype(cfg.dtype)[tokens]
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))

    def body(x, layer):
        lp, ck_slot, cv_slot = layer
        q, k, v = _project_qkv(lp, x, positions, cfg)
        ck = lax.dynamic_update_slice_in_dim(ck_slot, k, 0, axis=1)  # [B,S,KV,Dh]
        cv = lax.dynamic_update_slice_in_dim(cv_slot, v, 0, axis=1)
        # Causal over the prompt; nothing beyond T is visible.
        k_pos = jnp.arange(S, dtype=jnp.int32)
        mask = (k_pos[None, None, :] <= positions[:, :, None]) & (k_pos[None, None, :] < T)
        o = _cache_attention(q, ck, cv, mask, cfg)
        x = x + o.reshape(B, T, -1) @ lp["wo"].astype(o.dtype)
        x = _mlp(lp, x, cfg)
        return x, (ck, cv)

    x, (ks, vs) = lax.scan(body, x, (params["layers"], cache["k"], cache["v"]))
    x = _rms_norm(x, params["norm_f"], cfg.norm_eps)
    logits = (x[:, -1] @ _head(params).astype(x.dtype)).astype(jnp.float32)
    return logits, {"k": ks, "v": vs}, jnp.int32(T)


def decode_step(params, token, cache, pos, cfg: TransformerConfig):
    """One token: token [B] int32 at position pos (scalar int32).

    Returns (logits [B, V] f32, updated cache)."""
    B = token.shape[0]
    x = params["embed"].astype(cfg.dtype)[token][:, None, :]  # [B, 1, D]
    positions = jnp.full((B, 1), pos, jnp.int32)
    S = cache["k"].shape[2]

    def body(x, layer):
        lp, ck_slot, cv_slot = layer
        q, k, v = _project_qkv(lp, x, positions, cfg)
        ck = lax.dynamic_update_slice(ck_slot, k, (0, pos, 0, 0))
        cv = lax.dynamic_update_slice(cv_slot, v, (0, pos, 0, 0))
        k_pos = jnp.arange(S, dtype=jnp.int32)
        mask = jnp.broadcast_to(k_pos[None, None, :] <= pos, (B, 1, S))
        o = _cache_attention(q, ck, cv, mask, cfg)
        x = x + o.reshape(B, 1, -1) @ lp["wo"].astype(o.dtype)
        x = _mlp(lp, x, cfg)
        return x, (ck, cv)

    x, (ks, vs) = lax.scan(body, x, (params["layers"], cache["k"], cache["v"]))
    x = _rms_norm(x, params["norm_f"], cfg.norm_eps)
    logits = (x[:, 0] @ _head(params).astype(x.dtype)).astype(jnp.float32)
    return logits, {"k": ks, "v": vs}


def _sample(logits, key, temperature: float, top_k: int):
    if temperature == 0.0:
        return logits.argmax(axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


@partial(jax.jit, static_argnames=("cfg", "max_new_tokens", "temperature", "top_k"))
def generate(
    params,
    prompt,
    cfg: TransformerConfig,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: int = 0,
    key=None,
):
    """prompt [B, T] int32 -> generated [B, max_new_tokens] int32.

    One jit: prefill + a lax.scan of decode steps (no per-token host
    round trips). temperature=0 is greedy; top_k=0 disables truncation.
    """
    if cfg.num_experts > 0:
        raise NotImplementedError(
            "KV-cache decode supports dense MLP configs; MoE decode needs "
            "expert dispatch in the step function (train-side MoE lives in "
            "parallel/moe.py)."
        )
    if key is None:
        key = jax.random.PRNGKey(0)
    B, T = prompt.shape
    cache = init_cache(cfg, B, T + max_new_tokens)
    logits, cache, pos = prefill(params, prompt, cache, cfg)

    def step(carry, k):
        logits, cache, pos = carry
        tok = _sample(logits, k, temperature, top_k)
        logits, cache = decode_step(params, tok, cache, pos, cfg)
        return (logits, cache, pos + 1), tok

    keys = jax.random.split(key, max_new_tokens)
    _, toks = lax.scan(step, (logits, cache, pos), keys)
    return toks.T  # [B, max_new_tokens]
