"""MNIST-class MLP (BASELINE config #1: JaxTrainer MNIST MLP minimum slice)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def init_mlp(key, sizes=(784, 256, 128, 10), dtype=jnp.float32) -> dict:
    params = {}
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        k1, key = jax.random.split(key)
        params[f"w{i}"] = (jax.random.normal(k1, (fan_in, fan_out)) * fan_in**-0.5).astype(dtype)
        params[f"b{i}"] = jnp.zeros((fan_out,), dtype)
    return params


def mlp_forward(params: dict, x):
    n = len(params) // 2
    for i in range(n):
        x = x @ params[f"w{i}"] + params[f"b{i}"]
        if i < n - 1:
            x = jax.nn.relu(x)
    return x


def mlp_loss(params, batch):
    logits = mlp_forward(params, batch["x"])
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(logp, batch["y"][:, None], axis=-1).mean()
    acc = (logits.argmax(-1) == batch["y"]).mean()
    return nll, acc
